// Native parameter-server shard: multi-threaded TCP tensor server with a
// C ABI for ctypes.
//
// Role: the Python PS (tf_operator_tpu/train/ps.py) serializes every
// pull/push through pickle and the GIL; under many workers the shard
// becomes host-bound.  This server holds the shard in flat float32 buffers,
// speaks a length-prefixed binary tensor protocol, and applies downpour-SGD
// updates on C++ threads — Python only hosts the process.  (The reference
// has no native code of its own — its PS data path is TF's gRPC runtime
// inside user containers, SURVEY.md §2.9; this is the framework-owned
// equivalent.)
//
// Build: g++ -O3 -shared -fPIC -o libtpujob_ps.so ps_server.cpp -lpthread
//
// Wire protocol (little-endian), shared with train/native_ps.py:
//   request  frame: u8 op | u64 payload_len | payload
//   ops: 1=PULL (no payload)
//        2=PUSH (payload = tensor list)
//        3=SHUTDOWN (no payload)
//   tensor list: u32 count, then per tensor:
//        u16 name_len | name bytes | u64 elem_count | f32 elems
//   responses:
//        PULL     -> u64 version | tensor list
//        PUSH     -> u64 version (after applying)
//        SHUTDOWN -> u64 0

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint8_t kOpPull = 1;
constexpr uint8_t kOpPush = 2;
constexpr uint8_t kOpShutdown = 3;

bool SendAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool RecvAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void AppendU16(std::vector<char>* out, uint16_t v) {
  out->insert(out->end(), reinterpret_cast<char*>(&v),
              reinterpret_cast<char*>(&v) + sizeof(v));
}

void AppendU32(std::vector<char>* out, uint32_t v) {
  out->insert(out->end(), reinterpret_cast<char*>(&v),
              reinterpret_cast<char*>(&v) + sizeof(v));
}

void AppendU64(std::vector<char>* out, uint64_t v) {
  out->insert(out->end(), reinterpret_cast<char*>(&v),
              reinterpret_cast<char*>(&v) + sizeof(v));
}

class PsServer {
 public:
  PsServer(const std::string& host, int port, float lr)
      : host_(host), port_(port), lr_(lr) {}

  ~PsServer() { Stop(); }

  int AddParam(const std::string& name, const float* data, uint64_t n) {
    std::lock_guard<std::mutex> lock(mu_);
    params_[name].assign(data, data + n);
    return 0;
  }

  int GetParam(const std::string& name, float* out, uint64_t n) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = params_.find(name);
    if (it == params_.end() || it->second.size() != n) return -1;
    std::memcpy(out, it->second.data(), n * sizeof(float));
    return 0;
  }

  uint64_t Version() {
    std::lock_guard<std::mutex> lock(mu_);
    return version_;
  }

  int Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    addr.sin_addr.s_addr =
        host_.empty() ? INADDR_ANY : ::inet_addr(host_.c_str());
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return -1;
    }
    socklen_t len = sizeof(addr);
    ::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    if (::listen(listen_fd_, 64) < 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
      return -1;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return 0;
  }

  int Port() const { return port_; }

  void Wait() {
    std::unique_lock<std::mutex> lock(shutdown_mu_);
    shutdown_cv_.wait(lock, [this] { return shutdown_.load(); });
  }

  void Stop() {
    bool expected = false;
    if (stopping_.compare_exchange_strong(expected, true)) {
      shutdown_.store(true);
      shutdown_cv_.notify_all();
      if (listen_fd_ >= 0) {
        ::shutdown(listen_fd_, SHUT_RDWR);
        ::close(listen_fd_);
        listen_fd_ = -1;
      }
      if (accept_thread_.joinable()) accept_thread_.join();
      // Unblock Serve threads parked in recv() on idle client connections —
      // they only re-check shutdown_ between frames, so joining without
      // shutting their sockets down would hang here while any client keeps
      // its connection open.  Join outside the lock: exiting Serve threads
      // take workers_mu_ in ForgetConn.
      std::vector<std::thread> threads;
      {
        std::lock_guard<std::mutex> lock(workers_mu_);
        for (int fd : conn_fds_) ::shutdown(fd, SHUT_RDWR);
        threads.swap(workers_);
      }
      for (auto& t : threads) {
        if (t.joinable()) t.join();
      }
    }
  }

 private:
  void AcceptLoop() {
    while (!shutdown_.load()) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        if (shutdown_.load()) return;
        continue;
      }
      std::lock_guard<std::mutex> lock(workers_mu_);
      ReapFinishedLocked();
      conn_fds_.push_back(fd);
      workers_.emplace_back([this, fd] {
        // A throwing handler (bad_alloc on a corrupt frame, ...) must drop
        // one connection, not std::terminate the whole shard process.
        try {
          Serve(fd);
        } catch (...) {
          ForgetConn(fd);
          ::close(fd);
        }
        std::lock_guard<std::mutex> lock(workers_mu_);
        finished_ids_.push_back(std::this_thread::get_id());
      });
    }
  }

  // Join Serve threads that have announced completion, so a long-lived shard
  // handling many short connections doesn't accumulate dead std::threads.
  // Caller holds workers_mu_; join() only blocks for the instants between a
  // thread pushing its id and returning.
  void ReapFinishedLocked() {
    for (auto id : finished_ids_) {
      for (auto it = workers_.begin(); it != workers_.end(); ++it) {
        if (it->get_id() == id) {
          it->join();
          workers_.erase(it);
          break;
        }
      }
    }
    finished_ids_.clear();
  }

  void ForgetConn(int fd) {
    std::lock_guard<std::mutex> lock(workers_mu_);
    for (auto it = conn_fds_.begin(); it != conn_fds_.end(); ++it) {
      if (*it == fd) {
        conn_fds_.erase(it);
        return;
      }
    }
  }

  // Largest frame a well-formed client can need (shard sizes are model
  // parameters, far below this); anything bigger is a corrupt or hostile
  // frame and drops the connection instead of attempting the allocation.
  static constexpr uint64_t kMaxPayload = 1ull << 31;  // 2 GiB

  void Serve(int fd) {
    while (!shutdown_.load()) {
      uint8_t op = 0;
      uint64_t payload_len = 0;
      if (!RecvAll(fd, &op, 1) || !RecvAll(fd, &payload_len, 8)) break;
      if (payload_len > kMaxPayload) break;
      std::vector<char> payload(payload_len);
      if (payload_len > 0 && !RecvAll(fd, payload.data(), payload_len)) break;
      if (op == kOpPull) {
        std::vector<char> resp;
        {
          std::lock_guard<std::mutex> lock(mu_);
          AppendU64(&resp, version_);
          AppendU32(&resp, static_cast<uint32_t>(params_.size()));
          for (const auto& kv : params_) {
            AppendU16(&resp, static_cast<uint16_t>(kv.first.size()));
            resp.insert(resp.end(), kv.first.begin(), kv.first.end());
            AppendU64(&resp, kv.second.size());
            const char* d = reinterpret_cast<const char*>(kv.second.data());
            resp.insert(resp.end(), d, d + kv.second.size() * sizeof(float));
          }
        }
        if (!SendAll(fd, resp.data(), resp.size())) break;
      } else if (op == kOpPush) {
        uint64_t version = ApplyPush(payload);
        if (!SendAll(fd, &version, 8)) break;
      } else if (op == kOpShutdown) {
        uint64_t zero = 0;
        SendAll(fd, &zero, 8);
        shutdown_.store(true);
        shutdown_cv_.notify_all();
        break;
      } else {
        break;  // unknown op: drop the connection
      }
    }
    ForgetConn(fd);
    ::close(fd);
  }

  // payload: u32 count | per tensor u16 nlen | name | u64 elems | f32 data.
  // Malformed frames are ignored past the point of damage (version still
  // bumps for the tensors applied before it).
  uint64_t ApplyPush(const std::vector<char>& payload) {
    size_t off = 0;
    auto fits = [&](size_t n) { return off + n <= payload.size(); };
    if (!fits(4)) return Version();
    uint32_t count;
    std::memcpy(&count, payload.data() + off, 4);
    off += 4;
    std::lock_guard<std::mutex> lock(mu_);
    for (uint32_t i = 0; i < count; ++i) {
      if (!fits(2)) break;
      uint16_t nlen;
      std::memcpy(&nlen, payload.data() + off, 2);
      off += 2;
      if (!fits(nlen)) break;
      std::string name(payload.data() + off, nlen);
      off += nlen;
      if (!fits(8)) break;
      uint64_t elems;
      std::memcpy(&elems, payload.data() + off, 8);
      off += 8;
      // Divide, don't multiply: elems >= 2^62 would wrap elems * 4 past the
      // bounds check and desynchronize the parse offset.
      if (elems > (payload.size() - off) / sizeof(float)) break;
      const float* grad = reinterpret_cast<const float*>(payload.data() + off);
      off += elems * sizeof(float);
      auto it = params_.find(name);
      if (it == params_.end() || it->second.size() != elems) continue;
      float* p = it->second.data();
      const float lr = lr_;
      for (uint64_t j = 0; j < elems; ++j) p[j] -= lr * grad[j];
    }
    ++version_;
    return version_;
  }

  std::string host_;
  int port_;
  float lr_;
  int listen_fd_ = -1;
  std::thread accept_thread_;
  std::mutex workers_mu_;
  std::vector<std::thread> workers_;
  std::vector<std::thread::id> finished_ids_;
  std::vector<int> conn_fds_;
  std::mutex mu_;
  std::map<std::string, std::vector<float>> params_;
  uint64_t version_ = 0;
  std::atomic<bool> shutdown_{false};
  std::atomic<bool> stopping_{false};
  std::mutex shutdown_mu_;
  std::condition_variable shutdown_cv_;
};

}  // namespace

extern "C" {

void* tpujob_ps_create(const char* host, int port, float lr) {
  return new PsServer(host ? host : "", port, lr);
}

int tpujob_ps_add_param(void* h, const char* name, const float* data,
                        uint64_t n) {
  return static_cast<PsServer*>(h)->AddParam(name, data, n);
}

int tpujob_ps_get_param(void* h, const char* name, float* out, uint64_t n) {
  return static_cast<PsServer*>(h)->GetParam(name, out, n);
}

int tpujob_ps_start(void* h) { return static_cast<PsServer*>(h)->Start(); }

int tpujob_ps_port(void* h) { return static_cast<PsServer*>(h)->Port(); }

uint64_t tpujob_ps_version(void* h) {
  return static_cast<PsServer*>(h)->Version();
}

void tpujob_ps_wait(void* h) { static_cast<PsServer*>(h)->Wait(); }

void tpujob_ps_stop(void* h) { static_cast<PsServer*>(h)->Stop(); }

void tpujob_ps_destroy(void* h) { delete static_cast<PsServer*>(h); }

}  // extern "C"
