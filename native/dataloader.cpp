// Native data pipeline: multi-threaded synthetic batch generation with a
// bounded prefetch queue, exposed through a C ABI for ctypes.
//
// Role: the host-side input pipeline must stay ahead of the TPU step clock
// or HBM sits idle (the classic input-bound regime).  Python/numpy
// generation is single-threaded and GIL-bound; this loader generates and
// stages batches on C++ threads so Python only memcpy's a ready buffer.
// (The reference has no native code of its own — its data path lives in
// user containers; this is the framework-owned equivalent.)
//
// Build: g++ -O3 -shared -fPIC -o libtpujob_data.so dataloader.cpp -lpthread
//
// Generators mirror tf_operator_tpu/train/data.py semantics (learnable
// class-conditional patterns; exact values need not match Python).

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

constexpr int kKindImages = 0;
constexpr int kKindMnist = 1;
constexpr int kKindTokens = 2;

struct Batch {
  std::vector<float> x;
  std::vector<int32_t> y;
};

class Loader {
 public:
  Loader(int kind, int batch, int dim1, int dim2, int num_classes,
         uint32_t seed, int prefetch_depth, int num_threads)
      : kind_(kind),
        batch_(batch),
        dim1_(dim1),
        dim2_(dim2),
        num_classes_(num_classes),
        seed_(seed),
        depth_(prefetch_depth > 0 ? prefetch_depth : 4),
        stop_(false),
        produced_(0) {
    const int threads = num_threads > 0 ? num_threads : 2;
    for (int t = 0; t < threads; ++t) {
      workers_.emplace_back([this, t] { WorkerLoop(t); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      stop_ = true;
    }
    not_full_.notify_all();
    not_empty_.notify_all();
    for (auto& w : workers_) w.join();
  }

  size_t x_size() const {
    switch (kind_) {
      case kKindImages:
        return static_cast<size_t>(batch_) * dim1_ * dim1_ * 3;
      case kKindMnist:
        return static_cast<size_t>(batch_) * 784;
      case kKindTokens:
      default:
        return static_cast<size_t>(batch_) * dim1_;
    }
  }

  // Blocks until a batch is ready; copies into caller buffers.
  int Next(float* x_out, int32_t* y_out) {
    std::unique_lock<std::mutex> lock(mu_);
    not_empty_.wait(lock, [this] { return stop_ || !queue_.empty(); });
    if (queue_.empty()) return -1;  // stopped
    Batch batch = std::move(queue_.front());
    queue_.pop_front();
    lock.unlock();
    not_full_.notify_one();
    std::memcpy(x_out, batch.x.data(), batch.x.size() * sizeof(float));
    if (y_out != nullptr && !batch.y.empty()) {
      std::memcpy(y_out, batch.y.data(), batch.y.size() * sizeof(int32_t));
    }
    return 0;
  }

 private:
  void WorkerLoop(int worker_id) {
    std::mt19937 rng(seed_ + 0x9e3779b9u * (worker_id + 1));
    while (true) {
      Batch batch = Generate(rng);
      std::unique_lock<std::mutex> lock(mu_);
      not_full_.wait(lock, [this] {
        return stop_ || queue_.size() < static_cast<size_t>(depth_);
      });
      if (stop_) return;
      queue_.push_back(std::move(batch));
      ++produced_;
      lock.unlock();
      not_empty_.notify_one();
    }
  }

  // Fast uniform noise in [-s, s]: xorshift32 mapped to float.  The Python
  // generators use gaussian noise; uniform is equally learnable and ~50x
  // cheaper than std::normal_distribution, which otherwise dominates.
  static inline float FastNoise(uint32_t& state, float scale) {
    state ^= state << 13;
    state ^= state >> 17;
    state ^= state << 5;
    return (static_cast<float>(state) * (1.0f / 4294967296.0f) - 0.5f) *
           (2.0f * scale);
  }

  Batch Generate(std::mt19937& rng) {
    Batch batch;
    std::uniform_int_distribution<int> label_dist(0, num_classes_ - 1);
    uint32_t noise_state = rng() | 1u;
    if (kind_ == kKindImages) {
      batch.x.resize(x_size());
      batch.y.resize(batch_);
      const int hw = dim1_;
      for (int b = 0; b < batch_; ++b) {
        const int label = label_dist(rng);
        batch.y[b] = label;
        const float freq = static_cast<float>(label % 13 + 1);
        float* img = batch.x.data() + static_cast<size_t>(b) * hw * hw * 3;
        for (int row = 0; row < hw; ++row) {
          const float base =
              std::sin(2.0f * static_cast<float>(M_PI) * row / hw * freq);
          float* row_ptr = img + static_cast<size_t>(row) * hw * 3;
          for (int i = 0; i < hw * 3; ++i) {
            row_ptr[i] = base + FastNoise(noise_state, 0.75f);
          }
        }
      }
    } else if (kind_ == kKindMnist) {
      batch.x.resize(x_size());
      batch.y.resize(batch_);
      for (int b = 0; b < batch_; ++b) {
        const int label = label_dist(rng);
        batch.y[b] = label;
        float* img = batch.x.data() + static_cast<size_t>(b) * 784;
        for (int row = 0; row < 28; ++row) {
          for (int col = 0; col < 28; ++col) {
            img[row * 28 + col] =
                std::sin(col * (label + 1) * 0.35f + row * (9 - label) * 0.15f) +
                FastNoise(noise_state, 0.45f);
          }
        }
      }
    } else {  // tokens: markov-ish bigram stream, x holds float(token id)
      batch.x.resize(x_size());
      std::uniform_int_distribution<int> tok_dist(0, num_classes_ - 1);
      std::uniform_real_distribution<float> unit(0.0f, 1.0f);
      for (int b = 0; b < batch_; ++b) {
        int tok = tok_dist(rng);
        float* row = batch.x.data() + static_cast<size_t>(b) * dim1_;
        row[0] = static_cast<float>(tok);
        for (int t = 1; t < dim1_; ++t) {
          tok = unit(rng) < 0.1f ? tok_dist(rng)
                                 : static_cast<int>((tok * 31 + 7) % num_classes_);
          row[t] = static_cast<float>(tok);
        }
      }
    }
    return batch;
  }

  const int kind_;
  const int batch_;
  const int dim1_;
  const int dim2_;
  const int num_classes_;
  const uint32_t seed_;
  const int depth_;

  std::mutex mu_;
  std::condition_variable not_empty_;
  std::condition_variable not_full_;
  std::deque<Batch> queue_;
  bool stop_;
  std::atomic<int64_t> produced_;
  std::vector<std::thread> workers_;
};

}  // namespace

extern "C" {

void* dl_create(int kind, int batch, int dim1, int dim2, int num_classes,
                uint32_t seed, int prefetch_depth, int num_threads) {
  return new Loader(kind, batch, dim1, dim2, num_classes, seed, prefetch_depth,
                    num_threads);
}

int dl_next(void* handle, float* x_out, int32_t* y_out) {
  return static_cast<Loader*>(handle)->Next(x_out, y_out);
}

int64_t dl_x_size(void* handle) {
  return static_cast<int64_t>(static_cast<Loader*>(handle)->x_size());
}

void dl_destroy(void* handle) { delete static_cast<Loader*>(handle); }

}  // extern "C"
