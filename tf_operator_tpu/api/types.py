"""TPUJob resource types.

TPU-native re-design of the reference's API layer:
  - TFJob / TFJobSpec           ref: pkg/apis/tensorflow/v1/types.go:27-68
  - replica types               ref: types.go:73-92
  - shared job types            ref: vendor/github.com/kubeflow/common/pkg/apis/common/v1/types.go:24-201
  - SuccessPolicy               ref: pkg/apis/tensorflow/v1/common.go:17-23

New over the reference: a first-class TPU topology block on each replica spec
(accelerator type + slice topology + logical mesh), because on TPUs the
scheduling unit is the slice, not the individual device.
"""
from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

from .core import ObjectMeta, PodTemplateSpec

# ---------------------------------------------------------------------------
# Slice topology math — schema-level (the spec strings "4x8"/"2x2x2" are part
# of the API), shared by validation, defaults, and the runtime slice
# allocator (runtime/slices.py re-exports these).

# A host of a TPU pod slice carries 4 chips (v4: 2x2x1 per host; v5e/v5p:
# 4 chips/host).  Topologies with <=4 chips fit on one host.
CHIPS_PER_HOST = 4


def parse_topology(topology: str) -> tuple:
    """'4x8' -> (4, 8); '2x2x2' -> (2, 2, 2).  Raises ValueError on junk."""
    try:
        dims = tuple(int(d) for d in topology.lower().split("x"))
    except ValueError:
        raise ValueError(f"malformed slice topology {topology!r}")
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"malformed slice topology {topology!r}")
    return dims


def topology_chips(topology: str) -> int:
    chips = 1
    for d in parse_topology(topology):
        chips *= d
    return chips


def topology_hosts(topology: str) -> int:
    """Hosts (= worker processes) a slice of this shape spans."""
    return max(1, -(-topology_chips(topology) // CHIPS_PER_HOST))


class ReplicaType(str, Enum):
    """Replica roles (ref: pkg/apis/tensorflow/v1/types.go:73-92).

    PS/Chief/Master/Worker/Evaluator are kept for drop-in parity; on the TPU
    path Worker pods are TPU-slice hosts and Chief doubles as the JAX
    distributed coordinator.
    """

    PS = "PS"
    WORKER = "Worker"
    CHIEF = "Chief"
    MASTER = "Master"
    EVALUATOR = "Evaluator"


# Fixed iteration order for status computation: the reference iterates
# Chief, Evaluator, Master, PS, Worker (ref: status.go:88-94 — Go map
# iteration is randomized so the reference sorts; order matters because the
# chief rule must win before the worker rule runs).
REPLICA_TYPE_ORDER = [
    ReplicaType.CHIEF,
    ReplicaType.EVALUATOR,
    ReplicaType.MASTER,
    ReplicaType.PS,
    ReplicaType.WORKER,
]


class RestartPolicy(str, Enum):
    """(ref: vendor/.../apis/common/v1/types.go:94-106)"""

    ALWAYS = "Always"
    ON_FAILURE = "OnFailure"
    NEVER = "Never"
    # Restart decision made from the container exit code by the controller
    # (retryable codes → delete+recreate the pod; ref: types.go:103-105 and
    # util/train/train_util.go:18-53).
    EXIT_CODE = "ExitCode"


class CleanPodPolicy(str, Enum):
    """What to do with pods when the job reaches a terminal state
    (ref: vendor/.../apis/common/v1/types.go:137-146)."""

    ALL = "All"
    RUNNING = "Running"
    NONE = "None"


class SuccessPolicy(str, Enum):
    """(ref: pkg/apis/tensorflow/v1/common.go:17-23)"""

    DEFAULT = ""  # chief (if present) or worker-0 completion marks success
    ALL_WORKERS = "AllWorkers"


class JobConditionType(str, Enum):
    """(ref: vendor/.../apis/common/v1/types.go:107-133)"""

    CREATED = "Created"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    # No reference analogue: set (status True) when the controller
    # quarantines a job after repeated consecutive sync failures, flipped
    # False on the first successful sync (docs/self-healing.md).
    STUCK = "Stuck"
    # No reference analogue: an elastic job whose virtual→physical mapping
    # is changing (preemption shrink, repair grow, or spec resize).  The
    # gang is drained and re-emitted at the new physical width; flipped
    # False (RunningResized) once the resized gang is running
    # (docs/elasticity.md).
    RESIZING = "Resizing"
    # No reference analogue: the gang scheduler evicted this job's gang to
    # make room for a higher-priority gang (docs/scheduling-policy.md).
    # The drained job re-enters the policy queue at its own priority with
    # its backoff budget untouched; flipped False (RunningAfterPreemption)
    # once the gang runs again.
    PREEMPTED = "Preempted"


@dataclass
class JobCondition:
    """(ref: vendor/.../apis/common/v1/types.go:45-63)"""

    type: JobConditionType
    status: bool  # k8s ConditionStatus True/False collapsed to a bool
    reason: str = ""
    message: str = ""
    last_update_time: float = field(default_factory=time.time)
    last_transition_time: float = field(default_factory=time.time)


@dataclass
class ReplicaStatus:
    """(ref: vendor/.../apis/common/v1/types.go:65-77)"""

    active: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class JobStatus:
    """(ref: vendor/.../apis/common/v1/types.go:24-43)"""

    conditions: List[JobCondition] = field(default_factory=list)
    replica_statuses: Dict[str, ReplicaStatus] = field(default_factory=dict)
    start_time: Optional[float] = None
    completion_time: Optional[float] = None
    last_reconcile_time: Optional[float] = None
    # Strategy-level ZeRO weight-update sharding document (see
    # zero_sharding_plan_doc) stamped by the reconciler when the spec knob
    # is on — the searchable layout record the AMP planner (ROADMAP item 3)
    # reads back.  None when the knob is off.
    zero_sharding_plan: Optional[Dict[str, object]] = None
    # Elastic virtual→physical mapping document (see elastic_status_doc),
    # stamped by the reconciler for jobs with an elastic policy: current
    # resize generation, per-group virtual/physical widths, and the bounded
    # resize history.  None for non-elastic jobs.
    elastic: Optional[Dict[str, object]] = None


@dataclass
class SchedulingPolicy:
    """Gang-scheduling knobs (ref: vendor/.../apis/common/v1/types.go:148-154).

    min_available defaults to the total replica count — on TPUs a training
    gang below full slice membership cannot make progress.
    """

    min_available: Optional[int] = None
    queue: str = ""


# Ordered priority-class table for spec.scheduling.priorityClass, lowest
# first.  Strict priority: the gang scheduler never admits a class while a
# feasible higher class waits, and preemption never evicts a gang at or
# above the preemptor's class (docs/scheduling-policy.md).  Validation
# rejects names outside this table so a typo cannot silently land a
# production job in the wrong band.
PRIORITY_CLASSES = ("low", "batch", "standard", "high", "critical")
DEFAULT_PRIORITY_CLASS = "standard"
DEFAULT_TENANT = "default"


def priority_rank(priority_class: str) -> int:
    """Rank of a class in the ordered table (higher = more urgent).
    Unknown/empty names rank as the default class — annotations written by
    an older controller must not crash admission."""
    try:
        return PRIORITY_CLASSES.index(priority_class)
    except ValueError:
        return PRIORITY_CLASSES.index(DEFAULT_PRIORITY_CLASS)


@dataclass
class SchedulingSpec:
    """Multi-tenant scheduling policy block (spec.scheduling).

    No reference analogue: the reference delegates arbitration to Volcano
    queues.  Here the in-process gang scheduler arbitrates — strict
    priority across classes, weighted fair share (dominant chip share)
    across tenants within a class, FIFO within a tenant
    (docs/scheduling-policy.md).
    """

    # Name from PRIORITY_CLASSES; validation rejects anything else.
    priority_class: str = DEFAULT_PRIORITY_CLASS
    # Fair-share accounting bucket within a class (a team/user id).
    tenant: str = DEFAULT_TENANT
    # Consent to graceful eviction: only preemptible gangs are ever chosen
    # as victims when a higher class cannot fit.
    preemptible: bool = False


@dataclass
class RunPolicy:
    """Job-level lifecycle policy (ref: vendor/.../apis/common/v1/types.go:156-201)."""

    clean_pod_policy: Optional[CleanPodPolicy] = None
    ttl_seconds_after_finished: Optional[int] = None
    active_deadline_seconds: Optional[float] = None
    backoff_limit: Optional[int] = None
    scheduling_policy: Optional[SchedulingPolicy] = None


@dataclass
class TPUTopology:
    """TPU-native addition: what fabric this replica group runs on.

    The reference expresses accelerators only as an opaque resource request in
    the pod template (nvidia.com/gpu); TPU slices need structure — the slice
    shape determines gang size, and the logical mesh determines how the
    training runtime lays out dp/tp/sp axes over ICI.
    """

    accelerator: str = ""  # e.g. "v5litepod-8"
    topology: str = ""  # physical chip topology, e.g. "2x4"
    # Logical mesh requested for the workload, axis name -> size,
    # e.g. {"dp": 2, "tp": 4}.  Injected as TPUJOB_MESH_SHAPE.
    mesh: Dict[str, int] = field(default_factory=dict)
    # ZeRO-style cross-replica sharding of optimizer state + weight update
    # over the mesh's data-parallel axis (train/zero.py, arXiv:2004.13336).
    # Injected as TPUJOB_ZERO_SHARD_WEIGHT_UPDATE; the reconciler mirrors
    # the chosen strategy into status.zero_sharding_plan.
    zero_shard_weight_update: bool = False
    # Declared per-device memory budget in GiB (0 = undeclared).  With
    # model_params also declared, the reconciler rejects the job at
    # admission when even the analytic lower bound of the training
    # footprint — params + grads + optimizer moments under the declared
    # sharding, the model analysis/hlo.py cross-checks against compiled
    # HLO — cannot fit (reason MemoryInfeasible, docs/roofline.md).
    device_memory_gb: float = 0.0
    # Declared trainable-parameter count of the workload (0 = undeclared).
    # The control plane never sees the param tree, so feasibility needs
    # the submitter to state the model size; lying just moves the failure
    # back to OOM time.
    model_params: int = 0

    def num_chips(self) -> int:
        return topology_chips(self.topology) if self.topology else 0


@dataclass
class ElasticPolicy:
    """Elastic virtual-replica policy (VirtualFlow, arXiv:2009.09523).

    `replicas` on the owning ReplicaSpec becomes the *virtual* replica
    count V — the fixed logical width the workload is written against.
    The controller maps those V virtual replicas onto P physical replicas
    (pods / slice hosts), P ∈ [min_replicas, max_replicas], shrinking on
    slice preemption and re-growing on repair instead of failing the job.
    Virtual replica j runs on physical replica j % P; gradient
    accumulation keeps the global batch semantics identical across P
    (docs/elasticity.md).
    """

    min_replicas: Optional[int] = None  # floor; below it the gang waits
    max_replicas: Optional[int] = None  # ceiling; defaults to V


@dataclass
class ReplicaSpec:
    """(ref: vendor/.../apis/common/v1/types.go:79-92)"""

    replicas: Optional[int] = None
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    restart_policy: Optional[RestartPolicy] = None
    tpu: Optional[TPUTopology] = None
    # When set the group is elastic: `replicas` counts virtual replicas,
    # the physical pod count floats within the policy's bounds.
    elastic: Optional[ElasticPolicy] = None


@dataclass
class TPUJobSpec:
    """(ref: pkg/apis/tensorflow/v1/types.go:47-68)"""

    replica_specs: Dict[ReplicaType, ReplicaSpec] = field(default_factory=dict)
    run_policy: RunPolicy = field(default_factory=RunPolicy)
    success_policy: Optional[SuccessPolicy] = None
    # Each worker sees a sparse cluster spec (itself + all PS) and workers may
    # be scaled without restarting the job (ref: types.go:61-67).
    enable_dynamic_worker: bool = False
    # Multi-tenant arbitration knobs; None means the default class/tenant,
    # not preemptible (identical to a pre-policy job).
    scheduling: Optional[SchedulingSpec] = None


@dataclass
class TPUJob:
    """The TPUJob resource (ref: pkg/apis/tensorflow/v1/types.go:27-44)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: TPUJobSpec = field(default_factory=TPUJobSpec)
    status: JobStatus = field(default_factory=JobStatus)

    # constant discriminator: job_to_dict emits constants.KIND and
    # job_from_dict never restores it — not a round-tripped field
    kind: str = "TPUJob"  # contract: exempt(wire-roundtrip)

    def deepcopy(self) -> "TPUJob":
        return copy.deepcopy(self)

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


# --- type helpers (ref: pkg/apis/tensorflow/v1/util.go:22-34) ---

def is_chief_or_master(rtype: ReplicaType) -> bool:
    return rtype in (ReplicaType.CHIEF, ReplicaType.MASTER)


def is_worker(rtype: ReplicaType) -> bool:
    return rtype == ReplicaType.WORKER


def is_evaluator(rtype: ReplicaType) -> bool:
    return rtype == ReplicaType.EVALUATOR


def contains_chief_or_master(job: TPUJob) -> bool:
    """(ref: pkg/controller.v1/tensorflow/util.go:45-52)"""
    return any(is_chief_or_master(rt) for rt in job.spec.replica_specs)


def is_elastic(job: TPUJob) -> bool:
    """True when any replica group carries an elastic policy."""
    return any(rs.elastic is not None for rs in job.spec.replica_specs.values())


def elastic_bounds(rspec: ReplicaSpec) -> tuple:
    """(min, max, virtual) physical-width bounds for an elastic group.

    Virtual width V is rspec.replicas; min defaults to 1, max to V.  Only
    meaningful when rspec.elastic is set (callers gate on that).
    """
    virtual = int(rspec.replicas or 1)
    pol = rspec.elastic
    lo = int(pol.min_replicas) if pol and pol.min_replicas is not None else 1
    hi = int(pol.max_replicas) if pol and pol.max_replicas is not None else virtual
    return lo, hi, virtual


def effective_replicas(job: TPUJob, rtype: ReplicaType) -> int:
    """Physical replica count the controller should run for `rtype` right
    now: the resize-doc width for elastic groups (status.elastic, stamped
    by the reconciler), else the spec width.  Non-elastic groups always use
    the spec width — the doc never overrides them."""
    rspec = job.spec.replica_specs.get(rtype)
    if rspec is None:
        return 0
    spec_width = int(rspec.replicas or 1)
    if rspec.elastic is None:
        return spec_width
    lo, hi, _ = elastic_bounds(rspec)
    doc = job.status.elastic or {}
    group = (doc.get("groups") or {}).get(rtype.value) or {}
    physical = group.get("physical")
    if physical is None:
        return min(spec_width, hi)
    # Clamp against the *current* spec bounds so a spec resize immediately
    # narrows a stale doc width.
    return max(lo, min(int(physical), hi))


def effective_total_replicas(job: TPUJob) -> int:
    """Physical pod count across all groups (the elastic-aware analogue of
    defaults.total_replicas, which counts spec/virtual widths)."""
    return sum(effective_replicas(job, rt) for rt in job.spec.replica_specs)


def elastic_status_doc(job: TPUJob) -> Optional[Dict[str, object]]:
    """The virtual→physical mapping document stamped into status.elastic
    for elastic jobs, or None when no group is elastic.

    Carries the current resize generation, per-group widths, and the
    virtual→physical assignment (virtual j → physical j % P) so operators
    and the resume path can read the live mapping without re-deriving it.
    The resize `history` list is appended by the reconciler on each
    transition and preserved here.
    """
    if not is_elastic(job):
        return None
    prior = job.status.elastic or {}
    groups: Dict[str, object] = {}
    for rtype in REPLICA_TYPE_ORDER:
        rspec = job.spec.replica_specs.get(rtype)
        if rspec is None or rspec.elastic is None:
            continue
        lo, hi, virtual = elastic_bounds(rspec)
        physical = effective_replicas(job, rtype)
        groups[rtype.value] = {
            "virtual": virtual,
            "physical": physical,
            "min": lo,
            "max": hi,
            "assignment": {
                str(j): j % physical for j in range(virtual)
            } if physical > 0 else {},
        }
    return {
        "generation": int(prior.get("generation") or 0),
        "groups": groups,
        "history": list(prior.get("history") or []),
    }


def zero_sharding_plan_doc(spec: TPUJobSpec) -> Optional[Dict[str, object]]:
    """The strategy-level ZeRO weight-update sharding document for a spec,
    or None when no replica group asks for it.

    This is the controller-side half of the plan: which replica group, which
    mesh axis, how many shards.  The per-param half (shard dims) is chosen
    by the training runtime (train/zero.py) from the live param tree, which
    the control plane never sees; the AMP planner (ROADMAP item 3) searches
    over exactly the fields recorded here.  The doc must stay truthful to
    what the runtime will actually do: an explicit mesh whose dp axis is
    absent or 1 runs dense (workloads/lm.py announces and skips), so no doc
    is emitted for it.  Without an explicit mesh the runtime defaults all
    devices onto dp (mesh_from_env); numShards is then the slice chip count
    when a topology is declared, else None (sharding active, width unknown
    to the control plane).
    """
    for rtype in REPLICA_TYPE_ORDER:
        rspec = spec.replica_specs.get(rtype)
        if rspec is None or rspec.tpu is None:
            continue
        if not rspec.tpu.zero_shard_weight_update:
            continue
        mesh = rspec.tpu.mesh
        num_shards: Optional[int] = None
        if mesh:
            num_shards = int(mesh.get("dp", 1))
            if num_shards <= 1:
                continue  # runtime runs dense on this mesh: no plan
        elif rspec.tpu.topology:
            num_shards = rspec.tpu.num_chips() or None
            if num_shards is not None and num_shards <= 1:
                continue
        return {
            "axis": "dp",
            "numShards": num_shards,
            "replicaType": rtype.value,
        }
    return None
