"""TPUJob spec validation.

Behavioral contract of the reference's ValidateV1TFJobSpec
(/root/reference/pkg/apis/tensorflow/validation/validation.go:27-73):
  - replica specs must be non-empty and each non-nil
  - each template must have ≥1 container
  - images must be non-empty
  - exactly one container per template must carry the operator container name
  - at most one Chief/Master replica
  - at most one Evaluator replica

TPU additions: topology strings must parse ("AxB[xC]"), logical mesh size (if
given) must equal the slice chip count, and unknown replica-type keys are
rejected (the reference rejects these implicitly through its typed API).
"""
from __future__ import annotations

from typing import List

from . import constants
from .defaults import normalize_replica_type
from .types import ReplicaType, TPUJob, TPUJobSpec


class ValidationError(ValueError):
    pass


def validate(job: TPUJob) -> None:
    if not job.metadata.name:
        raise ValidationError("TPUJob must have a name")
    validate_spec(job.spec)


def validate_spec(spec: TPUJobSpec) -> None:
    if not spec.replica_specs:
        raise ValidationError("TPUJobSpec is not valid: replica_specs is empty")

    for key, rspec in spec.replica_specs.items():
        rtype = normalize_replica_type(key)
        if rtype is None:
            valid = ", ".join(rt.value for rt in ReplicaType)
            raise ValidationError(
                f"TPUJobSpec is not valid: unknown replica type {key!r} (valid: {valid})"
            )
        if rspec is None:
            raise ValidationError(f"TPUJobSpec is not valid: replica spec for {rtype.value} is nil")
        _validate_replica(rtype, rspec)

    _validate_singleton(spec, (ReplicaType.CHIEF, ReplicaType.MASTER), "chief/master")
    _validate_singleton(spec, (ReplicaType.EVALUATOR,), "evaluator")
    _validate_multislice(spec)
    _validate_scheduling(spec)


_TENANT_RE = None  # compiled lazily; DNS-label shape like k8s names


def _validate_scheduling(spec: TPUJobSpec) -> None:
    """spec.scheduling: the class must come from the ordered table (a typo
    must not silently land a job in the default band), and the tenant must
    be a DNS-label-shaped accounting key — it becomes a metric label value
    (tpujob_tenant_dominant_share) and a pod annotation."""
    global _TENANT_RE
    if spec.scheduling is None:
        return
    from .types import PRIORITY_CLASSES

    sched = spec.scheduling
    if sched.priority_class and sched.priority_class not in PRIORITY_CLASSES:
        valid = ", ".join(PRIORITY_CLASSES)
        raise ValidationError(
            "TPUJobSpec is not valid: unknown scheduling.priorityClass "
            f"{sched.priority_class!r} (valid, lowest first: {valid})"
        )
    if sched.tenant:
        if _TENANT_RE is None:
            import re

            _TENANT_RE = re.compile(r"^[a-z0-9]([-a-z0-9]{0,61}[a-z0-9])?$")
        if not _TENANT_RE.match(sched.tenant):
            raise ValidationError(
                "TPUJobSpec is not valid: scheduling.tenant "
                f"{sched.tenant!r} must be a lowercase DNS label "
                "(alphanumeric and '-', at most 63 chars)"
            )


def _validate_multislice(spec: TPUJobSpec) -> None:
    """A multislice group (replicas spanning >1 slice) must be the job's only
    JAX-process replica type carrying a slice topology: all accelerator
    processes share one jax.distributed group, and a MEGASCALE document that
    differs across the group (or is absent for some members) hangs libtpu
    multislice init (controller/topology.py:_add_multislice_env).

    For the same reason a dynamic-worker group must fit a single slice:
    scaling across the slice boundary would create pods whose MEGASCALE env
    disagrees with the running members' (created when the group was
    single-slice).  This also rejects the scale-up update itself — the
    controller re-validates on every event."""
    from .types import topology_hosts

    sliced_jax_types = []
    multislice = False
    for key, rspec in spec.replica_specs.items():
        rtype = normalize_replica_type(key)
        if rtype not in (ReplicaType.CHIEF, ReplicaType.MASTER, ReplicaType.WORKER):
            continue
        if rspec is None or rspec.tpu is None or not rspec.tpu.topology:
            continue
        sliced_jax_types.append(rtype)
        try:
            hosts = topology_hosts(rspec.tpu.topology)
        except ValueError:
            continue  # malformed topology is reported by _validate_replica
        if int(rspec.replicas or 1) > hosts:
            multislice = True
    if multislice and len(sliced_jax_types) > 1:
        names = ", ".join(rt.value for rt in sliced_jax_types)
        raise ValidationError(
            "TPUJobSpec is not valid: a multislice job must keep all its "
            f"accelerator processes in one replica type, found topologies on {names}"
        )
    if multislice and spec.enable_dynamic_worker:
        raise ValidationError(
            "TPUJobSpec is not valid: enableDynamicWorker requires the worker "
            "group to fit one slice (scaling across the slice boundary would "
            "give new pods a MEGASCALE document the running members lack)"
        )


def _validate_singleton(spec: TPUJobSpec, rtypes, label: str) -> None:
    """≤1 replica across the given types (ref: validation.go:58-71)."""
    count = 0
    for key, rspec in spec.replica_specs.items():
        if normalize_replica_type(key) in rtypes and rspec is not None:
            count += int(rspec.replicas or 1)
    if count > 1:
        raise ValidationError(f"TPUJobSpec is not valid: more than one {label} replica specified")


def _validate_replica(rtype: ReplicaType, rspec) -> None:
    containers = rspec.template.containers
    if not containers:
        raise ValidationError(
            f"TPUJobSpec is not valid: containers for {rtype.value} replica is empty"
        )

    named: List[str] = []
    for c in containers:
        if not c.image:
            raise ValidationError(
                f"TPUJobSpec is not valid: image for {rtype.value} container {c.name!r} is empty"
            )
        if c.name in (constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME):
            named.append(c.name)
    if len(named) == 0:
        raise ValidationError(
            "TPUJobSpec is not valid: there is no container named "
            f"{constants.DEFAULT_CONTAINER_NAME!r} or {constants.ALT_CONTAINER_NAME!r} "
            f"in the {rtype.value} replica template"
        )
    if len(named) > 1:
        raise ValidationError(
            f"TPUJobSpec is not valid: more than one operator container in {rtype.value} template"
        )

    if rspec.elastic is not None:
        virtual = int(rspec.replicas or 1)
        lo = rspec.elastic.min_replicas
        hi = rspec.elastic.max_replicas
        if lo is not None and lo < 1:
            raise ValidationError(
                f"TPUJobSpec is not valid: elastic.minReplicas for {rtype.value} "
                f"must be >= 1, got {lo}"
            )
        if hi is not None and hi > virtual:
            raise ValidationError(
                f"TPUJobSpec is not valid: elastic.maxReplicas for {rtype.value} "
                f"({hi}) exceeds the virtual replica count ({virtual}) — physical "
                "replicas can never outnumber the virtual replicas they host"
            )
        if lo is not None and lo > virtual:
            raise ValidationError(
                f"TPUJobSpec is not valid: elastic.minReplicas for {rtype.value} "
                f"({lo}) exceeds the virtual replica count ({virtual})"
            )
        if lo is not None and hi is not None and lo > hi:
            raise ValidationError(
                f"TPUJobSpec is not valid: elastic.minReplicas ({lo}) > "
                f"elastic.maxReplicas ({hi}) for {rtype.value}"
            )

    if rspec.tpu is not None and rspec.tpu.topology:
        try:
            chips = rspec.tpu.num_chips()
        except ValueError:
            raise ValidationError(
                f"TPUJobSpec is not valid: malformed TPU topology {rspec.tpu.topology!r}"
            ) from None
        if rspec.tpu.mesh:
            mesh_size = 1
            for size in rspec.tpu.mesh.values():
                mesh_size *= size
            if mesh_size != chips:
                raise ValidationError(
                    f"TPUJobSpec is not valid: logical mesh {rspec.tpu.mesh} has "
                    f"{mesh_size} devices but topology {rspec.tpu.topology!r} has {chips} chips"
                )

    if rspec.tpu is not None:
        if rspec.tpu.device_memory_gb < 0:
            raise ValidationError(
                f"TPUJobSpec is not valid: tpu.deviceMemoryGB for {rtype.value} "
                f"must be >= 0, got {rspec.tpu.device_memory_gb}"
            )
        if rspec.tpu.model_params < 0:
            raise ValidationError(
                f"TPUJobSpec is not valid: tpu.modelParams for {rtype.value} "
                f"must be >= 0, got {rspec.tpu.model_params}"
            )
