"""Defaulting for TPUJob specs.

Behavioral contract of the reference's SetDefaults_TFJob
(/root/reference/pkg/apis/tensorflow/v1/defaults.go:92-113):
  - replica-type keys normalized to canonical casing ("ps" → "PS", defaults.go:70-89)
  - replicas default 1 (defaults.go:28-33)
  - restartPolicy default Never (defaults.go:61-67)
  - the framework port is injected on the operator container if the user
    declared no port with the well-known name (defaults.go:36-58)
  - cleanPodPolicy default Running, successPolicy default "" (defaults.go:98-104)

TPU additions: scheduling_policy.min_available defaults to the total replica
count (full-gang), and a replica with a TPU topology gets the slice chip
count as its google.com/tpu resource request.
"""
from __future__ import annotations

from typing import Optional

from . import constants
from .core import ContainerPort
from .types import (
    CleanPodPolicy,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    SuccessPolicy,
    TPUJob,
)

DEFAULT_RESTART_POLICY = RestartPolicy.NEVER

_CANONICAL = {rt.value.lower(): rt for rt in ReplicaType}


def normalize_replica_type(name: str) -> Optional[ReplicaType]:
    """Case-insensitive replica-type lookup (ref: defaults.go:70-89)."""
    if isinstance(name, ReplicaType):
        return name
    return _CANONICAL.get(str(name).lower())


def set_defaults_replica(spec: ReplicaSpec) -> None:
    if spec.replicas is None:
        spec.replicas = 1
    if spec.restart_policy is None:
        spec.restart_policy = DEFAULT_RESTART_POLICY
    if spec.elastic is not None:
        # replicas is the virtual width V; physical bounds default to the
        # widest safe band: [1, V].
        if spec.elastic.min_replicas is None:
            spec.elastic.min_replicas = 1
        if spec.elastic.max_replicas is None:
            spec.elastic.max_replicas = int(spec.replicas)
    _set_default_port(spec)
    _set_default_tpu_resources(spec)


def _set_default_port(spec: ReplicaSpec) -> None:
    """Inject the framework port on the operator container unless the user
    already declared one with the well-known name (ref: defaults.go:36-58)."""
    container = spec.template.container(
        constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME
    )
    if container is None:
        return
    for port in container.ports:
        if port.name == constants.DEFAULT_PORT_NAME:
            return
    container.ports.append(
        ContainerPort(name=constants.DEFAULT_PORT_NAME, container_port=constants.DEFAULT_PORT)
    )


def _set_default_tpu_resources(spec: ReplicaSpec) -> None:
    """A replica that declares a TPU topology implicitly requests that many
    chips (the reference's examples hand-write nvidia.com/gpu requests)."""
    if spec.tpu is None or not spec.tpu.topology:
        return
    container = spec.template.container(
        constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME
    )
    if container is not None and constants.TPU_RESOURCE not in container.resources:
        container.resources[constants.TPU_RESOURCE] = float(spec.tpu.num_chips())


def set_defaults(job: TPUJob) -> TPUJob:
    """Default a TPUJob in place and return it (ref: defaults.go:92-113)."""
    spec = job.spec
    if spec.success_policy is None:
        spec.success_policy = SuccessPolicy.DEFAULT
    if spec.run_policy.clean_pod_policy is None:
        spec.run_policy.clean_pod_policy = CleanPodPolicy.RUNNING

    # Normalize replica-type keys (accepts raw strings of any casing).
    normalized = {}
    for key, rspec in list(spec.replica_specs.items()):
        canonical = normalize_replica_type(key)
        normalized[canonical if canonical is not None else key] = rspec
    spec.replica_specs = normalized

    for rspec in spec.replica_specs.values():
        set_defaults_replica(rspec)

    if spec.run_policy.scheduling_policy is not None:
        sp = spec.run_policy.scheduling_policy
        if sp.min_available is None:
            sp.min_available = total_replicas(job)

    # spec.scheduling stays None when absent (policy-less jobs serialize
    # byte-identically to pre-policy manifests); a present block has its
    # empty fields normalized to the documented defaults.
    if spec.scheduling is not None:
        from .types import DEFAULT_PRIORITY_CLASS, DEFAULT_TENANT

        if not spec.scheduling.priority_class:
            spec.scheduling.priority_class = DEFAULT_PRIORITY_CLASS
        if not spec.scheduling.tenant:
            spec.scheduling.tenant = DEFAULT_TENANT
    return job


def total_replicas(job: TPUJob) -> int:
    """(ref: vendor/.../util/k8sutil/k8sutil.go GetTotalReplicas)"""
    return sum(int(r.replicas or 0) for r in job.spec.replica_specs.values())
