"""API-level constants for the TPUJob resource.

TPU-native re-design of the reference's constants
(/root/reference/pkg/apis/tensorflow/v1/constants.go:21-34 and
vendor/github.com/kubeflow/common/pkg/apis/common/v1/constants.go:3-18).
"""

# --- Group / version / kind (ref: pkg/apis/tensorflow/v1/register.go:31-44) ---
API_GROUP = "tpu-operator.dev"
API_VERSION = "v1"
KIND = "TPUJob"
PLURAL = "tpujobs"
SINGULAR = "tpujob"
CRD_NAME = f"{PLURAL}.{API_GROUP}"

# --- Container / port contract ---
# The operator acts on exactly one container per pod template.  For drop-in
# parity with reference TFJobs the default name is "tensorflow"
# (ref: pkg/apis/tensorflow/v1/constants.go:23-25); "tpu" is accepted as an
# alias for native jobs.
DEFAULT_CONTAINER_NAME = "tensorflow"
ALT_CONTAINER_NAME = "tpu"
# Port the framework injects if the user declares none
# (ref: constants.go:27-31 — name "tfjob-port", port 2222).
DEFAULT_PORT_NAME = "tpujob-port"
DEFAULT_PORT = 2222

# --- Well-known labels stamped on pods/services ---
# (ref: vendor/.../apis/common/v1/constants.go:3-18)
LABEL_GROUP_NAME = "group-name"
LABEL_JOB_NAME = "job-name"
LABEL_REPLICA_TYPE = "replica-type"
LABEL_REPLICA_INDEX = "replica-index"
LABEL_JOB_ROLE = "job-role"
JOB_ROLE_MASTER = "master"

# --- Gang scheduling ---
# (ref: vendor/.../controller.v1/common/pod.go:42-53,472-488)
GANG_SCHEDULER_NAME = "tpu-gang"
GANG_GROUP_ANNOTATION = "scheduling.tpu-operator.dev/group-name"
# The reference's exact gang shapes, used by --gang-mechanism volcano so a
# Volcano deployment admits our gangs without any in-process scheduler:
# schedulerName "volcano" (pod.go:43) + the batch-scheduler group annotation
# (pod.go:52-53) on every gang pod.
VOLCANO_SCHEDULER_NAME = "volcano"
VOLCANO_GROUP_ANNOTATION = "scheduling.k8s.io/group-name"
# Stamped by the substrate once a gang pod has been admitted/started
# (InMemoryCluster.bind_pod); the k8s backend signals boundness via
# spec.nodeName instead (pods/binding subresource).
ANNOTATION_BOUND = "tpu-operator.dev/bound"
# Scheduling-policy annotations the reconciler stamps on gang pods from
# spec.scheduling, read back by the in-process gang scheduler for its
# policy queue (docs/scheduling-policy.md).  Pods without them schedule
# as the default class/tenant, non-preemptible.
ANNOTATION_PRIORITY_CLASS = "scheduling.tpu-operator.dev/priority-class"
ANNOTATION_TENANT = "scheduling.tpu-operator.dev/tenant"
ANNOTATION_PREEMPTIBLE = "scheduling.tpu-operator.dev/preemptible"

# --- Slice allocation annotations (no reference analogue: GPU pods are
# placed individually; TPU slices are allocated whole).  The reconciler
# stamps accelerator/topology from the replica's tpu block; the gang
# scheduler writes slice id + host rank back at admission.
ANNOTATION_ACCELERATOR = "tpu-operator.dev/accelerator"
ANNOTATION_SLICE_TOPOLOGY = "tpu-operator.dev/slice-topology"
ANNOTATION_SLICE_ID = "tpu-operator.dev/slice-id"
ANNOTATION_SLICE_HOST = "tpu-operator.dev/slice-host"

# --- Environment variables the controller injects into pods ---
# TF_CONFIG is kept byte-compatible with the reference
# (ref: pkg/controller.v1/tensorflow/tensorflow.go:39-61).
ENV_TF_CONFIG = "TF_CONFIG"
# JAX / TPU coordination env (the TPU-native topology document; no reference
# analogue — the reference only speaks TF_CONFIG).
ENV_COORDINATOR_ADDRESS = "TPUJOB_COORDINATOR_ADDRESS"
ENV_PROCESS_ID = "TPUJOB_PROCESS_ID"
ENV_NUM_PROCESSES = "TPUJOB_NUM_PROCESSES"
ENV_MESH_SHAPE = "TPUJOB_MESH_SHAPE"  # json dict axis->size, e.g. {"dp":2,"tp":4}
# "1" => the training runtime shards optimizer state + weight update over
# the dp axis (ZeRO-style, train/zero.py; spec knob tpu.zeroShardWeightUpdate)
ENV_ZERO_SHARD_WEIGHT_UPDATE = "TPUJOB_ZERO_SHARD_WEIGHT_UPDATE"
ENV_SLICE_TOPOLOGY = "TPUJOB_SLICE_TOPOLOGY"  # e.g. "2x4" chips
ENV_ACCELERATOR = "TPUJOB_ACCELERATOR"  # e.g. "v5litepod-8"
ENV_REPLICA_TYPE = "TPUJOB_REPLICA_TYPE"
ENV_REPLICA_INDEX = "TPUJOB_REPLICA_INDEX"
# Elastic virtual-replica mapping (docs/elasticity.md): V virtual replicas
# (the fixed spec width) multiplexed onto P physical replicas; each physical
# worker derives its virtual set as {j : j % P == replica_index}.  The
# generation ties a running gang to the resize-doc revision that laid it out.
ENV_VIRTUAL_REPLICAS = "TPUJOB_VIRTUAL_REPLICAS"
ENV_PHYSICAL_REPLICAS = "TPUJOB_PHYSICAL_REPLICAS"
ENV_ELASTIC_GENERATION = "TPUJOB_ELASTIC_GENERATION"
# Multi-slice (DCN) coordination env, emitted when one replica group spans
# more than one slice — the names JAX/libtpu multislice reads.
ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
# Override for the cluster DNS domain appended to service addresses
# (ref: pkg/controller.v1/tensorflow/tensorflow.go:30-33,160-163).
ENV_CUSTOM_CLUSTER_DOMAIN = "CUSTOM_CLUSTER_DOMAIN"

# --- Resource names ---
TPU_RESOURCE = "google.com/tpu"  # replaces nvidia.com/gpu in the reference examples
