"""TPUJob (de)serialization: dicts/JSON/YAML, plus reference-TFJob ingestion.

Drop-in parity goal (BASELINE.json north star: "examples/v1 TFJobs run
unmodified"): `job_from_manifest` accepts BOTH this framework's native
TPUJob manifests and Kubeflow TFJob manifests
(apiVersion kubeflow.org/v1, kind TFJob, spec.tfReplicaSpecs —
ref /root/reference/pkg/apis/tensorflow/v1/types.go:27-68), converting
nvidia.com/gpu resource requests to google.com/tpu.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from . import constants
from .core import (
    Container,
    ContainerPort,
    EnvVar,
    ObjectMeta,
    PodTemplateSpec,
)
from .defaults import normalize_replica_type
from .types import (
    CleanPodPolicy,
    ElasticPolicy,
    JobCondition,
    JobConditionType,
    JobStatus,
    ReplicaSpec,
    ReplicaStatus,
    RestartPolicy,
    RunPolicy,
    SchedulingPolicy,
    SchedulingSpec,
    SuccessPolicy,
    TPUJob,
    TPUJobSpec,
    TPUTopology,
)


# ---------------------------------------------------------------------------
# to dict

def job_to_dict(job: TPUJob) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "replicaSpecs": {
            rt.value: _replica_to_dict(rs)
            for rt, rs in job.spec.replica_specs.items()
        },
        "runPolicy": _run_policy_to_dict(job.spec.run_policy),
        "successPolicy": job.spec.success_policy.value
        if job.spec.success_policy is not None else None,
        "enableDynamicWorker": job.spec.enable_dynamic_worker,
    }
    if job.spec.scheduling is not None:
        spec["scheduling"] = _scheduling_to_dict(job.spec.scheduling)
    return {
        "apiVersion": f"{constants.API_GROUP}/{constants.API_VERSION}",
        "kind": constants.KIND,
        "metadata": {
            "name": job.metadata.name,
            "namespace": job.metadata.namespace,
            "uid": job.metadata.uid,
            "labels": dict(job.metadata.labels),
            "annotations": dict(job.metadata.annotations),
        },
        "spec": spec,
        "status": status_to_dict(job.status),
    }


def _scheduling_to_dict(s: SchedulingSpec) -> Dict[str, Any]:
    return {
        "priorityClass": s.priority_class,
        "tenant": s.tenant,
        "preemptible": s.preemptible,
    }


def _replica_to_dict(rs: ReplicaSpec) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "replicas": rs.replicas,
        "restartPolicy": rs.restart_policy.value if rs.restart_policy else None,
        "template": _template_to_dict(rs.template),
    }
    if rs.tpu is not None:
        out["tpu"] = {
            "accelerator": rs.tpu.accelerator,
            "topology": rs.tpu.topology,
            "mesh": dict(rs.tpu.mesh),
            "zeroShardWeightUpdate": rs.tpu.zero_shard_weight_update,
            "deviceMemoryGB": rs.tpu.device_memory_gb,
            "modelParams": rs.tpu.model_params,
        }
    if rs.elastic is not None:
        out["elastic"] = {
            "minReplicas": rs.elastic.min_replicas,
            "maxReplicas": rs.elastic.max_replicas,
        }
    return out


def _template_to_dict(t: PodTemplateSpec) -> Dict[str, Any]:
    return {
        "metadata": {"labels": dict(t.metadata.labels),
                     "annotations": dict(t.metadata.annotations)},
        "spec": {
            "containers": [
                {
                    "name": c.name,
                    "image": c.image,
                    "command": list(c.command),
                    "args": list(c.args),
                    "env": [{"name": e.name, "value": e.value} for e in c.env],
                    "ports": [
                        {"name": p.name, "containerPort": p.container_port}
                        for p in c.ports
                    ],
                    "resources": {"limits": dict(c.resources)},
                    # volumeMounts, probes, ... passthrough survives the
                    # round trip
                    **dict(c.extra),
                }
                for c in t.containers
            ],
            "restartPolicy": t.restart_policy,
            "schedulerName": t.scheduler_name,
            "nodeSelector": dict(t.node_selector),
            # volumes, affinity, ... passthrough survives the round trip
            **dict(t.extra),
        },
    }


def _run_policy_to_dict(rp: RunPolicy) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "cleanPodPolicy": rp.clean_pod_policy.value if rp.clean_pod_policy else None,
        "ttlSecondsAfterFinished": rp.ttl_seconds_after_finished,
        "activeDeadlineSeconds": rp.active_deadline_seconds,
        "backoffLimit": rp.backoff_limit,
    }
    if rp.scheduling_policy is not None:
        out["schedulingPolicy"] = {
            "minAvailable": rp.scheduling_policy.min_available,
            "queue": rp.scheduling_policy.queue,
        }
    return out


def status_to_dict(status: JobStatus) -> Dict[str, Any]:
    return {
        "conditions": [
            {
                "type": c.type.value,
                "status": "True" if c.status else "False",
                "reason": c.reason,
                "message": c.message,
                "lastUpdateTime": c.last_update_time,
                "lastTransitionTime": c.last_transition_time,
            }
            for c in status.conditions
        ],
        "replicaStatuses": {
            rt: {"active": rs.active, "succeeded": rs.succeeded, "failed": rs.failed}
            for rt, rs in status.replica_statuses.items()
        },
        "startTime": status.start_time,
        "completionTime": status.completion_time,
        "lastReconcileTime": status.last_reconcile_time,
        "zeroShardingPlan": status.zero_sharding_plan,
        "elastic": status.elastic,
    }


# ---------------------------------------------------------------------------
# from dict

def job_from_dict(data: Dict[str, Any]) -> TPUJob:
    """Parse a native TPUJob or a reference TFJob manifest."""
    kind = data.get("kind", constants.KIND)
    meta = data.get("metadata", {})
    spec_raw = data.get("spec", {})

    replica_key = "replicaSpecs"
    if kind == "TFJob" or "tfReplicaSpecs" in spec_raw:
        replica_key = "tfReplicaSpecs"

    replica_specs = {}
    for rt_raw, rs_raw in (spec_raw.get(replica_key) or {}).items():
        rtype = normalize_replica_type(rt_raw)
        key = rtype if rtype is not None else rt_raw
        replica_specs[key] = _replica_from_dict(rs_raw or {})

    run_policy = _run_policy_from_dict(spec_raw)
    success = spec_raw.get("successPolicy")

    job = TPUJob(
        metadata=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
        ),
        spec=TPUJobSpec(
            replica_specs=replica_specs,
            run_policy=run_policy,
            success_policy=SuccessPolicy(success) if success is not None else None,
            enable_dynamic_worker=bool(spec_raw.get("enableDynamicWorker", False)),
            scheduling=_scheduling_from_dict(spec_raw.get("scheduling")),
        ),
    )
    status_raw = data.get("status")
    if status_raw:
        job.status = status_from_dict(status_raw)
    return job


def _scheduling_from_dict(data: Optional[Dict[str, Any]]) -> Optional[SchedulingSpec]:
    if not data:
        return None
    from .types import DEFAULT_PRIORITY_CLASS, DEFAULT_TENANT

    return SchedulingSpec(
        priority_class=data.get("priorityClass") or DEFAULT_PRIORITY_CLASS,
        tenant=data.get("tenant") or DEFAULT_TENANT,
        preemptible=bool(data.get("preemptible", False)),
    )


def _replica_from_dict(data: Dict[str, Any]) -> ReplicaSpec:
    template = _template_from_dict(data.get("template") or {})
    restart = data.get("restartPolicy")
    tpu_raw = data.get("tpu")
    tpu = None
    if tpu_raw:
        tpu = TPUTopology(
            accelerator=tpu_raw.get("accelerator", ""),
            topology=tpu_raw.get("topology", ""),
            mesh={k: int(v) for k, v in (tpu_raw.get("mesh") or {}).items()},
            zero_shard_weight_update=bool(
                tpu_raw.get("zeroShardWeightUpdate", False)
            ),
            device_memory_gb=float(tpu_raw.get("deviceMemoryGB", 0.0)),
            model_params=int(tpu_raw.get("modelParams", 0)),
        )
    elastic_raw = data.get("elastic")
    elastic = None
    if elastic_raw:
        elastic = ElasticPolicy(
            min_replicas=elastic_raw.get("minReplicas"),
            max_replicas=elastic_raw.get("maxReplicas"),
        )
    return ReplicaSpec(
        replicas=data.get("replicas"),
        restart_policy=RestartPolicy(restart) if restart else None,
        template=template,
        tpu=tpu,
        elastic=elastic,
    )


def _template_from_dict(data: Dict[str, Any]) -> PodTemplateSpec:
    meta = data.get("metadata") or {}
    spec = data.get("spec") or {}
    containers: List[Container] = []
    for c_raw in spec.get("containers") or []:
        resources_raw = c_raw.get("resources") or {}
        limits = dict(resources_raw.get("limits") or resources_raw.get("requests") or {})
        # GPU → TPU resource translation for reference manifests.
        if "nvidia.com/gpu" in limits:
            limits[constants.TPU_RESOURCE] = float(limits.pop("nvidia.com/gpu"))
        containers.append(
            Container(
                name=c_raw.get("name", ""),
                image=c_raw.get("image", ""),
                command=list(c_raw.get("command") or []),
                args=list(c_raw.get("args") or []),
                env=[
                    EnvVar(name=e.get("name", ""), value=str(e.get("value", "")))
                    for e in (c_raw.get("env") or [])
                ],
                ports=[
                    ContainerPort(
                        name=p.get("name", ""),
                        container_port=int(p.get("containerPort", 0)),
                    )
                    for p in (c_raw.get("ports") or [])
                ],
                resources={k: float(v) for k, v in limits.items()},
                extra={
                    k: v for k, v in c_raw.items()
                    if k not in ("name", "image", "command", "args", "env",
                                 "ports", "resources")
                },
            )
        )
    return PodTemplateSpec(
        metadata=ObjectMeta(
            labels=dict(meta.get("labels") or {}),
            annotations=dict(meta.get("annotations") or {}),
        ),
        containers=containers,
        restart_policy=spec.get("restartPolicy", ""),
        scheduler_name=spec.get("schedulerName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        extra={
            k: v for k, v in spec.items()
            if k not in ("containers", "restartPolicy", "schedulerName", "nodeSelector")
        },
    )


def _run_policy_from_dict(spec_raw: Dict[str, Any]) -> RunPolicy:
    # Native nests under runPolicy; the reference's v1 also accepts top-level
    # fields (ref: types.go:47-60 — RunPolicy inlined).
    rp_raw = dict(spec_raw.get("runPolicy") or {})
    for key in ("cleanPodPolicy", "ttlSecondsAfterFinished",
                "activeDeadlineSeconds", "backoffLimit", "schedulingPolicy"):
        if key not in rp_raw and key in spec_raw:
            rp_raw[key] = spec_raw[key]
    clean = rp_raw.get("cleanPodPolicy")
    sp_raw = rp_raw.get("schedulingPolicy")
    return RunPolicy(
        clean_pod_policy=CleanPodPolicy(clean) if clean else None,
        ttl_seconds_after_finished=rp_raw.get("ttlSecondsAfterFinished"),
        active_deadline_seconds=rp_raw.get("activeDeadlineSeconds"),
        backoff_limit=rp_raw.get("backoffLimit"),
        scheduling_policy=SchedulingPolicy(
            min_available=sp_raw.get("minAvailable"),
            queue=sp_raw.get("queue", ""),
        ) if sp_raw else None,
    )


def status_from_dict(data: Dict[str, Any]) -> JobStatus:
    conditions = [
        JobCondition(
            type=JobConditionType(c["type"]),
            status=c.get("status") in (True, "True"),
            reason=c.get("reason", ""),
            message=c.get("message", ""),
            last_update_time=c.get("lastUpdateTime") or 0.0,
            last_transition_time=c.get("lastTransitionTime") or 0.0,
        )
        for c in data.get("conditions") or []
    ]
    replica_statuses = {
        rt: ReplicaStatus(
            active=int(rs.get("active", 0)),
            succeeded=int(rs.get("succeeded", 0)),
            failed=int(rs.get("failed", 0)),
        )
        for rt, rs in (data.get("replicaStatuses") or {}).items()
    }
    return JobStatus(
        conditions=conditions,
        replica_statuses=replica_statuses,
        start_time=data.get("startTime"),
        completion_time=data.get("completionTime"),
        last_reconcile_time=data.get("lastReconcileTime"),
        zero_sharding_plan=data.get("zeroShardingPlan"),
        elastic=data.get("elastic"),
    )


# ---------------------------------------------------------------------------
# JSON / YAML entry points

def job_from_manifest(text: str) -> TPUJob:
    """Parse YAML or JSON manifest text (native TPUJob or reference TFJob)."""
    data: Optional[Dict[str, Any]] = None
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        try:
            import yaml  # type: ignore

            data = yaml.safe_load(text)
        except ImportError:
            data = _mini_yaml(text)
    if not isinstance(data, dict):
        raise ValueError("manifest did not parse to a mapping")
    return job_from_dict(data)


def _mini_yaml(text: str):
    """Tiny YAML-subset parser (mappings, lists, scalars) used only when
    PyYAML is unavailable; enough for the example manifests in examples/."""
    import re

    lines = [
        line.rstrip() for line in text.splitlines()
        if line.strip() and not line.strip().startswith("#")
    ]

    def parse_scalar(s: str):
        s = s.strip().strip('"').strip("'")
        if s in ("true", "True"):
            return True
        if s in ("false", "False"):
            return False
        if re.fullmatch(r"-?\d+", s):
            return int(s)
        if re.fullmatch(r"-?\d+\.\d*", s):
            return float(s)
        return s

    def parse_block(idx: int, indent: int):
        # returns (obj, next_idx)
        container = None
        while idx < len(lines):
            line = lines[idx]
            cur_indent = len(line) - len(line.lstrip())
            if cur_indent < indent:
                break
            stripped = line.strip()
            if stripped.startswith("- "):
                if container is None:
                    container = []
                item_text = stripped[2:]
                if ":" in item_text and not item_text.split(":", 1)[1].strip():
                    # "- key:" → nested mapping item
                    key = item_text.split(":", 1)[0]
                    sub, idx = parse_block(idx + 1, cur_indent + 2)
                    container.append({key: sub})
                elif ":" in item_text:
                    key, val = item_text.split(":", 1)
                    item = {key.strip(): parse_scalar(val)}
                    idx += 1
                    # continuation keys at deeper indent
                    while idx < len(lines):
                        nline = lines[idx]
                        nindent = len(nline) - len(nline.lstrip())
                        if nindent <= cur_indent or nline.strip().startswith("- "):
                            break
                        nstripped = nline.strip()
                        if nstripped.endswith(":"):
                            sub, idx = parse_block(idx + 1, nindent + 2)
                            item[nstripped[:-1]] = sub
                        else:
                            k, v = nstripped.split(":", 1)
                            item[k.strip()] = parse_scalar(v)
                            idx += 1
                    container.append(item)
                else:
                    container.append(parse_scalar(item_text))
                    idx += 1
            else:
                if container is None:
                    container = {}
                if stripped.endswith(":"):
                    sub, idx = parse_block(idx + 1, cur_indent + 1)
                    container[stripped[:-1]] = sub
                else:
                    key, val = stripped.split(":", 1)
                    container[key.strip()] = parse_scalar(val)
                    idx += 1
        return container, idx

    obj, _ = parse_block(0, 0)
    return obj
