"""Minimal "core v1" object model: Pods, Services, Events, object metadata.

The reference operates on Kubernetes core-v1 objects via client-go.  This
framework is cluster-agnostic: the controller reconciles against the small
object model below through a ClusterInterface seam (runtime/cluster.py), with
backends that are in-memory (unit tests — the analogue of the reference's
fake clients, /root/reference/pkg/common/util/v1/testutil/), real local
processes (hermetic E2E + single-host TPU runs), or a real cluster.

Only the fields the reconcile engine actually reads/writes are modelled;
everything else passes through `extra` untouched (the reference's
PodTemplateSpec-passthrough philosophy, tf_job_design_doc.md §TFJob Resource).
"""
from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional


class PodPhase(str, Enum):
    """Mirror of k8s core-v1 pod phases the reconciler branches on."""

    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    # Owner reference: (kind, name, uid) of the controlling TPUJob, used for
    # adoption/orphaning (ref: vendor/.../control/controller_ref_manager.go).
    # Not wire fields: the cluster backend stamps owner refs and timestamps
    # server-side (like k8s ownerReferences/creationTimestamp); a TPUJob
    # manifest round trip intentionally drops them.
    owner_kind: str = ""  # contract: exempt(wire-roundtrip)
    owner_name: str = ""  # contract: exempt(wire-roundtrip)
    owner_uid: str = ""  # contract: exempt(wire-roundtrip)
    creation_timestamp: float = field(default_factory=time.time)  # contract: exempt(wire-roundtrip)
    deletion_timestamp: Optional[float] = None  # contract: exempt(wire-roundtrip)

    def controlled_by(self, kind: str, uid: str) -> bool:
        return self.owner_kind == kind and self.owner_uid == uid


@dataclass
class EnvVar:
    name: str
    value: str


@dataclass
class ContainerPort:
    name: str = ""
    container_port: int = 0


@dataclass
class Container:
    """One container of a pod template.

    `resources` is a flat {resource_name: quantity} map; the TPU resource is
    constants.TPU_RESOURCE (the reference's examples request nvidia.com/gpu,
    e.g. examples/v1/distribution_strategy/keras-API/multi_worker_tfjob.yaml).
    """

    name: str
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    ports: List[ContainerPort] = field(default_factory=list)
    resources: Dict[str, float] = field(default_factory=dict)
    # volumeMounts, probes, securityContext, ... passthrough (same philosophy
    # as PodTemplateSpec.extra) — the k8s backend must not strip fields the
    # reconcile engine doesn't read.
    extra: Dict[str, Any] = field(default_factory=dict)

    def set_env(self, name: str, value: str) -> None:
        for e in self.env:
            if e.name == name:
                e.value = value
                return
        self.env.append(EnvVar(name=name, value=value))

    def get_env(self, name: str) -> Optional[str]:
        for e in self.env:
            if e.name == name:
                return e.value
        return None


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    containers: List[Container] = field(default_factory=list)
    # "Never" | "Always" | "OnFailure" — what the substrate does on container
    # exit; set by the controller from the replica RestartPolicy
    # (ref: pkg/controller.v1/tensorflow/pod.go:310-317).
    restart_policy: str = ""
    scheduler_name: str = ""
    node_selector: Dict[str, str] = field(default_factory=dict)
    # set by the scheduler at binding time (pods/binding subresource on the
    # k8s backend); non-empty means the pod has been scheduled onto a node —
    # runtime state, never part of the TPUJob template wire format
    node_name: str = ""  # contract: exempt(wire-roundtrip)
    extra: Dict[str, Any] = field(default_factory=dict)  # volumes, affinity, ... passthrough

    def container(self, *names: str) -> Optional[Container]:
        for c in self.containers:
            if c.name in names:
                return c
        return None


@dataclass
class ContainerStatus:
    name: str
    restart_count: int = 0
    running: bool = False
    terminated: bool = False
    exit_code: Optional[int] = None


@dataclass
class PodStatus:
    phase: PodPhase = PodPhase.PENDING
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    start_time: Optional[float] = None
    reason: str = ""
    message: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status: PodStatus = field(default_factory=PodStatus)

    def deepcopy(self) -> "Pod":
        return copy.deepcopy(self)


@dataclass
class ServicePort:
    name: str = ""
    port: int = 0


@dataclass
class Service:
    """Headless-service analogue: a stable DNS name for one replica
    (ref: vendor/.../controller.v1/common/service.go:303-317)."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    selector: Dict[str, str] = field(default_factory=dict)
    ports: List[ServicePort] = field(default_factory=list)
    cluster_ip: str = "None"  # headless


@dataclass
class Event:
    """K8s-Event analogue emitted on the TPUJob (ref: record.EventRecorder
    usage, e.g. pkg/controller.v1/tensorflow/pod.go:131,146)."""

    object_kind: str
    object_name: str
    namespace: str
    event_type: str  # "Normal" | "Warning"
    reason: str
    message: str
    timestamp: float = field(default_factory=time.time)


@dataclass
class PodGroup:
    """Gang-scheduling unit: all-or-nothing admission of `min_member` pods.

    TPU-native semantics: a multi-host slice is inherently a gang — partial
    host sets are useless — so one PodGroup == one slice allocation
    (ref: Volcano PodGroup sync, vendor/.../common/job_controller.go:211-239).
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_member: int = 0
    queue: str = ""
    # Filled by the scheduler/slice-allocator: "Pending" | "Inqueue" | "Running"
    phase: str = "Pending"


@dataclass
class PodDisruptionBudget:
    """PDB analogue: guards *voluntary* evictions of a gang's pods.

    The reference offers this as the non-Volcano gang mechanism
    (SyncPdb/DeletePdb, vendor/.../common/job_controller.go:242-316):
    min_available = total replicas means no voluntary disruption may take a
    slice host away from a running gang.  Involuntary failures (crashes,
    preemption) are not guarded — they flow through the restart state machine.
    """

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_available: int = 0
    selector: Dict[str, str] = field(default_factory=dict)
