"""tf_operator_tpu — a TPU-native distributed-training job framework.

A brand-new re-architecture (not a port) of the capabilities of Kubeflow's
tf-operator (reference: /root/reference): a TPUJob resource + reconciling
controller that turns a declarative replica map into gang-scheduled pods on
Cloud TPU slices, injects cluster topology (TF_CONFIG + JAX coordination env),
and drives the Created→Running→Restarting→Succeeded/Failed state machine —
plus the TPU-side training runtime (JAX/XLA/pallas) the reference delegates to
user containers: SPMD meshes, data/tensor/sequence parallelism, ring
attention, and reference workloads (MNIST, ResNet-50, BERT, Transformer LM).

Layer map (mirrors SURVEY.md §1):
  api/        — TPUJob types, defaults, validation   (ref: pkg/apis/tensorflow/v1)
  runtime/    — generic job reconcile engine          (ref: vendor kubeflow/common)
  controller/ — TPUJob-specific reconciler + topology (ref: pkg/controller.v1/tensorflow)
  server/     — flags, metrics, leader election       (ref: cmd/tf-operator.v1)
  sdk/        — Python client                         (ref: sdk/python/kubeflow/tfjob)
  parallel/   — meshes, shardings, collectives, ring attention (TPU-native, no ref analogue)
  ops/        — pallas kernels + jax fallbacks
  models/     — MNIST / ResNet-50 / BERT / Transformer LM
  train/      — sharded train-step/trainer machinery
  workloads/  — runnable pod entrypoints (the "user container" side)
"""

__version__ = "0.3.0"
