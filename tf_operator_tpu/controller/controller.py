"""TPUJobController: the job-type-specific brain.

Re-architecture of the reference's TFController
(/root/reference/pkg/controller.v1/tensorflow/controller.go,job.go,pod.go):
watch handlers feed a rate-limited workqueue; N worker threads pop keys and
run the generic reconcile engine with TPU-specific plugin hooks (topology
injection, master-role labeling, success matrix).  Expectations gate syncs so
a stale store view never causes duplicate pod creation
(ref: controller.go:319,339-358).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..api import constants
from ..api.core import Event, Pod, Service
from ..api.defaults import set_defaults
from ..api.types import (
    JobConditionType,
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    contains_chief_or_master,
)
from ..api.validation import ValidationError, validate
from ..runtime import conditions
from ..runtime.cluster import ClusterInterface, EventType, NotFound
from ..runtime.control import RealPodControl, RealServiceControl
from ..runtime.expectations import expectation_key
from ..runtime.reconciler import (
    JobPlugin,
    JobReconciler,
    ReconcilerConfig,
)
from ..runtime.workqueue import RateLimitingQueue, ShutDown
from ..utils import locks
from ..utils import logging as tpulog
from ..utils import metrics
from . import status as status_engine
from . import topology

CONTROLLER_NAME = "tpujob-controller"

FAILED_VALIDATION_REASON = "FailedValidation"

# Degraded-mode backstop: when the substrate's ClientHealth reports this many
# consecutive request giveups (runtime/k8s.py DEGRADED_GIVEUP_THRESHOLD), the
# resync period widens by this factor so a flapping apiserver isn't hammered
# by the full-relist loop, and one ClusterDegraded Warning event marks the
# episode.  Recovery is automatic: the first completed request resets the
# streak and the next resync tick narrows the period again.
DEGRADED_RESYNC_FACTOR = 4.0


class TPUJobController(JobPlugin):
    def __init__(
        self,
        cluster: ClusterInterface,
        config: Optional[ReconcilerConfig] = None,
        resolver: topology.AddressResolver = topology.dns_resolver,
        threadiness: int = 1,
    ) -> None:
        self.controller_name = CONTROLLER_NAME
        self.cluster = cluster
        self.resolver = resolver
        self.threadiness = threadiness
        self.work_queue = RateLimitingQueue()
        self.pod_control = RealPodControl(cluster)
        self.service_control = RealServiceControl(cluster)
        self.reconciler = JobReconciler(
            cluster=cluster,
            pod_control=self.pod_control,
            service_control=self.service_control,
            plugin=self,
            config=config,
        )
        self.expectations = self.reconciler.expectations
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._sync_errors: Dict[str, str] = {}
        # job keys already warned about disabled multislice emission;
        # check-and-add under _warned_lock so threadiness>1 emits exactly
        # one MultisliceDisabled event per job
        self._multislice_warned: set = set()  # guarded-by: _warned_lock
        self._warned_lock = locks.new_lock("multislice-warned")
        # degraded-mode backstop state (see _check_degraded)
        self._degraded = False
        self.resync_period_current = (
            self.reconciler.config.reconciler_sync_loop_period
        )

        cluster.watch_jobs(self._on_job_event)
        cluster.watch_pods(self._on_pod_event)
        cluster.watch_services(self._on_service_event)

    # ------------------------------------------------------------------
    # watch handlers (ref: controller.go:135-175; job.go:54-170;
    # common/pod.go:73-214)

    def _on_job_event(self, etype: EventType, job: TPUJob) -> None:
        if etype == EventType.ADDED:
            self.add_job(job)
        elif etype == EventType.MODIFIED:
            self.work_queue.add(job.key())
        elif etype == EventType.DELETED:
            # Pods/services are garbage-collected by ownership in real k8s;
            # our substrates clean up on terminal state instead.
            self.expectations.delete_expectations(job.key())
            with self._warned_lock:
                self._multislice_warned.discard(job.key())

    def add_job(self, job: TPUJob) -> None:
        """Admission: validate, default, stamp JobCreated, enqueue
        (ref: addTFJob, job.go:54-131)."""
        try:
            validate(job)
        except ValidationError as err:
            # Reject: write a Failed condition + warning event, do not enqueue
            # (ref: job.go:65-105).
            conditions.update_job_conditions(
                job.status, JobConditionType.FAILED, FAILED_VALIDATION_REASON, str(err)
            )
            self.cluster.record_event(
                Event(
                    object_kind=job.kind,
                    object_name=job.metadata.name,
                    namespace=job.metadata.namespace,
                    event_type="Warning",
                    reason=FAILED_VALIDATION_REASON,
                    message=str(err),
                )
            )
            try:
                self.cluster.update_job_status(
                    job.metadata.namespace, job.metadata.name, job.status
                )
            except NotFound:
                pass
            return

        set_defaults(job)
        conditions.update_job_conditions(
            job.status,
            JobConditionType.CREATED,
            "TPUJobCreated",
            f"TPUJob {job.metadata.name} is created.",
        )
        metrics.jobs_created.labels().inc()
        self.work_queue.add(job.key())

    def _on_pod_event(self, etype: EventType, pod: Pod) -> None:
        key = self._owner_key(pod)
        if key is None:
            return
        rtype = pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        if etype == EventType.ADDED:
            self.expectations.creation_observed(expectation_key(key, rtype, "pods"))
        elif etype == EventType.DELETED:
            self.expectations.deletion_observed(expectation_key(key, rtype, "pods"))
        self.work_queue.add(key)

    def _on_service_event(self, etype: EventType, svc: Service) -> None:
        key = self._owner_key(svc)
        if key is None:
            return
        rtype = svc.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        if etype == EventType.ADDED:
            self.expectations.creation_observed(expectation_key(key, rtype, "services"))
        elif etype == EventType.DELETED:
            self.expectations.deletion_observed(expectation_key(key, rtype, "services"))
        self.work_queue.add(key)

    @staticmethod
    def _owner_key(obj) -> Optional[str]:
        meta = obj.metadata
        if meta.owner_kind != "TPUJob" or not meta.owner_name:
            return None
        return f"{meta.namespace}/{meta.owner_name}"

    # ------------------------------------------------------------------
    # sync loop (ref: Run/runWorker/processNextWorkItem, controller.go:186-274)

    def run(self, stop_after: Optional[float] = None) -> None:
        """Start worker threads; blocks until stop() (or stop_after seconds)."""
        self.start()
        if stop_after is not None:
            time.sleep(stop_after)
            self.stop()
        else:
            while not self._stop.is_set():
                time.sleep(0.2)

    def start(self) -> None:
        """Non-blocking run()."""
        for i in range(self.threadiness):
            t = threading.Thread(target=self._run_worker, name=f"tpujob-worker-{i}", daemon=True)
            t.start()
            self._threads.append(t)
        resync = threading.Thread(target=self._resync_loop, name="tpujob-resync", daemon=True)
        resync.start()
        self._threads.append(resync)

    def _resync_loop(self) -> None:
        """Periodic full resync (ref: ReconcilerSyncLoopPeriod 15s,
        common/job_controller.go:60-77): the backstop for timer-driven
        policies (TTL, ActiveDeadlineSeconds) across controller restarts.
        Under a degraded control plane the period widens (see
        _check_degraded) and list failures skip the tick instead of killing
        the thread — the resync loop must outlive any apiserver outage."""
        base = self.reconciler.config.reconciler_sync_loop_period
        while not self._stop.wait(timeout=self.resync_period_current):
            # Whole tick under one guard: the resync thread must never die —
            # a dead backstop silently disables TTL/deadline policies AND
            # the degraded-mode detection that matters most mid-outage.
            try:
                factor = (DEGRADED_RESYNC_FACTOR if self._check_degraded()
                          else 1.0)
                self.resync_period_current = base * factor
                for job in self.cluster.list_jobs():
                    self.work_queue.add(job.key())
            except Exception as err:  # noqa: BLE001 — transient; next tick retries
                tpulog.logger_for_key("resync").warning(
                    "resync tick failed: %s", err)

    def _check_degraded(self) -> bool:
        """Poll the substrate's ClientHealth (duck-typed; absent on
        in-memory substrates => never degraded).  Emits ClusterDegraded
        exactly once per episode; recovery is logged and re-arms the
        event for the next episode."""
        health = getattr(self.cluster, "health", None)
        if health is None:
            return False
        degraded = health.degraded()
        if degraded and not self._degraded:
            self._degraded = True
            tpulog.logger_for_key("resync").warning(
                "control plane degraded: %d consecutive request giveups; "
                "widening resync period x%g",
                health.consecutive_giveups, DEGRADED_RESYNC_FACTOR)
            # Best-effort by record_event contract: a failed write while
            # degraded must not abort the resync loop.  Target the
            # cluster's own namespace — a namespace-scoped deployment has
            # no RBAC to write events into "default".
            namespace = (getattr(self.cluster, "namespace", None)
                         or getattr(getattr(self.cluster, "config", None),
                                    "namespace", None)
                         or "default")
            self.cluster.record_event(Event(
                object_kind="TPUJob",
                object_name=CONTROLLER_NAME,
                namespace=namespace,
                event_type="Warning",
                reason="ClusterDegraded",
                message=(
                    f"{health.consecutive_giveups} consecutive apiserver "
                    f"request giveups; resync period widened "
                    f"x{DEGRADED_RESYNC_FACTOR:g} until the control plane "
                    "recovers"),
            ))
        elif not degraded and self._degraded:
            self._degraded = False
            tpulog.logger_for_key("resync").info(
                "control plane recovered; resync period restored")
        return degraded

    def stop(self) -> None:
        self._stop.set()
        self.work_queue.shutdown()
        for t in self._threads:
            t.join(timeout=5)

    def _run_worker(self) -> None:
        while not self._stop.is_set():
            try:
                key = self.work_queue.get(timeout=0.5)
            except ShutDown:
                return
            except TimeoutError:
                continue
            try:
                self.sync_job(key)
                self.work_queue.forget(key)
            except Exception as err:  # noqa: BLE001 — sync errors requeue with backoff
                self._sync_errors[key] = str(err)
                tpulog.logger_for_key(key).warning("sync failed: %s", err)
                self.work_queue.add_rate_limited(key)
            finally:
                self.work_queue.done(key)

    def sync_job(self, key: str) -> bool:
        """One reconcile pass for `key` (ref: syncTFJob, controller.go:290-334).
        Returns True if a reconcile ran (expectations satisfied)."""
        start = time.monotonic()
        try:
            return self._sync_job(key)
        finally:
            # Per-sync latency log (ref: controller.go:291-295).
            tpulog.logger_for_key(key).debug(
                "finished syncing tpujob (%.1f ms)", (time.monotonic() - start) * 1e3
            )

    def _sync_job(self, key: str) -> bool:
        namespace, _, name = key.partition("/")
        try:
            job = self.cluster.get_job(namespace, name)
        except NotFound:
            self.expectations.delete_expectations(key)
            return True

        job = job.deepcopy()
        set_defaults(job)

        # Sync gate: only act on a caught-up view — unless dynamic workers
        # force every-loop syncs (ref: controller.go:319).
        if not (self.satisfied_expectations(job) or job.spec.enable_dynamic_worker):
            return False

        result = self.reconciler.reconcile_job(job)
        if result.requeue_after is not None:
            self.work_queue.add_after(key, result.requeue_after)
        return True

    def satisfied_expectations(self, job: TPUJob) -> bool:
        """(ref: satisfiedExpectations, controller.go:339-358)"""
        key = job.key()
        return all(
            self.expectations.satisfied(expectation_key(key, rtype.value, kind))
            for rtype in job.spec.replica_specs
            for kind in ("pods", "services")
        )

    # ------------------------------------------------------------------
    # JobPlugin hooks

    def set_cluster_spec(self, job: TPUJob, pod: Pod, rtype: ReplicaType, index: int) -> None:
        def warn(reason: str, message: str) -> None:
            # One Warning Event per job, not one per pod per resync: the
            # condition is a property of the spec, which is immutable for
            # a given generation of pod creations.
            with self._warned_lock:
                if job.key() in self._multislice_warned:
                    return
                self._multislice_warned.add(job.key())
            self.cluster.record_event(Event(
                object_kind=job.kind,
                object_name=job.metadata.name,
                namespace=job.metadata.namespace,
                event_type="Warning",
                reason=reason,
                message=message,
            ))

        topology.set_cluster_spec(job, pod, rtype, index, self.resolver, warn)

    def is_master_role(
        self, replicas: Dict[ReplicaType, ReplicaSpec], rtype: ReplicaType, index: int
    ) -> bool:
        """Chief/Master pod if declared, else worker-0
        (ref: controller.go:409-416)."""
        if any(rt in (ReplicaType.CHIEF, ReplicaType.MASTER) for rt in replicas):
            return rtype in (ReplicaType.CHIEF, ReplicaType.MASTER)
        return rtype == ReplicaType.WORKER and index == 0

    def update_job_status(self, job: TPUJob, replicas, status, pods, restarting_this_pass) -> None:
        status_engine.update_job_status(
            job,
            replicas,
            status,
            pods,
            restarting_this_pass=restarting_this_pass,
            record_event=self.cluster.record_event,
            on_start_time_set=lambda deadline: self.work_queue.add_after(job.key(), deadline),
        )

    def on_pod_created(self, job: TPUJob, rtype: ReplicaType) -> None:
        pass
