"""TPUJobController: the job-type-specific brain.

Re-architecture of the reference's TFController
(/root/reference/pkg/controller.v1/tensorflow/controller.go,job.go,pod.go):
watch handlers feed a sharded, rate-limited workqueue; worker pools (one per
shard, selected by stable key hash so a hot tenant's backoff storm cannot
serialize other tenants) pop keys and run the generic reconcile engine with
TPU-specific plugin hooks (topology injection, master-role labeling, success
matrix).  Reads on the sync hot path come from a shared informer cache
(runtime/informer.py, docs/informer-cache.md) instead of the wire, the
client-go L0/L1 analogue that collapses per-sync apiserver traffic to ~zero;
writes stay on the cluster.  Expectations gate syncs so a stale store view
never causes duplicate pod creation (ref: controller.go:319,339-358).

On top of the reference's loop sits a self-healing layer (controller/health.py,
docs/self-healing.md): a `tpujob-watchdog` thread respawns dead workers,
flags hung syncs, and force-reconnects stale watch streams; poison jobs —
keys whose sync fails `quarantine_threshold` times in a row — are parked out
of the hot queue with a Stuck condition and probed once per resync tick, so
one bad job cannot starve the others.  `health_report()` aggregates all of it
into the live/ready verdict `/healthz` serves.
"""
from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, List, Optional

from ..api import constants
from ..api.core import Event, Pod, Service
from ..api.defaults import set_defaults
from ..api.serialization import job_to_dict
from ..api.types import (
    JobConditionType,
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    contains_chief_or_master,
)
from ..api.validation import ValidationError, validate
from ..runtime import conditions
from ..runtime.cluster import ClusterInterface, EventType, NotFound
from ..runtime.control import RealPodControl, RealServiceControl
from ..runtime.expectations import expectation_key
from ..runtime.informer import DEFAULT_RELIST_PERIOD, InformerCache
from ..runtime.reconciler import (
    JobPlugin,
    JobReconciler,
    ReconcilerConfig,
)
from ..runtime.shardlease import ShardLeaseConfig, ShardLeaseManager
from ..runtime.workqueue import ShardedWorkQueue, ShutDown
from ..utils import clock, locks
from ..utils import logging as tpulog
from ..utils import metrics
from . import status as status_engine
from . import topology
from .health import (
    ACTION_QUARANTINED,
    ACTION_REQUEUE,
    SelfHealingConfig,
    SyncHealth,
)

CONTROLLER_NAME = "tpujob-controller"

FAILED_VALIDATION_REASON = "FailedValidation"
JOB_STUCK_REASON = "JobStuck"
JOB_RECOVERED_REASON = "SyncRecovered"

# Degraded-mode backstop: when the substrate's ClientHealth reports this many
# consecutive request giveups (runtime/k8s.py DEGRADED_GIVEUP_THRESHOLD), the
# resync period widens by this factor so a flapping apiserver isn't hammered
# by the full-relist loop, and one ClusterDegraded Warning event marks the
# episode.  Recovery is automatic: the first completed request resets the
# streak and the next resync tick narrows the period again.
DEGRADED_RESYNC_FACTOR = 4.0


def _spec_fingerprint(job: TPUJob) -> str:
    """Stable digest of the job's spec, for release-on-spec-change: a
    MODIFIED event whose spec digest differs from the last observed one is
    a user edit, not one of the controller's own status writes."""
    try:
        return json.dumps(job_to_dict(job).get("spec", {}), sort_keys=True,
                          default=str)
    except (TypeError, ValueError):
        return repr(job.spec)


class TPUJobController(JobPlugin):
    def __init__(
        self,
        cluster: ClusterInterface,
        config: Optional[ReconcilerConfig] = None,
        resolver: topology.AddressResolver = topology.dns_resolver,
        threadiness: int = 1,
        healing: Optional[SelfHealingConfig] = None,
        shards: int = 1,
        use_informer: bool = True,
        informer_relist_period: float = DEFAULT_RELIST_PERIOD,
        shard_lease: Optional[ShardLeaseConfig] = None,
        identity: Optional[str] = None,
    ) -> None:
        self.controller_name = CONTROLLER_NAME
        self.cluster = cluster
        self.resolver = resolver
        # `threadiness` is workers PER SHARD (with shards=1 — the default —
        # it is the total, i.e. today's meaning, preserved exactly).
        self.threadiness = threadiness
        self.num_shards = max(1, int(shards))
        self.work_queue = ShardedWorkQueue(self.num_shards)
        # The informer registers its watch handlers BEFORE ours below, so
        # on every event the store is updated first and the enqueued key's
        # sync reads a view that already includes that event.  Reads the
        # hot path used to pay wire traffic for (get_job + the two
        # label-selected lists per sync) come from it; writes stay on the
        # cluster.  docs/informer-cache.md tells the whole story.
        self.informer: Optional[InformerCache] = (
            InformerCache(cluster, relist_period=informer_relist_period)
            if use_informer else None
        )
        self.reads = self.informer if self.informer is not None else cluster
        self.pod_control = RealPodControl(cluster)
        self.service_control = RealServiceControl(cluster)
        self.reconciler = JobReconciler(
            cluster=cluster,
            pod_control=self.pod_control,
            service_control=self.service_control,
            plugin=self,
            config=config,
            reads=self.reads,
        )
        self.expectations = self.reconciler.expectations
        # All status PUTs (reconcile passes AND the rare out-of-pass Stuck
        # marker / validation writes) share one coalescing writer so the
        # no-op/echo suppression sees every write (docs/federation.md).
        self.status_writer = self.reconciler.status_writer
        self.healing = healing or SelfHealingConfig()
        self.sync_health = SyncHealth(self.healing)
        # Federation (runtime/shardlease.py, docs/federation.md): with a
        # ShardLeaseConfig this replica syncs only the shards whose leases
        # it holds; peers sharing the cluster's lease store split the rest.
        # The lease shard space IS the workqueue shard space — one
        # shard_for(key) answers both routing and ownership.
        self.identity = identity or f"{CONTROLLER_NAME}-{id(self):x}"
        self.shard_manager: Optional[ShardLeaseManager] = None
        if shard_lease is not None:
            # Copy, don't alias: the caller may share one config between
            # controllers with different shard counts, and mutating theirs
            # would rewrite a sibling manager's shard range under it.
            self.shard_manager = ShardLeaseManager(
                cluster, self.identity,
                dataclasses.replace(shard_lease, num_shards=self.num_shards),
                on_adopt=self._on_shard_adopted,
                on_drop=self._on_shard_dropped,
            )
        # Event-driven resync backstop: keys whose last sync verifiably did
        # nothing (no write, expectations satisfied, no pending timer).
        # Intermediate resync ticks skip them; any watch event or shard
        # adoption clears the mark (docs/federation.md).
        self._quiescent: set = set()  # guarded-by: _quiescent_lock
        self._quiescent_lock = locks.new_lock("controller-quiescent")
        self._resync_tick = 0  # only the resync thread touches it
        self._stop = threading.Event()
        self._resync_now = threading.Event()  # watchdog-triggered resync
        self._started = False
        self._workers_lock = locks.new_lock("controller-workers")
        self._workers: Dict[int, threading.Thread] = {}  # guarded-by: _workers_lock
        self._worker_restarts = 0  # guarded-by: _workers_lock
        self._aux_threads: List[threading.Thread] = []
        self._watchdog: Optional[threading.Thread] = None
        # job keys already warned about disabled multislice emission;
        # check-and-add under _warned_lock so threadiness>1 emits exactly
        # one MultisliceDisabled event per job
        self._multislice_warned: set = set()  # guarded-by: _warned_lock
        self._warned_lock = locks.new_lock("multislice-warned")
        # degraded-mode backstop state (see _check_degraded)
        self._degraded = False
        self.resync_period_current = (
            self.reconciler.config.reconciler_sync_loop_period
        )
        # gang scheduler is attached post-construction (server.py wiring);
        # the property setter hooks its slice provider's repair events
        self._gang_scheduler = None

        cluster.watch_jobs(self._on_job_event)
        cluster.watch_pods(self._on_pod_event)
        cluster.watch_services(self._on_service_event)

    # ------------------------------------------------------------------
    # watch handlers (ref: controller.go:135-175; job.go:54-170;
    # common/pod.go:73-214)

    # ------------------------------------------------------------------
    # shard ownership + quiescence (the federation seams; no-ops without a
    # shard manager — the solo controller behaves exactly as before)

    def owns_key(self, key: str) -> bool:
        """Does this replica currently own `key`'s shard lease?  Always
        True without federation."""
        return (self.shard_manager is None
                or self.shard_manager.owns(self.work_queue.shard_index(key)))

    def _enqueue(self, key: str) -> None:
        """Ownership-gated enqueue: every peer replica sees every watch
        event, but only the shard owner queues work for it.  Keys of
        unowned shards are dropped here — the owner saw the same event."""
        if self.owns_key(key):
            self.work_queue.add(key)

    def _mark_active(self, key: str) -> None:
        with self._quiescent_lock:
            self._quiescent.discard(key)

    def _is_quiescent(self, key: str) -> bool:
        with self._quiescent_lock:
            return key in self._quiescent

    def _note_pass(self, key: str, job: TPUJob, result) -> None:
        """After a reconcile pass: a verified no-op (nothing written, no
        creations/deletions pending, no timer to re-arm, not a dynamic-
        worker job that syncs every loop) marks the key quiescent so the
        resync backstop skips it until the next event touches it."""
        quiet = (not result.wrote_status
                 and result.requeue_after is None
                 and not job.spec.enable_dynamic_worker
                 and self.satisfied_expectations(job))
        with self._quiescent_lock:
            if quiet:
                self._quiescent.add(key)
            else:
                self._quiescent.discard(key)

    def _forget_key(self, key: str) -> None:
        """Release every per-key residue on deletion/NotFound."""
        self.expectations.delete_expectations(key)
        self.work_queue.forget(key)
        self.sync_health.forget(key)
        self.status_writer.forget(key)
        with self._quiescent_lock:
            self._quiescent.discard(key)

    def _on_shard_adopted(self, shard: int) -> None:
        """We just acquired `shard`'s lease (initial claim, rebalance, or a
        dead peer's expiry).  Replay every job on the shard: whatever
        events fired while the shard was ownerless are repaired here,
        which is the no-lost-key half of the handoff invariant.  A job
        with NO conditions was created in an ownerless window and never
        admitted anywhere — it gets the full add_job admission (validate,
        reject-or-stamp-Created, enqueue), not a bare enqueue: the sync
        path never validates, so skipping admission would reconcile an
        invalid spec into quarantine instead of FailedValidation."""
        try:
            if self.informer is not None:
                keys = self.informer.job_keys()
            else:
                keys = [job.key() for job in self.reads.list_jobs()]
            for key in keys:
                if self.work_queue.shard_index(key) != shard:
                    continue
                self._mark_active(key)
                namespace, _, name = key.partition("/")
                try:
                    job = self.reads.get_job(namespace, name)
                except NotFound:
                    continue  # deleted since the key scan
                if not job.status.conditions:
                    # Admit a PRIVATE copy (the sync path's deepcopy
                    # idiom): `job` may be the informer's live cached
                    # object, and add_job mutates (defaults + Created
                    # stamp) — mutating the cache in place diverges it
                    # from the wire until a relist quietly reverts it.
                    job = job.deepcopy()
                    self.add_job(job)
                    if any(c.type == JobConditionType.CREATED
                           for c in job.status.conditions):
                        # Persist the admission verdict: nothing else
                        # writes the Created stamp for a job admitted
                        # here (the validation-reject path writes its
                        # own Failed status inside add_job).
                        try:
                            self.status_writer.write(
                                job.metadata.namespace,
                                job.metadata.name, job.status)
                        except NotFound:
                            pass
                else:
                    # Through the owns_key-gated _enqueue, not a bare
                    # work_queue.add: the lease can bounce between the
                    # key scan above and this enqueue (rebalance against
                    # a returning peer), and an unfenced add would queue
                    # a key whose shard we no longer own — the new owner
                    # re-enqueues it on ITS adoption, so dropping here is
                    # the correct half of the handoff.
                    self._enqueue(key)
        except Exception as err:  # noqa: BLE001 — next resync tick re-covers the shard
            tpulog.logger_for_key("shardlease").warning(
                "adoption enqueue of shard %d failed: %s", shard, err)

    def _on_shard_dropped(self, shard: int) -> None:
        """We no longer own `shard`: drop its queued/delayed keys (the new
        owner re-enqueues on adoption) and forget our last-written status
        snapshots — a peer may write those keys now, so our memory of the
        wire is no longer trustworthy."""
        self.work_queue.purge_shard(shard)
        self.status_writer.forget_where(
            lambda key: self.work_queue.shard_index(key) == shard)

    # ------------------------------------------------------------------

    def _on_job_event(self, etype: EventType, job: TPUJob) -> None:
        if etype == EventType.ADDED:
            if not self.owns_key(job.key()):
                # A peer owns this shard; its add_job runs admission.  If
                # the shard is ownerless right now, whoever adopts it
                # re-enqueues the key and the sync path takes over (the
                # same catch-up an operator restart gets).
                return
            self._mark_active(job.key())
            self.add_job(job)
        elif etype == EventType.MODIFIED:
            # Fingerprints are only computed for quarantined keys: the
            # baseline is captured at quarantine entry (_mark_job_stuck), so
            # the healthy steady state pays nothing for release-on-spec-change
            # despite every controller status write arriving here as MODIFIED.
            if (self.sync_health.is_quarantined(job.key())
                    and self.sync_health.observe_spec(
                        job.key(), _spec_fingerprint(job))):
                # A spec edit releases quarantine: the fixed manifest gets a
                # fresh start immediately, not after probation — including
                # the rate-limiter's backoff ladder, or the first post-edit
                # failure would requeue at near-max delay.
                self.work_queue.forget(job.key())
                tpulog.logger_for_key(job.key()).info(
                    "spec change released quarantine")
            self._mark_active(job.key())
            self._enqueue(job.key())
        elif etype == EventType.DELETED:
            # Pods/services are garbage-collected by ownership in real k8s;
            # our substrates clean up on terminal state instead.
            self._forget_key(job.key())
            with self._warned_lock:
                self._multislice_warned.discard(job.key())

    def add_job(self, job: TPUJob) -> None:
        """Admission: validate, default, stamp JobCreated, enqueue
        (ref: addTFJob, job.go:54-131)."""
        try:
            validate(job)
        except ValidationError as err:
            # Reject: write a Failed condition + warning event, do not enqueue
            # (ref: job.go:65-105).
            conditions.update_job_conditions(
                job.status, JobConditionType.FAILED, FAILED_VALIDATION_REASON, str(err)
            )
            self.cluster.record_event(
                Event(
                    object_kind=job.kind,
                    object_name=job.metadata.name,
                    namespace=job.metadata.namespace,
                    event_type="Warning",
                    reason=FAILED_VALIDATION_REASON,
                    message=str(err),
                )
            )
            try:
                self.status_writer.write(
                    job.metadata.namespace, job.metadata.name, job.status
                )
            except NotFound:
                pass
            return

        set_defaults(job)
        conditions.update_job_conditions(
            job.status,
            JobConditionType.CREATED,
            "TPUJobCreated",
            f"TPUJob {job.metadata.name} is created.",
        )
        metrics.jobs_created.labels().inc()
        self._enqueue(job.key())

    def _on_pod_event(self, etype: EventType, pod: Pod) -> None:
        key = self._owner_key(pod)
        if key is None:
            return
        rtype = pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        if etype == EventType.ADDED:
            self.expectations.creation_observed(expectation_key(key, rtype, "pods"))
        elif etype == EventType.DELETED:
            self.expectations.deletion_observed(expectation_key(key, rtype, "pods"))
        elif etype == EventType.MODIFIED:
            from ..api.core import PodPhase
            from ..runtime.reconciler import PREEMPTION_REASONS

            if (
                pod.status.phase == PodPhase.FAILED
                and pod.status.reason in PREEMPTION_REASONS
                and self.owns_key(key)
            ):
                # Preemption requeues with a clean slate: the rate-limiter
                # backoff a job accrued from its own earlier failures must
                # not delay its return to the policy queue — the eviction
                # was the scheduler's decision, not another job failure.
                self.work_queue.forget(key)
        self._mark_active(key)
        self._enqueue(key)

    def _on_service_event(self, etype: EventType, svc: Service) -> None:
        key = self._owner_key(svc)
        if key is None:
            return
        rtype = svc.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
        if etype == EventType.ADDED:
            self.expectations.creation_observed(expectation_key(key, rtype, "services"))
        elif etype == EventType.DELETED:
            self.expectations.deletion_observed(expectation_key(key, rtype, "services"))
        self._mark_active(key)
        self._enqueue(key)

    @staticmethod
    def _owner_key(obj) -> Optional[str]:
        meta = obj.metadata
        if meta.owner_kind != "TPUJob" or not meta.owner_name:
            return None
        return f"{meta.namespace}/{meta.owner_name}"

    # ------------------------------------------------------------------
    # sync loop (ref: Run/runWorker/processNextWorkItem, controller.go:186-274)

    def run(self, stop_after: Optional[float] = None) -> None:
        """Start worker threads; blocks until stop() (or stop_after seconds)."""
        self.start()
        if stop_after is not None:
            time.sleep(stop_after)
            self.stop()
        else:
            while not self._stop.is_set():
                time.sleep(0.2)

    def start(self) -> None:
        """Non-blocking run()."""
        self._started = True
        if self.informer is not None:
            self.informer.start_relist()
        if self.shard_manager is not None:
            # Synchronous first tick inside: this replica owns (and has
            # enqueued, via _on_shard_adopted) its share of the shard space
            # before the first worker pops a key.
            self.shard_manager.start()
        for i in range(self.total_workers):
            self._spawn_worker(i)
        resync = threading.Thread(target=self._resync_loop, name="tpujob-resync", daemon=True)
        resync.start()
        self._aux_threads.append(resync)
        watchdog = threading.Thread(target=self._watchdog_loop,
                                    name="tpujob-watchdog", daemon=True)
        watchdog.start()
        self._watchdog = watchdog
        self._aux_threads.append(watchdog)

    @property
    def total_workers(self) -> int:
        """Worker threads across all shards (threadiness is per shard)."""
        return self.threadiness * self.num_shards

    def shard_of_worker(self, worker_id: int) -> int:
        """Worker ids are grouped per shard: [0, threadiness) serve shard 0,
        the next `threadiness` serve shard 1, and so on — a worker never
        pulls from another shard's queue, which is the non-serialization
        guarantee sharding exists for."""
        return worker_id // self.threadiness

    def _spawn_worker(self, worker_id: int) -> None:
        thread = threading.Thread(target=self._run_worker, args=(worker_id,),
                                  name=f"tpujob-worker-{worker_id}", daemon=True)
        # Register AND start under the lock: a watchdog sweep between the
        # two would see a registered-but-unstarted thread as not alive and
        # double-spawn the worker id (two threads sharing one in-flight
        # slot).  _run_worker never takes _workers_lock, so starting while
        # holding it cannot deadlock.
        with self._workers_lock:
            self._workers[worker_id] = thread
            thread.start()

    def _resync_loop(self) -> None:
        """Periodic full resync (ref: ReconcilerSyncLoopPeriod 15s,
        common/job_controller.go:60-77): the backstop for timer-driven
        policies (TTL, ActiveDeadlineSeconds) across controller restarts.
        Under a degraded control plane the period widens (see
        _check_degraded) and list failures skip the tick instead of killing
        the thread — the resync loop must outlive any apiserver outage."""
        base = self.reconciler.config.reconciler_sync_loop_period
        while not self._stop.is_set():
            # Wake early when the watchdog requests a triggered resync
            # (stale-watch kick): the relist must NOT run on the watchdog
            # thread, where a hung apiserver would block hang detection.
            triggered = self._resync_now.wait(
                timeout=self.resync_period_current)
            if triggered:
                # Clear ONLY when the flag was observed: a watchdog set()
                # landing between a timed-out wait and an unconditional
                # clear() would be swallowed — and with the event-driven
                # backstop, a swallowed trigger downgrades the stale-watch
                # repair to a quiescent-skipping tick.  Left set, the next
                # wait() returns immediately and runs the full tick.
                self._resync_now.clear()
            if self._stop.is_set():
                break
            # Whole tick under one guard: the resync thread must never die —
            # a dead backstop silently disables TTL/deadline policies AND
            # the degraded-mode detection that matters most mid-outage.
            try:
                factor = (DEGRADED_RESYNC_FACTOR if self._check_degraded()
                          else 1.0)
                self.resync_period_current = base * factor
                # Each resync tick grants every quarantined key one probe:
                # the tick's enqueue below delivers it to a worker, which
                # admits exactly one sync attempt (controller/health.py).
                self.sync_health.grant_probes()
                # Event-driven backstop: most ticks skip quiescent keys —
                # jobs whose last sync verifiably did nothing and which
                # hold no pending timer — so the steady-state cost of an
                # idle job is zero syncs per tick.  Every Nth tick (and
                # every watchdog-triggered one: those exist to repair lost
                # events, which is exactly what quiescence cannot see)
                # enqueues everything.
                self._resync_tick += 1
                every = self.healing.full_resync_every
                full = (triggered or every <= 1
                        or self._resync_tick % every == 0)
                # The relist comes from the informer store when one runs:
                # at 5k jobs a per-tick wire LIST is exactly the traffic
                # the cache exists to collapse, and the informer's own
                # relist loop keeps the store honest on its own cadence.
                for job in self.reads.list_jobs():
                    key = job.key()
                    if full or not self._is_quiescent(key):
                        self._enqueue(key)
            except Exception as err:  # noqa: BLE001 — transient; next tick retries
                tpulog.logger_for_key("resync").warning(
                    "resync tick failed: %s", err)

    def _check_degraded(self) -> bool:
        """Poll the substrate's ClientHealth (duck-typed; absent on
        in-memory substrates => never degraded).  Emits ClusterDegraded
        exactly once per episode; recovery is logged and re-arms the
        event for the next episode."""
        health = getattr(self.cluster, "health", None)
        if health is None:
            return False
        degraded = health.degraded()
        if degraded and not self._degraded:
            self._degraded = True
            tpulog.logger_for_key("resync").warning(
                "control plane degraded: %d consecutive request giveups; "
                "widening resync period x%g",
                health.consecutive_giveups, DEGRADED_RESYNC_FACTOR)
            # Best-effort by record_event contract: a failed write while
            # degraded must not abort the resync loop.  Target the
            # cluster's own namespace — a namespace-scoped deployment has
            # no RBAC to write events into "default".
            namespace = (getattr(self.cluster, "namespace", None)
                         or getattr(getattr(self.cluster, "config", None),
                                    "namespace", None)
                         or "default")
            self.cluster.record_event(Event(
                object_kind="TPUJob",
                object_name=CONTROLLER_NAME,
                namespace=namespace,
                event_type="Warning",
                reason="ClusterDegraded",
                message=(
                    f"{health.consecutive_giveups} consecutive apiserver "
                    f"request giveups; resync period widened "
                    f"x{DEGRADED_RESYNC_FACTOR:g} until the control plane "
                    "recovers"),
            ))
        elif not degraded and self._degraded:
            self._degraded = False
            tpulog.logger_for_key("resync").info(
                "control plane recovered; resync period restored")
        return degraded

    def stop(self) -> None:
        self._stop.set()
        self._resync_now.set()  # wake the resync loop out of its period wait
        if self.shard_manager is not None:
            # Graceful handoff: release our shard leases so survivors adopt
            # immediately instead of waiting out the lease duration.  (A
            # crash-stopped manager — stop(release=False) already called —
            # keeps crash semantics; this second stop is a no-op.)
            self.shard_manager.stop(release=True)
        if self.informer is not None:
            self.informer.stop()
        self.work_queue.shutdown()
        with self._workers_lock:
            workers = list(self._workers.values())
        for t in workers + self._aux_threads:
            t.join(timeout=5)

    def _run_worker(self, worker_id: int) -> None:
        shard = self.shard_of_worker(worker_id)
        shard_queue = self.work_queue.shard(shard)
        while not self._stop.is_set():
            try:
                key = shard_queue.get(timeout=0.5)
            except ShutDown:
                return
            except TimeoutError:
                continue
            if (self.shard_manager is not None
                    and not self.shard_manager.owns(shard)):
                # Ownership fence at the last possible moment: the lease
                # was lost (or never re-acquired) between enqueue and pop.
                # Absorb the key — the current owner re-enqueued the whole
                # shard on adoption, so nothing is lost, and syncing here
                # would be the doubly-owned split brain the leases prevent.
                shard_queue.done(key)
                continue
            try:
                if not self.sync_health.admit(key):
                    # Quarantined with no probe due: absorb the enqueue,
                    # then re-arm the probation wakeup.  The re-arm matters:
                    # the delayed-delivery queue keeps only the EARLIEST
                    # pending deadline per key, so the original probation
                    # arm may have been coalesced away by a sooner delivery
                    # (a TTL/deadline re-arm) — the one being absorbed right
                    # now.  Without this, a parked key could end up with no
                    # scheduled delivery at all and recovery would wait on
                    # the resync backstop, which degraded mode widens
                    # exactly when quarantines are most likely.
                    self.work_queue.add_after(
                        key, self.healing.quarantine_probation)
                    continue
                self.sync_health.record_sync_start(worker_id, key)
                synced = self.sync_job(key)
                self.work_queue.forget(key)
                # Only a sync that actually ran a reconcile (not one gated
                # by unsatisfied expectations, which does zero work) counts
                # as the success that resets failure streaks and releases
                # quarantine/Stuck.
                if synced and self.sync_health.record_sync_success(key):
                    self._clear_stuck_condition(key)
            except Exception as err:  # noqa: BLE001 — sync errors requeue with backoff
                # A failing key is never quiescent: the resync backstop
                # must keep seeing it even if an older pass marked it idle.
                self._mark_active(key)
                action = self.sync_health.record_sync_failure(key, str(err))
                tpulog.logger_for_key(key).warning("sync failed: %s", err)
                if action == ACTION_REQUEUE:
                    self.work_queue.add_rate_limited(key)
                else:
                    if action == ACTION_QUARANTINED:
                        self._mark_job_stuck(key, str(err))
                    # Parked either way: the only scheduled retry is the
                    # probation-expiry probe (resync ticks may come sooner).
                    self.work_queue.add_after(
                        key, self.healing.quarantine_probation)
            finally:
                # In-flight until ALL per-key work is done, including the
                # Stuck marker/clear writes above: those hit the same
                # apiserver the sync just failed against, and a hang there
                # must be as visible to the watchdog as a hang in sync_job.
                self.sync_health.record_sync_end(worker_id)
                self.work_queue.done(key)

    def sync_job(self, key: str) -> bool:
        """One reconcile pass for `key` (ref: syncTFJob, controller.go:290-334).
        Returns True if a reconcile ran (expectations satisfied)."""
        start = time.monotonic()
        try:
            return self._sync_job(key)
        finally:
            # Per-sync latency log (ref: controller.go:291-295).
            tpulog.logger_for_key(key).debug(
                "finished syncing tpujob (%.1f ms)", (time.monotonic() - start) * 1e3
            )

    def _sync_job(self, key: str) -> bool:
        namespace, _, name = key.partition("/")
        try:
            # Informer read: the steady-state sync costs the apiserver
            # nothing.  A miss falls back to the wire inside the cache, so
            # NotFound still means the job is really gone.
            job = self.reads.get_job(namespace, name)
        except NotFound:
            # The job is gone: release every per-key residue — expectations,
            # rate-limiter backoff state, status-writer snapshot, and any
            # quarantine — or the maps grow one dead entry per deleted job
            # for the process lifetime.
            self._forget_key(key)
            return True

        job = job.deepcopy()
        set_defaults(job)

        # Sync gate: only act on a caught-up view — unless dynamic workers
        # force every-loop syncs (ref: controller.go:319).
        if not (self.satisfied_expectations(job) or job.spec.enable_dynamic_worker):
            return False

        result = self.reconciler.reconcile_job(job)
        if result.requeue_after is not None:
            self.work_queue.add_after(key, result.requeue_after)
        self._note_pass(key, job, result)
        return True

    def satisfied_expectations(self, job: TPUJob) -> bool:
        """(ref: satisfiedExpectations, controller.go:339-358)"""
        key = job.key()
        return all(
            self.expectations.satisfied(expectation_key(key, rtype.value, kind))
            for rtype in job.spec.replica_specs
            for kind in ("pods", "services")
        )

    # ------------------------------------------------------------------
    # self-healing: quarantine surfacing + the watchdog
    # (controller/health.py holds the state; docs/self-healing.md the story)

    def _mark_job_stuck(self, key: str, error: str) -> None:
        """Surface a fresh quarantine on the TPUJob itself: a Warning event
        plus a Stuck=True condition.  Both best-effort — the job's sync is
        already failing, and the marker must not take the worker down."""
        namespace, _, name = key.partition("/")
        failures = self.sync_health.failures(key)
        message = (
            f"sync failed {failures} consecutive times; quarantined with "
            f"{self.healing.quarantine_probation:.0f}s probation (released "
            f"early on spec change or resync probe): {error}")
        try:
            self.cluster.record_event(Event(
                object_kind="TPUJob",
                object_name=name,
                namespace=namespace,
                event_type="Warning",
                reason=JOB_STUCK_REASON,
                message=message,
            ))
            # Wire read, NOT the informer: this is a read-modify-write of
            # status on a rare event, and a cache that hasn't seen our own
            # recent writes yet would silently clobber them.  deepcopy
            # before mutating, like _sync_job: InMemoryCluster returns the
            # live stored object, and a torn in-place condition write would
            # race concurrent workers (and leak state on a failed
            # update_job_status).
            job = self.cluster.get_job(namespace, name).deepcopy()
            # Baseline for release-on-spec-change: MODIFIED events only
            # compare fingerprints for quarantined keys, against this.
            self.sync_health.set_spec_baseline(key, _spec_fingerprint(job))
            # set_operational_condition, not update_job_conditions: the
            # sticky-Failed rule would silently drop Stuck on a job that
            # already failed, and a failed job's cleanup sync can be
            # exactly what is quarantining.
            conditions.set_operational_condition(
                job.status, JobConditionType.STUCK, JOB_STUCK_REASON, message)
            self.status_writer.write(namespace, name, job.status)
        except NotFound:
            self.sync_health.forget(key)
        except Exception as err:  # noqa: BLE001 — marker is best-effort
            tpulog.logger_for_key(key).warning(
                "could not write Stuck condition: %s", err)

    def _clear_stuck_condition(self, key: str) -> None:
        """Retract Stuck=True after the first successful sync of a
        previously quarantined job (best-effort, like the marker)."""
        namespace, _, name = key.partition("/")
        try:
            # Wire read for the same reason as _mark_job_stuck: a cache
            # that predates our own Stuck write would report the condition
            # absent and this retraction would silently never happen.
            job = self.cluster.get_job(namespace, name).deepcopy()
            if conditions.clear_condition(
                    job.status, JobConditionType.STUCK, JOB_RECOVERED_REASON,
                    "sync succeeded; quarantine released"):
                self.status_writer.write(namespace, name, job.status)
        except NotFound:
            pass
        except Exception as err:  # noqa: BLE001 — marker is best-effort
            tpulog.logger_for_key(key).warning(
                "could not clear Stuck condition: %s", err)

    def _watchdog_loop(self) -> None:
        """The `tpujob-watchdog` monitor: respawns dead workers, flags hung
        syncs, force-reconnects stale watches, and keeps the self-healing
        gauges fresh.  Every tick is guarded — the watchdog outliving its
        own sweep errors is the whole point of having one."""
        logged_stuck: set = set()  # (worker, key) pairs already warned
        while not self._stop.wait(timeout=self.healing.watchdog_interval):
            try:
                self._watchdog_tick(logged_stuck)
            except Exception as err:  # noqa: BLE001 — monitor must outlive any tick
                tpulog.logger_for_key("watchdog").warning(
                    "watchdog tick failed: %s", err)

    def _watchdog_tick(self, logged_stuck: set) -> None:
        log = tpulog.logger_for_key("watchdog")
        # 1. Respawn dead workers.  A sync that escapes the broad handler
        # (SystemExit, MemoryError, a C-extension abort surfaced as a
        # BaseException) kills its thread; without respawn the controller
        # silently loses 1/N of its throughput per incident.
        with self._workers_lock:
            dead = [(i, t) for i, t in self._workers.items()
                    if not t.is_alive()]
        for worker_id, _thread in dead:
            if self._stop.is_set():
                break
            log.warning("worker %d died; respawning", worker_id)
            with self._workers_lock:
                self._worker_restarts += 1
            metrics.worker_restarts.labels().inc()
            self._spawn_worker(worker_id)

        # 2. Hung syncs: flag in-flight syncs past the deadline.  The sync
        # itself cannot be aborted safely (it may hold the reconcile's
        # half-applied writes) — the watchdog's job is to make the hang
        # loudly observable (metrics + not-ready) rather than silent.
        stuck = self.sync_health.stuck_syncs()
        metrics.stuck_syncs.labels().set(float(len(stuck)))
        metrics.stuck_sync_age.labels().set(
            max((s["age_seconds"] for s in stuck), default=0.0))
        current = {(s["worker"], s["key"]) for s in stuck}
        for entry in stuck:
            pair = (entry["worker"], entry["key"])
            if pair not in logged_stuck:
                log.warning(
                    "sync of %s on worker %d stuck for %.1fs (deadline %.1fs)",
                    entry["key"], entry["worker"], entry["age_seconds"],
                    self.healing.stuck_sync_deadline)
        logged_stuck.clear()
        logged_stuck.update(current)

        # 3. Watch staleness (duck-typed: only the k8s substrate has
        # heartbeats).  A kicked watch reconnects and relists on its own;
        # the triggered resync below re-enqueues every job so anything the
        # dead stream swallowed is reconciled immediately, not at the next
        # resync tick.
        kick = getattr(self.cluster, "kick_stale_watches", None)
        if kick is not None:
            stale = kick(self.healing.watch_stale_deadline)
            if stale:
                log.warning(
                    "stale watches %s force-reconnected; triggering resync",
                    stale)
                # Delegate the relist to the resync thread: a stale watch
                # usually means the apiserver is misbehaving, and a blocking
                # list_jobs() here would wedge the watchdog itself through
                # the client's whole retry budget.  The informer store gets
                # the same treatment: whatever events the blind stream
                # swallowed are repaired on ITS thread, immediately, not at
                # the next relist period.
                if self.informer is not None:
                    self.informer.relist_soon()
                self._resync_now.set()

        # 4. Gauges the report and /metrics share.  tpujob_queue_depth stays
        # the fleet aggregate; per-shard depth and enqueue->dequeue latency
        # quantiles land on the sharded gauges.
        stats = self.work_queue.stats()
        metrics.queue_depth.labels().set(float(stats["depth"]))
        for index, shard_stats in enumerate(stats["shards"]):
            metrics.queue_shard_depth.labels(str(index)).set(
                float(shard_stats["depth"]))
            for quantile, value in shard_stats["latency"].items():
                metrics.queue_latency.labels(str(index), quantile).set(value)
        metrics.quarantined_jobs.labels().set(
            float(self.sync_health.quarantine_count()))

    # ------------------------------------------------------------------
    # deep health (served by /healthz on both HTTP surfaces)

    def health_report(self, standby_ok: bool = False) -> dict:
        """Aggregated self-health: the JSON `/healthz` serves.  `live` means
        the control loop can still make progress (or the watchdog can
        restore it); `ready` means it is currently healthy on every axis —
        workers, in-flight syncs, watch freshness, and substrate health.
        `standby_ok=True` (set by the server when leader election is on)
        makes a deliberately not-started replica report ready: a standby
        waiting for the lease is healthy by design and must not break the
        Deployment's readiness rollout."""
        stopped = self._stop.is_set()
        with self._workers_lock:
            workers = dict(self._workers)
            restarts = self._worker_restarts
        alive = sum(1 for t in workers.values() if t.is_alive())
        standby = standby_ok and not self._started and not stopped
        reasons: List[str] = []
        if not self._started and not standby:
            reasons.append("not-started: controller workers not running yet")
        if stopped:
            reasons.append("stopped: controller is shutting down")
        if self._started and alive < self.total_workers:
            reasons.append(f"workers: {alive}/{self.total_workers} alive")

        stuck = self.sync_health.stuck_syncs()
        for entry in stuck:
            reasons.append(
                f"stuck-sync: {entry['key']} on worker {entry['worker']} "
                f"for {entry['age_seconds']:.1f}s "
                f"(deadline {self.healing.stuck_sync_deadline:.1f}s)")

        watches: Dict[str, dict] = {}
        ages = getattr(self.cluster, "watch_ages", None)
        if ages is not None:
            for watch_key, age in ages().items():
                is_stale = age > self.healing.watch_stale_deadline
                watches[watch_key] = {
                    "age_seconds": round(age, 3), "stale": is_stale,
                }
                if is_stale:
                    reasons.append(
                        f"watch: {watch_key} stale for {age:.1f}s")

        degraded_report = None
        substrate_health = getattr(self.cluster, "health", None)
        if substrate_health is not None:
            is_degraded = substrate_health.degraded()
            degraded_report = {
                "degraded": is_degraded,
                "consecutive_giveups": substrate_health.consecutive_giveups,
                "episodes": getattr(substrate_health, "episodes", 0),
            }
            if is_degraded:
                reasons.append(
                    "degraded: apiserver client in giveup backoff "
                    f"({substrate_health.consecutive_giveups} consecutive)")

        quarantine = self.sync_health.report()
        watchdog_alive = self._watchdog.is_alive() if self._watchdog else False
        live = not stopped and (not self._started
                                or alive > 0 or watchdog_alive)
        ready = (self._started or standby) and not stopped and not reasons
        return {
            # Legacy key: pre-upgrade SDK clients check status == "ok", so a
            # ready server must keep answering it or old pollers read an
            # upgraded healthy operator as down forever.
            "status": "ok" if ready else "not-ready",
            "live": live,
            "ready": ready,
            "standby": standby,
            "reasons": reasons,
            "timestamp": clock.now(),
            "workers": {
                "expected": self.total_workers,
                "per_shard": self.threadiness,
                "alive": alive,
                "restarts": restarts,
                "watchdog_alive": watchdog_alive,
            },
            # Aggregate queue keys keep their pre-sharding shape; the
            # per-shard breakdown (depth/backoff/latency quantiles per
            # shard) rides along under queue.shards.
            "queue": dict(self.work_queue.stats(),
                          quarantined=quarantine["count"],
                          num_shards=self.num_shards),
            "informer": (self.informer.report()
                         if self.informer is not None else None),
            "federation": (self.shard_manager.report()
                           if self.shard_manager is not None else None),
            "status_writer": self.status_writer.counters(),
            "syncs": {
                "in_flight_stuck": stuck,
                "stuck_sync_deadline_seconds": self.healing.stuck_sync_deadline,
            },
            "watches": watches,
            "degraded": degraded_report,
            "quarantine": quarantine,
            "resync_period_seconds": self.resync_period_current,
        }

    # ------------------------------------------------------------------
    # JobPlugin hooks

    def set_cluster_spec(self, job: TPUJob, pod: Pod, rtype: ReplicaType, index: int) -> None:
        def warn(reason: str, message: str) -> None:
            # One Warning Event per job, not one per pod per resync: the
            # condition is a property of the spec, which is immutable for
            # a given generation of pod creations.
            with self._warned_lock:
                if job.key() in self._multislice_warned:
                    return
                self._multislice_warned.add(job.key())
            self.cluster.record_event(Event(
                object_kind=job.kind,
                object_name=job.metadata.name,
                namespace=job.metadata.namespace,
                event_type="Warning",
                reason=reason,
                message=message,
            ))

        topology.set_cluster_spec(job, pod, rtype, index, self.resolver, warn)

    def is_master_role(
        self, replicas: Dict[ReplicaType, ReplicaSpec], rtype: ReplicaType, index: int
    ) -> bool:
        """Chief/Master pod if declared, else worker-0
        (ref: controller.go:409-416)."""
        if any(rt in (ReplicaType.CHIEF, ReplicaType.MASTER) for rt in replicas):
            return rtype in (ReplicaType.CHIEF, ReplicaType.MASTER)
        return rtype == ReplicaType.WORKER and index == 0

    def update_job_status(self, job: TPUJob, replicas, status, pods, restarting_this_pass) -> None:
        status_engine.update_job_status(
            job,
            replicas,
            status,
            pods,
            restarting_this_pass=restarting_this_pass,
            record_event=self.cluster.record_event,
            on_start_time_set=lambda deadline: self.work_queue.add_after(job.key(), deadline),
        )

    def on_pod_created(self, job: TPUJob, rtype: ReplicaType) -> None:
        pass

    @property
    def gang_scheduler(self):
        return self._gang_scheduler

    @gang_scheduler.setter
    def gang_scheduler(self, scheduler) -> None:
        """Attaching the gang scheduler also subscribes to its slice
        provider's fabric events: a REPAIR is new capacity, and re-growing
        an elastic job is a job-sync decision (_reconcile_elastic), so the
        affected jobs must be requeued — without this the grow waits for
        the periodic resync backstop (minutes on a quiet cluster).  The
        scheduler's own watcher handles the preemption side by failing the
        slice's pods, which requeues via the pod watch."""
        self._gang_scheduler = scheduler
        # Shard-ownership gate for the scheduler's admit/evict decisions:
        # the adopting controller lends its owns_key, so a federated
        # deployment's scheduler only arbitrates gangs of shards this
        # replica holds.  First adopter wins — an explicitly configured
        # gate (e.g. a shared scheduler in tests) is never overwritten.
        if getattr(scheduler, "owns_gang", True) is None:
            scheduler.owns_gang = self.owns_key
        provider = getattr(scheduler, "slice_provider", None)
        if provider is not None:
            provider.watch(self._on_slice_repaired)

    def _on_slice_repaired(self, slc, event: str) -> None:
        if event != "repaired":
            return
        from ..api.types import is_elastic

        try:
            jobs = self.cluster.list_jobs()
        except Exception:  # noqa: BLE001 — a fabric event must never die here
            log.warning("slice %s repaired: listing jobs for elastic "
                        "requeue failed", slc.id)
            return
        for job in jobs:
            if is_elastic(job) and not conditions.is_finished(job.status):
                self._mark_active(job.key())
                self._enqueue(job.key())

    def usable_slice_hosts(self, job: TPUJob, accelerator: str,
                           topology: str):
        """Host capacity an elastic group of this slice shape could run on:
        hosts of FREE slices plus hosts of slices this job's gang already
        holds (the gang key is namespace/name, the slice holder string the
        scheduler allocates under).  None when no slice provider is wired —
        the elastic engine then never grows."""
        provider = getattr(
            getattr(self, "gang_scheduler", None), "slice_provider", None
        )
        if provider is None:
            return None
        from ..runtime.slices import SliceState, normalize_topology

        shape_topology = normalize_topology(topology)
        key = job.key()
        hosts = 0
        for s in provider.list_slices():
            if s.accelerator != accelerator or s.topology != shape_topology:
                continue
            if s.state == SliceState.FREE or (
                s.state == SliceState.ALLOCATED and s.holder == key
            ):
                hosts += s.hosts
        return hosts
