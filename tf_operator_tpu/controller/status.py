"""TPUJob status engine: the success/failure condition matrix.

Faithful re-implementation of the reference's UpdateJobStatus
(/root/reference/pkg/controller.v1/tensorflow/status.go:57-204), which is the
most test-covered contract in the reference (~30 unit cases + 3 E2E suites):

  - replica types evaluated in fixed order Chief, Evaluator, Master, PS, Worker
  - with a Chief/Master spec: chief running → JobRunning; chief expected==0
    (all chief replicas succeeded) → JobSucceeded
  - without: all workers done → JobSucceeded; worker-0 done → JobSucceeded
    unless SuccessPolicy=AllWorkers; any worker running → JobRunning
  - failed>0 → JobFailed with CompletionTime, unless a Restarting condition
    exists (the restart cycle owns the status then)
"""
from __future__ import annotations

from typing import Dict

from ..api.core import Event, PodPhase
from ..api.types import (
    REPLICA_TYPE_ORDER,
    JobConditionType,
    JobStatus,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    SuccessPolicy,
    TPUJob,
    contains_chief_or_master,
    effective_replicas,
    is_chief_or_master,
)
from ..runtime import conditions
from ..runtime.reconciler import (
    filter_for_replica_type,
    get_container_exit_code,
    get_pod_slices,
)
from ..utils import clock, metrics

JOB_RUNNING_REASON = "TPUJobRunning"
JOB_SUCCEEDED_REASON = "TPUJobSucceeded"
JOB_FAILED_REASON = "TPUJobFailed"
JOB_RESTARTING_REASON = "JobRestarting"


def is_worker0_completed(job: TPUJob, pods) -> bool:
    """Worker-0 pod Succeeded with exit code 0 (ref: IsWorker0Completed,
    pod.go:350-366)."""
    rspec = job.spec.replica_specs.get(ReplicaType.WORKER)
    if rspec is None:
        return False
    worker_pods = filter_for_replica_type(pods, ReplicaType.WORKER)
    slices = get_pod_slices(worker_pods, int(rspec.replicas or 0))
    for index, pod_slice in enumerate(slices):
        if index == 0 and len(pod_slice) == 1:
            pod = pod_slice[0]
            if pod.status.phase == PodPhase.SUCCEEDED and get_container_exit_code(pod) == 0:
                return True
    return False


def update_job_status(
    job: TPUJob,
    replicas: Dict[ReplicaType, ReplicaSpec],
    status: JobStatus,
    pods,
    restarting_this_pass: bool = False,
    record_event=None,
    on_start_time_set=None,
) -> None:
    """Compute conditions from replica statuses (ref: status.go:57-204).

    `record_event(event)` and `on_start_time_set(deadline_seconds)` are
    optional hooks: the latter re-arms the ActiveDeadlineSeconds sync
    (ref: status.go:78-86 WorkQueue.AddAfter).

    Deliberate divergence from the reference: the reference decides
    "restart owns the status" by re-reading the Restarting *condition*
    after possibly setting Running for the same replica type
    (status.go:168-180).  That both fails jobs whose sibling workers are
    still Running during a retryable restart (Running removed Restarting
    first), and — read across syncs — permanently swallows later permanent
    failures while a stale Restarting condition lingers.  We use the
    per-sync `restarting_this_pass` signal from the reconcile pass instead:
    a restart suppresses JobFailed only in the pass that performed it."""
    worker0_completed = is_worker0_completed(job, pods)

    if status.start_time is None:
        status.start_time = clock.now()
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is not None and on_start_time_set is not None:
            on_start_time_set(deadline)

    has_chief = contains_chief_or_master(job)

    for rtype in REPLICA_TYPE_ORDER:
        rspec = replicas.get(rtype)
        if rspec is None:
            continue
        rs = status.replica_statuses.get(rtype.value, ReplicaStatus())
        # An elastic group runs (and therefore completes) at its PHYSICAL
        # width, not the virtual spec width (docs/elasticity.md).
        if rspec.elastic is not None:
            expected = effective_replicas(job, rtype) - rs.succeeded
        else:
            expected = int(rspec.replicas or 0) - rs.succeeded
        running = rs.active
        failed = rs.failed

        if has_chief:
            if is_chief_or_master(rtype):
                if running > 0:
                    conditions.update_job_conditions(
                        status,
                        JobConditionType.RUNNING,
                        JOB_RUNNING_REASON,
                        f"TPUJob {job.metadata.name} is running.",
                    )
                if expected == 0:
                    _mark_succeeded(job, status, record_event)
        else:
            if rtype == ReplicaType.WORKER:
                all_done = expected == 0
                w0_done = (
                    worker0_completed
                    and job.spec.success_policy != SuccessPolicy.ALL_WORKERS
                )
                if all_done or w0_done:
                    _mark_succeeded(job, status, record_event)
                elif running > 0:
                    conditions.update_job_conditions(
                        status,
                        JobConditionType.RUNNING,
                        JOB_RUNNING_REASON,
                        f"TPUJob {job.metadata.name} is running.",
                    )

        if failed > 0:
            # A restart performed this pass hands ownership of the status to
            # the restart cycle (ref: status.go:168-195; divergence note in
            # the docstring).
            if restarting_this_pass:
                pass  # jobs_restarted already counted by the reconcile pass
            else:
                msg = (
                    f"TPUJob {job.metadata.name} has failed because "
                    f"{failed} {rtype.value} replica(s) failed."
                )
                if record_event is not None:
                    record_event(
                        Event(
                            object_kind=job.kind,
                            object_name=job.metadata.name,
                            namespace=job.metadata.namespace,
                            event_type="Normal",
                            reason=JOB_FAILED_REASON,
                            message=msg,
                        )
                    )
                if status.completion_time is None:
                    status.completion_time = clock.now()
                newly_failed = not conditions.is_failed(status)
                conditions.update_job_conditions(
                    status, JobConditionType.FAILED, JOB_FAILED_REASON, msg
                )
                if newly_failed:
                    metrics.jobs_failed.labels().inc()


def _mark_succeeded(job: TPUJob, status: JobStatus, record_event) -> None:
    msg = f"TPUJob {job.metadata.name} successfully completed."
    if record_event is not None:
        record_event(
            Event(
                object_kind=job.kind,
                object_name=job.metadata.name,
                namespace=job.metadata.namespace,
                event_type="Normal",
                reason=JOB_SUCCEEDED_REASON,
                message=msg,
            )
        )
    if status.completion_time is None:
        status.completion_time = clock.now()
    newly_succeeded = not conditions.is_succeeded(status)
    conditions.update_job_conditions(
        status, JobConditionType.SUCCEEDED, JOB_SUCCEEDED_REASON, msg
    )
    if newly_succeeded:
        metrics.jobs_successful.labels().inc()
