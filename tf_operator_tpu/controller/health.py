"""Self-healing bookkeeping for the controller (docs/self-healing.md).

The reference operator trusts client-go and the informer machinery to keep
its control loop alive; its own failure modes — a job whose sync always
throws, a sync that hangs on a wedged RPC, a worker thread that dies — are
invisible and unhandled.  On preemptible TPU slices a wedged reconcile loop
idles an entire slice (PAPERS.md: "Exploring the limits of Concurrency in ML
Training on Google TPUs"), so this module makes those modes first-class
state the `tpujob-watchdog` thread and the deep `/healthz` report act on:

  - **poison-job quarantine**: after `quarantine_threshold` consecutive sync
    failures a key is parked out of the hot queue.  While parked, enqueues
    are absorbed without a sync; one probe is granted per resync tick and on
    probation expiry, and a spec change releases the key entirely — so a
    poison job costs one sync attempt per resync period instead of an
    endless rate-limited requeue stream, and one bad job can never starve
    the queue.
  - **in-flight sync tracking**: workers register (key, start) around every
    sync so the watchdog can flag syncs past `stuck_sync_deadline` and the
    health report can show exactly which key is wedged on which worker.
  - **bounded sync-error detail**: the last error per failing key (capped at
    `sync_errors_cap`, cleared on success/deletion) for the health report.

All state lives behind one leaf lock: no method calls out to the cluster,
queue, or metrics while holding it, so the self-healing layer cannot join a
lock cycle with the substrate (docs/static-analysis.md lock discipline).
"""
from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..utils import locks

# record_sync_failure outcomes: the controller's requeue decision.
ACTION_REQUEUE = "requeue"          # below threshold: normal rate-limited requeue
ACTION_QUARANTINED = "quarantined"  # just crossed the threshold: park + mark
ACTION_PARKED = "parked"            # probe failed: stay parked until next probe


@dataclass
class SelfHealingConfig:
    """Tuning knobs for the self-healing layer (docs/self-healing.md)."""

    # consecutive sync failures before a key is quarantined
    quarantine_threshold: int = 5
    # seconds a quarantined key waits before an expiry-driven probe;
    # resync ticks and spec changes release/probe earlier
    quarantine_probation: float = 60.0
    # an in-flight sync older than this is reported stuck (and not-ready)
    stuck_sync_deadline: float = 60.0
    # a watch stream with no event/heartbeat for this long is force-reconnected
    watch_stale_deadline: float = 300.0
    # watchdog sweep period
    watchdog_interval: float = 1.0
    # bound on the per-key last-sync-error detail map
    sync_errors_cap: int = 64
    # Event-driven resync backstop cadence: every Nth tick enqueues ALL
    # jobs; the ticks in between skip keys whose last sync was a verified
    # no-op (quiescent), so an idle job costs zero syncs and zero writes
    # per backstop tick.  1 restores the classic enqueue-everything tick;
    # watchdog-triggered resyncs (stale-watch repair) are always full.
    full_resync_every: int = 4


@dataclass
class _Quarantine:
    since: float          # monotonic entry time (this episode)
    until: float          # monotonic probation expiry for the next probe
    failures: int
    probe_granted: bool = False


class SyncHealth:
    """Quarantine + in-flight-sync + sync-error state, behind one leaf lock."""

    def __init__(self, config: Optional[SelfHealingConfig] = None) -> None:
        self.config = config or SelfHealingConfig()
        self._lock = locks.new_lock("sync-health")
        self._failures: Dict[str, int] = {}  # guarded-by: _lock
        self._quarantine: Dict[str, _Quarantine] = {}  # guarded-by: _lock
        # keys whose TPUJob carries a Stuck=True condition we still owe a clear
        self._stuck_marked: Set[str] = set()  # guarded-by: _lock
        # spec fingerprint per quarantined job (release-on-spec-change);
        # baseline set at quarantine entry, dropped on release
        self._spec_fps: Dict[str, str] = {}  # guarded-by: _lock
        # key -> last sync error, newest last, bounded at sync_errors_cap
        self._sync_errors: "OrderedDict[str, str]" = OrderedDict()  # guarded-by: _lock
        # worker id -> (key, monotonic start) for the sync it is running now
        self._in_flight: Dict[int, Tuple[str, float]] = {}  # guarded-by: _lock

    # ------------------------------------------------------------------
    # quarantine state machine

    def admit(self, key: str) -> bool:
        """Should a worker that just popped `key` actually sync it?  True
        for healthy keys; for quarantined keys True only when a probe is
        due (granted by a resync tick, a spec change, or probation expiry)
        — consuming the probe and re-arming the probation timer."""
        with self._lock:
            q = self._quarantine.get(key)
            if q is None:
                return True
            now = time.monotonic()
            if q.probe_granted or now >= q.until:
                q.probe_granted = False
                q.until = now + self.config.quarantine_probation
                return True
            return False

    def record_sync_failure(self, key: str, error: str) -> str:
        """Count a failed sync; returns the requeue action for the caller
        (ACTION_REQUEUE / ACTION_QUARANTINED / ACTION_PARKED)."""
        with self._lock:
            n = self._failures.get(key, 0) + 1
            self._failures[key] = n
            self._sync_errors[key] = error
            self._sync_errors.move_to_end(key)
            while len(self._sync_errors) > self.config.sync_errors_cap:
                self._sync_errors.popitem(last=False)
            q = self._quarantine.get(key)
            if q is not None:
                q.failures = n
                return ACTION_PARKED
            if n >= self.config.quarantine_threshold:
                now = time.monotonic()
                self._quarantine[key] = _Quarantine(
                    since=now, until=now + self.config.quarantine_probation,
                    failures=n)
                self._stuck_marked.add(key)
                return ACTION_QUARANTINED
            return ACTION_REQUEUE

    def record_sync_success(self, key: str) -> bool:
        """Clear all failure state for `key`; returns True when the job
        carries a Stuck condition the controller should now retract."""
        with self._lock:
            self._failures.pop(key, None)
            self._sync_errors.pop(key, None)
            self._quarantine.pop(key, None)
            self._spec_fps.pop(key, None)
            was_marked = key in self._stuck_marked
            self._stuck_marked.discard(key)
            return was_marked

    def grant_probes(self) -> List[str]:
        """A resync tick grants every quarantined key one probe; returns the
        granted keys so the caller can log/observe."""
        with self._lock:
            for q in self._quarantine.values():
                q.probe_granted = True
            return list(self._quarantine)

    def set_spec_baseline(self, key: str, fingerprint: str) -> None:
        """Record the quarantine-entry spec fingerprint later MODIFIED
        events compare against (no probe, no release — this is the
        reference point, not an observation)."""
        with self._lock:
            self._spec_fps[key] = fingerprint

    def observe_spec(self, key: str, fingerprint: str) -> bool:
        """Track the job's spec fingerprint.  Only called for quarantined
        keys (the baseline is captured at quarantine entry, subsequent
        MODIFIED events compare against it), so the map stays as small as
        the quarantine itself.  A changed spec releases the quarantine (the
        operator's contract: a fixed manifest gets a fresh start
        immediately, not after probation) and returns True.

        A quarantined key with NO baseline means the entry-time get_job
        failed (best-effort) — this MODIFIED could itself be the user's
        fixing edit, so grant a probe: one immediate sync attempt instead
        of waiting out the resync tick, without the unbounded-release risk
        of treating every baseline-less event as an edit."""
        with self._lock:
            previous = self._spec_fps.get(key)
            self._spec_fps[key] = fingerprint
            q = self._quarantine.get(key)
            if q is None:
                return False
            if previous is None:
                q.probe_granted = True
                return False
            if previous != fingerprint:
                self._quarantine.pop(key)
                self._failures.pop(key, None)
                self._spec_fps.pop(key, None)
                # The pre-edit error is no longer this spec's error; keep
                # _stuck_marked so the first success still retracts the
                # condition.
                self._sync_errors.pop(key, None)
                return True
            return False

    def forget(self, key: str) -> None:
        """Drop every trace of `key` (job deleted / NotFound)."""
        with self._lock:
            self._failures.pop(key, None)
            self._quarantine.pop(key, None)
            self._stuck_marked.discard(key)
            self._spec_fps.pop(key, None)
            self._sync_errors.pop(key, None)

    def is_quarantined(self, key: str) -> bool:
        with self._lock:
            return key in self._quarantine

    def quarantine_count(self) -> int:
        with self._lock:
            return len(self._quarantine)

    def failures(self, key: str) -> int:
        with self._lock:
            return self._failures.get(key, 0)

    # ------------------------------------------------------------------
    # in-flight sync tracking (the watchdog's raw material)

    def record_sync_start(self, worker_id: int, key: str) -> None:
        with self._lock:
            self._in_flight[worker_id] = (key, time.monotonic())

    def record_sync_end(self, worker_id: int) -> None:
        with self._lock:
            self._in_flight.pop(worker_id, None)

    def stuck_syncs(self, deadline: Optional[float] = None) -> List[dict]:
        """In-flight syncs older than `deadline` (default: the configured
        stuck_sync_deadline), oldest first."""
        if deadline is None:
            deadline = self.config.stuck_sync_deadline
        now = time.monotonic()
        with self._lock:
            snapshot = list(self._in_flight.items())
        stuck = [
            {"worker": worker_id, "key": key, "age_seconds": now - start}
            for worker_id, (key, start) in snapshot
            if now - start > deadline
        ]
        stuck.sort(key=lambda entry: -entry["age_seconds"])
        return stuck

    # ------------------------------------------------------------------
    # health-report detail

    def sync_errors(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._sync_errors)

    def report(self) -> dict:
        """Quarantine + error detail for the aggregated health report."""
        now = time.monotonic()
        with self._lock:
            return {
                "count": len(self._quarantine),
                "keys": {
                    key: {
                        "failures": q.failures,
                        "quarantined_for_seconds": round(now - q.since, 3),
                        "next_probe_in_seconds": round(max(0.0, q.until - now), 3),
                        "probe_granted": q.probe_granted,
                        "last_error": self._sync_errors.get(key, ""),
                    }
                    for key, q in self._quarantine.items()
                },
                "sync_errors": dict(self._sync_errors),
            }
