"""Subpackage."""
