"""Cluster-topology generation: TF_CONFIG plus the TPU/JAX coordination env.

This is the single injection point the reference calls SetClusterSpec
(/root/reference/pkg/controller.v1/tensorflow/pod.go:250-283 and
tensorflow.go:97-173), re-imagined for TPUs:

  - TF_CONFIG is emitted byte-compatible with the reference (dense
    {"cluster","task","environment":"cloud"}; sparse {"sparseCluster","task"}
    for EnableDynamicWorker) so reference TFJobs run unmodified.
  - Additionally a TPU-native topology document is emitted as env vars:
    coordinator address + process id/count (`jax.distributed.initialize`
    inputs), slice topology and logical mesh shape (so the training runtime
    can lay dp/tp/sp axes over ICI without re-discovering the fabric).

Addresses default to headless-service DNS names
`<job>-<rtype>-<idx>.<ns>.svc[.<CUSTOM_CLUSTER_DOMAIN>]:<port>`
(ref: tensorflow.go:153-166); local runtimes may override via resolver.
"""
from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional

from ..api import constants
from ..api.core import Pod
from ..api.types import (
    REPLICA_TYPE_ORDER,
    ReplicaType,
    TPUJob,
    effective_replicas,
    elastic_bounds,
    is_chief_or_master,
)
from ..runtime.reconciler import gen_general_name, get_port_from_job

# resolver(job, rtype, index, port) -> "host:port"
AddressResolver = Callable[[TPUJob, ReplicaType, int, int], str]


def _group_width(job: TPUJob, rtype: ReplicaType, rspec) -> int:
    """Pods the group actually runs: the mapped PHYSICAL width for elastic
    groups (resize doc, docs/elasticity.md), else the spec width.  Every
    topology document below is addressed to real pods, so it must follow
    the physical width — the virtual width only appears in the elastic env
    vars that tell the workload how to multiplex."""
    if rspec is not None and rspec.elastic is not None:
        return effective_replicas(job, rtype)
    return int(rspec.replicas or 0) if rspec is not None else 0


def dns_resolver(job: TPUJob, rtype: ReplicaType, index: int, port: int) -> str:
    """(ref: tensorflow.go:153-166)"""
    host = gen_general_name(job.metadata.name, rtype.value, index)
    svc = f"{host}.{job.metadata.namespace}.svc"
    domain = os.environ.get(constants.ENV_CUSTOM_CLUSTER_DOMAIN, "")
    if domain:
        svc += f".{domain}"
    return f"{svc}:{port}"


def gen_cluster_spec(
    job: TPUJob, resolver: AddressResolver = dns_resolver
) -> Dict[str, List[str]]:
    """{replica-type-lowercase: [host:port, ...]} (ref: genClusterSpec,
    tensorflow.go:142-173)."""
    cluster: Dict[str, List[str]] = {}
    for rtype, rspec in job.spec.replica_specs.items():
        port = get_port_from_job(job.spec, rtype)
        cluster[rtype.value.lower()] = [
            resolver(job, rtype, i, port)
            for i in range(_group_width(job, rtype, rspec))
        ]
    return cluster


def sparse_cluster_spec(
    cluster: Dict[str, List[str]], rtype: str, index: int
) -> Dict[str, object]:
    """Each worker sees itself + all PS; each PS sees only itself
    (ref: convertClusterSpecToSparseClusterSpec, tensorflow.go:74-84)."""
    sparse: Dict[str, object] = {"worker": {}, "ps": []}
    if rtype == "ps":
        sparse["ps"] = [cluster[rtype][index]]
    elif rtype == "worker":
        sparse["ps"] = list(cluster.get("ps", []))
        sparse["worker"] = {index: cluster[rtype][index]}
    return sparse


def gen_tf_config(
    job: TPUJob, rtype: ReplicaType, index: int, resolver: AddressResolver = dns_resolver
) -> str:
    """The TF_CONFIG JSON string (ref: genTFConfigJSONStr, tensorflow.go:97-139)."""
    cluster = gen_cluster_spec(job, resolver)
    rt = rtype.value.lower()
    if job.spec.enable_dynamic_worker:
        payload: Dict[str, object] = {
            "sparseCluster": sparse_cluster_spec(cluster, rt, index),
            "task": {"type": rt, "index": index},
        }
    else:
        payload = {
            "cluster": cluster,
            "task": {"type": rt, "index": index},
            "environment": "cloud",
        }
    return json.dumps(payload, separators=(",", ":"))


def is_distributed(job: TPUJob) -> bool:
    """Single-process jobs get no TF_CONFIG (ref: isDistributed, pod.go:287-308)."""
    count = 0
    for rtype in REPLICA_TYPE_ORDER:
        rspec = job.spec.replica_specs.get(rtype)
        if rspec is None:
            continue
        if rspec.elastic is not None:
            count += effective_replicas(job, rtype)
        else:
            count += int(rspec.replicas) if rspec.replicas is not None else 1
    return count != 1


# ---------------------------------------------------------------------------
# TPU-native topology document

# Replica types that host accelerator processes and therefore get JAX
# coordination env.  PS/Evaluator are CPU-side and excluded from the
# jax.distributed process group.
_JAX_PROCESS_TYPES = (ReplicaType.CHIEF, ReplicaType.MASTER, ReplicaType.WORKER)


def jax_process_layout(job: TPUJob) -> List[tuple]:
    """Deterministic (rtype, index) -> process-id order: chief/master first
    (they coordinate), then workers — the TPU analogue of the reference's
    'chief else worker-0 is master' rule (controller.go:409-416)."""
    layout = []
    for rtype in (ReplicaType.CHIEF, ReplicaType.MASTER, ReplicaType.WORKER):
        rspec = job.spec.replica_specs.get(rtype)
        if rspec is not None:
            for i in range(_group_width(job, rtype, rspec)):
                layout.append((rtype, i))
    return layout


def gen_tpu_env(
    job: TPUJob, rtype: ReplicaType, index: int,
    resolver: AddressResolver = dns_resolver,
    warn: Optional[Callable[[str, str], None]] = None,
) -> Dict[str, str]:
    """The TPU-native topology document, one env-var map per process."""
    env: Dict[str, str] = {
        constants.ENV_REPLICA_TYPE: rtype.value.lower(),
        constants.ENV_REPLICA_INDEX: str(index),
    }
    layout = jax_process_layout(job)
    if layout:
        coord_rtype, coord_index = layout[0]
        coord_port = get_port_from_job(job.spec, coord_rtype)
        env[constants.ENV_COORDINATOR_ADDRESS] = resolver(
            job, coord_rtype, coord_index, coord_port
        )
        env[constants.ENV_NUM_PROCESSES] = str(len(layout))
        if rtype in _JAX_PROCESS_TYPES:
            try:
                env[constants.ENV_PROCESS_ID] = str(layout.index((rtype, index)))
            except ValueError:
                pass

    rspec = job.spec.replica_specs.get(rtype)
    if rspec is not None and rspec.elastic is not None:
        # Elastic mapping document (docs/elasticity.md): the workload
        # derives its virtual-replica set as {j : j % P == index} and tags
        # checkpoints with the generation the layout came from.
        lo, hi, virtual = elastic_bounds(rspec)
        env[constants.ENV_VIRTUAL_REPLICAS] = str(virtual)
        env[constants.ENV_PHYSICAL_REPLICAS] = str(
            effective_replicas(job, rtype)
        )
        generation = (job.status.elastic or {}).get("generation") or 0
        env[constants.ENV_ELASTIC_GENERATION] = str(int(generation))
    if rspec is not None and rspec.tpu is not None:
        if rspec.tpu.accelerator:
            env[constants.ENV_ACCELERATOR] = rspec.tpu.accelerator
        if rspec.tpu.topology:
            env[constants.ENV_SLICE_TOPOLOGY] = rspec.tpu.topology
        if rspec.tpu.mesh:
            env[constants.ENV_MESH_SHAPE] = json.dumps(
                rspec.tpu.mesh, separators=(",", ":")
            )
        if rspec.tpu.zero_shard_weight_update:
            env[constants.ENV_ZERO_SHARD_WEIGHT_UPDATE] = "1"
        _add_multislice_env(env, job, rtype, rspec, index, resolver, warn)
    return env


def _add_multislice_env(
    env: Dict[str, str],
    job: TPUJob,
    rtype: ReplicaType,
    rspec,
    index: int,
    resolver: AddressResolver,
    warn: Optional[Callable[[str, str], None]] = None,
) -> None:
    """DCN multislice coordination (no reference analogue; SURVEY §7's
    'across slices/DCN, emit coordinator addresses').

    One replica == one slice host (runtime/slices.py packing), so a group
    whose replica count exceeds one slice's host count spans several slices
    wired over DCN.  The scheduler packs slices per replica type in replica-
    index order, so `index // hosts` here names exactly the slice the pod
    lands on.  A multislice job must keep all its accelerator processes in
    one replica type — api/validation.py rejects multislice specs that
    spread slice topologies over several JAX process types, and this
    function emits nothing for them (an inconsistent MEGASCALE document
    across one jax.distributed group hangs libtpu init).  Emit the
    MEGASCALE_* document JAX/libtpu multislice reads: a single coordinator
    (slice 0, host 0) plus this process's slice id.  Within a slice,
    workers still find each other over ICI — only the cross-slice layer
    needs addresses, exactly the reference's TF_CONFIG division of labor
    re-drawn at the slice boundary.
    """
    import math

    from ..api.types import topology_hosts

    if not rspec.tpu.topology:
        return
    if rtype not in _JAX_PROCESS_TYPES:
        # A PS/Evaluator group is not part of the jax.distributed process
        # group; giving it its own MEGASCALE document (coordinator=ps-0)
        # would hand CPU-side pods a conflicting multislice view.
        return
    try:
        hosts = topology_hosts(rspec.tpu.topology)
    except ValueError:
        return
    replicas = _group_width(job, rtype, rspec)
    num_slices = max(1, math.ceil(replicas / hosts))
    if num_slices < 2:
        return
    sliced_jax_types = [
        rt for rt in _JAX_PROCESS_TYPES
        if job.spec.replica_specs.get(rt) is not None
        and job.spec.replica_specs[rt].tpu is not None
        and job.spec.replica_specs[rt].tpu.topology
    ]
    if len(sliced_jax_types) > 1:
        # Correct but surprising: the group WOULD span slices, yet no
        # MEGASCALE document is emitted.  Tell the user why their
        # multislice job formed no DCN group instead of leaving them to
        # diff pod env against a working job.
        if warn is not None:
            warn(
                "MultisliceDisabled",
                f"replica type {rtype.value} spans {num_slices} slices but "
                "the job has multiple sliced JAX process types ("
                + ", ".join(rt.value for rt in sliced_jax_types)
                + "); MEGASCALE_* coordination env was not emitted because "
                "an inconsistent multislice document across one "
                "jax.distributed group hangs libtpu init — keep all "
                "accelerator processes in a single replica type to form a "
                "DCN group",
            )
        return
    port = get_port_from_job(job.spec, rtype)
    env[constants.ENV_MEGASCALE_COORDINATOR] = resolver(job, rtype, 0, port)
    env[constants.ENV_MEGASCALE_NUM_SLICES] = str(num_slices)
    env[constants.ENV_MEGASCALE_SLICE_ID] = str(index // hosts)


def set_cluster_spec(
    job: TPUJob,
    pod: Pod,
    rtype: ReplicaType,
    index: int,
    resolver: AddressResolver = dns_resolver,
    warn: Optional[Callable[[str, str], None]] = None,
) -> None:
    """Inject TF_CONFIG + TPU env into the operator container of `pod`
    (ref: SetClusterSpec, pod.go:250-283 — skipped when non-distributed)."""
    container = pod.spec.container(
        constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME
    )
    if container is None:
        return
    if is_distributed(job):
        container.set_env(constants.ENV_TF_CONFIG, gen_tf_config(job, rtype, index, resolver))
    for name, value in gen_tpu_env(job, rtype, index, resolver, warn).items():
        container.set_env(name, value)
