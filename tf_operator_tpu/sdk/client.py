"""TPUJobClient — the user-facing submit/watch/logs API.

API-parity rebuild of the reference's Python SDK
(/root/reference/sdk/python/kubeflow/tfjob/api/tf_job_client.py:52-356):
create, get, patch, delete, wait_for_job, wait_for_condition, get_job_status,
is_job_running, is_job_succeeded, get_pod_names, get_logs — against a
ClusterInterface instead of the k8s CustomObjects REST API.
"""
from __future__ import annotations

import time
from typing import Callable, Iterable, List, Optional, Union

from ..api import constants
from ..api.types import JobConditionType, TPUJob
from ..runtime import conditions
from ..runtime.cluster import ClusterInterface

TERMINAL_CONDITIONS = ("Succeeded", "Failed")


def _json_merge_patch(base: dict, patch: dict) -> dict:
    """RFC 7386 merge patch (client-side fallback for backends without a
    server-side PATCH verb)."""
    out = dict(base)
    for key, value in patch.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _json_merge_patch(out[key], value)
        elif value is None:
            out.pop(key, None)
        else:
            out[key] = value
    return out


class TimeoutError_(TimeoutError):
    pass


class TPUJobClient:
    def __init__(self, cluster: ClusterInterface, namespace: str = "default") -> None:
        self.cluster = cluster
        self.namespace = namespace

    # --- CRUD (ref: tf_job_client.py:52-197) ---

    def create(self, job: TPUJob, namespace: Optional[str] = None) -> TPUJob:
        if namespace:
            job.metadata.namespace = namespace
        elif not job.metadata.namespace:
            job.metadata.namespace = self.namespace
        return self.cluster.create_job(job)

    def get(self, name: str, namespace: Optional[str] = None) -> TPUJob:
        return self.cluster.get_job(namespace or self.namespace, name)

    def patch(self, name: str, patch: Union[dict, Callable[[TPUJob], None]],
              namespace: Optional[str] = None) -> TPUJob:
        """Patch a job.

        With a dict: JSON-merge-patch, the reference SDK's semantics
        (tf_job_client.py:114-136) — applied server-side on backends that
        support it (KubernetesCluster.patch_job), so concurrent patches to
        different fields don't race the way read-modify-write does.
        With a callable: legacy read-modify-write convenience.
        """
        ns = namespace or self.namespace
        if callable(patch):
            job = self.get(name, namespace)
            patch(job)
            return self.cluster.update_job(job)
        patcher = getattr(self.cluster, "patch_job", None)
        if patcher is not None:
            return patcher(ns, name, patch)
        from ..api import serialization

        job = self.get(name, namespace)
        merged = _json_merge_patch(serialization.job_to_dict(job), patch)
        return self.cluster.update_job(serialization.job_from_dict(merged))

    def delete(self, name: str, namespace: Optional[str] = None) -> None:
        self.cluster.delete_job(namespace or self.namespace, name)

    # --- status helpers (ref: tf_job_client.py:283-340) ---

    def get_job_status(self, name: str, namespace: Optional[str] = None) -> str:
        job = self.get(name, namespace)
        if job.status.conditions:
            # latest condition with status true wins
            for cond in reversed(job.status.conditions):
                if cond.status:
                    return cond.type.value
        return ""

    def is_job_running(self, name: str, namespace: Optional[str] = None) -> bool:
        return self.get_job_status(name, namespace) == "Running"

    def is_job_succeeded(self, name: str, namespace: Optional[str] = None) -> bool:
        return self.get_job_status(name, namespace) == "Succeeded"

    # --- waiting (ref: wait_for_condition :234-281, wait_for_job :198-233) ---

    def wait_for_condition(
        self,
        name: str,
        expected: Iterable[str],
        namespace: Optional[str] = None,
        timeout: float = 120.0,
        polling_interval: float = 0.1,
        status_callback: Optional[Callable[[TPUJob], None]] = None,
    ) -> TPUJob:
        expected = set(expected)
        deadline = time.time() + timeout
        while time.time() < deadline:
            job = self.get(name, namespace)
            if status_callback is not None:
                status_callback(job)
            for cond in job.status.conditions:
                if cond.status and cond.type.value in expected:
                    return job
            time.sleep(polling_interval)
        raise TimeoutError_(
            f"timeout waiting for TPUJob {name} to reach {sorted(expected)}; "
            f"currently {self.get_job_status(name, namespace)!r}"
        )

    def wait_for_job(self, name: str, namespace: Optional[str] = None,
                     timeout: float = 120.0) -> TPUJob:
        job = self.wait_for_condition(name, TERMINAL_CONDITIONS, namespace, timeout)
        return job

    def wait_for_deletion(self, name: str, namespace: Optional[str] = None,
                          timeout: float = 60.0) -> None:
        from ..runtime.cluster import NotFound

        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                self.get(name, namespace)
            except NotFound:
                return
            time.sleep(0.1)
        raise TimeoutError_(f"timeout waiting for TPUJob {name} deletion")

    # --- pods / logs (ref: get_pod_names :341-364, get_logs :340-356) ---

    def get_pod_names(self, name: str, namespace: Optional[str] = None,
                      replica_type: Optional[str] = None) -> List[str]:
        ns = namespace or self.namespace
        selector = {
            constants.LABEL_GROUP_NAME: constants.API_GROUP,
            constants.LABEL_JOB_NAME: name,
        }
        if replica_type:
            selector[constants.LABEL_REPLICA_TYPE] = replica_type.lower()
        return sorted(p.metadata.name for p in self.cluster.list_pods(ns, selector))

    def get_logs(self, name: str, namespace: Optional[str] = None,
                 replica_type: Optional[str] = None) -> dict:
        ns = namespace or self.namespace
        logs = {}
        for pod_name in self.get_pod_names(name, ns, replica_type):
            getter = getattr(self.cluster, "pod_logs", None)
            logs[pod_name] = getter(ns, pod_name) if getter else ""
        return logs

    def get_events(self, name: str, namespace: Optional[str] = None) -> list:
        return self.cluster.list_events(namespace or self.namespace, name)
