"""Table-logged job watch (ref: sdk/python/kubeflow/tfjob/api/tf_job_watch.py:29-59).

The reference polls the CRD watch API and prints NAME/STATE/TIME rows until the
job reaches Succeeded or Failed.  Here the poll goes through ClusterInterface
(in-memory, local-process, or remote HTTP — same seam everywhere) and rows are
emitted only on state transitions, so a long Running phase prints one line.
"""
from __future__ import annotations

import time
from datetime import datetime, timezone
from typing import Callable, Optional

from tf_operator_tpu.api.types import JobConditionType, TPUJob

TERMINAL = (JobConditionType.SUCCEEDED, JobConditionType.FAILED)
_FMT = "{:<32} {:<12} {:<24}"


def _state(job: TPUJob) -> str:
    for cond in reversed(job.status.conditions):
        if cond.status:
            return cond.type.value if hasattr(cond.type, "value") else str(cond.type)
    return "Created"


def watch(
    client,
    name: str,
    namespace: Optional[str] = None,
    timeout: float = 600.0,
    poll_interval: float = 1.0,
    printer: Callable[[str], None] = print,
) -> TPUJob:
    """Poll the job, printing a table row on every state transition, until a
    terminal condition or timeout.  Returns the final job object."""
    printer(_FMT.format("NAME", "STATE", "TIME"))
    deadline = time.time() + timeout
    last_state = None
    while True:
        job = client.get(name, namespace)
        state = _state(job)
        if state != last_state:
            stamp = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
            printer(_FMT.format(name, state, stamp))
            last_state = state
        if any(c.type in TERMINAL and c.status for c in job.status.conditions):
            return job
        if time.time() >= deadline:
            raise TimeoutError(
                f"timeout waiting for job {name} to finish (last state {state})"
            )
        time.sleep(poll_interval)
