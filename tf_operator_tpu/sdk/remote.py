"""RemoteCluster: ClusterInterface over the operator's REST API.

The out-of-process half of the SDK: TPUJobClient(RemoteCluster(url)) gives
the same create/wait/logs surface as the reference SDK has against the k8s
apiserver (ref: sdk/python/kubeflow/tfjob/api/tf_job_client.py).  Only the
read/write verbs a client needs are implemented; watches are client-side
polling (wait_for_condition), matching the reference SDK's get/poll loop.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..api.core import ContainerStatus, ObjectMeta, Pod, PodPhase, PodStatus
from ..api.serialization import job_from_dict, job_to_dict
from ..api.types import TPUJob
from ..runtime.cluster import AlreadyExists, ClusterInterface, NotFound


class ApiError(RuntimeError):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


class HealthReport(dict):
    """The aggregated /healthz report.  A plain dict except that its
    truthiness is the *ready* verdict, so code written against the old
    `healthz() -> bool` contract (`if cluster.healthz(): ...`) keeps
    working — a non-empty-but-not-ready report must not read as healthy."""

    def __bool__(self) -> bool:
        return bool(self.get("ready"))


class RemoteCluster(ClusterInterface):
    def __init__(self, base_url: str = "http://127.0.0.1:8008", timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # ------------------------------------------------------------------
    def _request(self, method: str, path: str, payload: Optional[dict] = None) -> dict:
        url = f"{self.base_url}{path}"
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as err:
            body = err.read().decode(errors="replace")
            try:
                message = json.loads(body).get("error", body)
            except json.JSONDecodeError:
                message = body
            if err.code == 404:
                raise NotFound(message) from None
            if err.code == 409:
                raise AlreadyExists(message) from None
            raise ApiError(err.code, message) from None

    # --- jobs ---

    def create_job(self, job: TPUJob) -> TPUJob:
        ns = job.metadata.namespace or "default"
        data = self._request("POST", f"/apis/v1/namespaces/{ns}/tpujobs",
                             job_to_dict(job))
        return job_from_dict(data)

    def get_job(self, namespace: str, name: str) -> TPUJob:
        return job_from_dict(
            self._request("GET", f"/apis/v1/namespaces/{namespace}/tpujobs/{name}")
        )

    def list_jobs(self, namespace: Optional[str] = None) -> List[TPUJob]:
        ns = namespace or "default"
        data = self._request("GET", f"/apis/v1/namespaces/{ns}/tpujobs")
        return [job_from_dict(item) for item in data.get("items", [])]

    def update_job(self, job: TPUJob) -> TPUJob:
        ns = job.metadata.namespace
        data = self._request(
            "PUT", f"/apis/v1/namespaces/{ns}/tpujobs/{job.metadata.name}",
            job_to_dict(job),
        )
        return job_from_dict(data)

    def delete_job(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/apis/v1/namespaces/{namespace}/tpujobs/{name}")

    # --- pods (read-only client view) ---

    def list_pods(self, namespace: Optional[str] = None,
                  selector: Optional[Dict[str, str]] = None) -> List[Pod]:
        ns = namespace or "default"
        path = f"/apis/v1/namespaces/{ns}/pods"
        if selector:
            sel = ",".join(f"{k}={v}" for k, v in selector.items())
            path += f"?selector={sel}"
        data = self._request("GET", path)
        return [self._pod_from_dict(item) for item in data.get("items", [])]

    @staticmethod
    def _pod_from_dict(data: dict) -> Pod:
        meta = data.get("metadata", {})
        status = data.get("status", {})
        return Pod(
            metadata=ObjectMeta(
                name=meta.get("name", ""),
                namespace=meta.get("namespace", "default"),
                labels=dict(meta.get("labels") or {}),
                annotations=dict(meta.get("annotations") or {}),
            ),
            status=PodStatus(
                phase=PodPhase(status.get("phase", "Pending")),
                start_time=status.get("startTime"),
                container_statuses=[
                    ContainerStatus(
                        name=cs.get("name", ""),
                        restart_count=int(cs.get("restartCount", 0)),
                        running=bool(cs.get("running")),
                        terminated=bool(cs.get("terminated")),
                        exit_code=cs.get("exitCode"),
                    )
                    for cs in status.get("containerStatuses") or []
                ],
            ),
        )

    def pod_logs(self, namespace: str, name: str) -> str:
        data = self._request(
            "GET", f"/apis/v1/namespaces/{namespace}/pods/{name}/log"
        )
        return data.get("log", "")

    # --- events ---

    def list_events(self, namespace: Optional[str] = None,
                    object_name: Optional[str] = None) -> list:
        from ..api.core import Event

        ns = namespace or "default"
        path = f"/apis/v1/namespaces/{ns}/events"
        if object_name:
            path += f"?object={object_name}"
        data = self._request("GET", path)
        return [
            Event(
                object_kind="TPUJob",
                object_name=item.get("object", ""),
                namespace=ns,
                event_type=item.get("type", ""),
                reason=item.get("reason", ""),
                message=item.get("message", ""),
                timestamp=item.get("timestamp", 0.0),
            )
            for item in data.get("items", [])
        ]

    def healthz(self) -> "HealthReport":
        """The operator's aggregated health report (docs/self-healing.md):
        at least {"live": bool, "ready": bool}, plus worker/queue/watch/
        quarantine detail from a controller-wired server.  Not-ready servers
        answer 503 with the same JSON body, so that path parses the body
        rather than surfacing an error; an unreachable server reports
        {"live": False, "ready": False, "error": ...}.  Old servers that
        answer a bare {"status": "ok"} JSON or plain-text "ok" body are
        mapped onto the same shape; any other unparseable body (a proxy's
        HTML error page, say) becomes the not-live error shape rather than
        an exception.  The returned HealthReport is a dict whose truthiness
        is the ready verdict, so `if cluster.healthz():` keeps its old
        bool-contract meaning."""
        url = f"{self.base_url}/healthz"
        req = urllib.request.Request(url, method="GET")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = resp.read()
            try:
                report = json.loads(body or b"{}")
            except json.JSONDecodeError:
                report = None
            if not isinstance(report, dict):
                # plain-text "ok" (or the JSON string "ok") is a legacy
                # healthy answer; any other non-object body is an error
                if body.strip().strip(b'"').lower() == b"ok":
                    report = {"status": "ok"}
                else:
                    return HealthReport(
                        live=False, ready=False,
                        error="unparseable healthz body: "
                              f"{body.decode(errors='replace')[:200]}")
        except urllib.error.HTTPError as err:
            body = err.read()
            try:
                report = json.loads(body or b"{}")
            except json.JSONDecodeError:
                report = None
            if not isinstance(report, dict):
                report = {"error": f"HTTP {err.code}: "
                                   f"{body.decode(errors='replace')[:200]}"}
        except OSError as err:
            return HealthReport(live=False, ready=False, error=str(err))
        ok = report.get("status") == "ok"
        report.setdefault("live", ok)
        report.setdefault("ready", ok)
        return HealthReport(report)
