"""Reference-SDK compatibility surface.

Users of the reference import `from kubeflow.tfjob import TFJobClient`
(/root/reference/sdk/python/kubeflow/tfjob/api/tf_job_client.py) with
methods create/get/patch/delete/wait_for_job/wait_for_condition/
get_job_status/is_job_running/is_job_succeeded/get_pod_names/get_logs.
TPUJobClient already exposes that exact method surface; this module provides
the familiar name plus the reference's constants
(ref: sdk/python/kubeflow/tfjob/constants/constants.py:18-33) mapped to this
framework's values, and a `log_status` watch callback matching the
reference's table logger (tf_job_watch.py:29-59).
"""
from __future__ import annotations

import time

from ..api import constants as _api_constants
from .client import TPUJobClient

# Reference constants surface (constants.py:18-33), TPU values.
TFJOB_GROUP = _api_constants.API_GROUP
TFJOB_VERSION = _api_constants.API_VERSION
TFJOB_KIND = _api_constants.KIND
TFJOB_PLURAL = _api_constants.PLURAL
TFJOB_LOGLEVEL = "INFO"

JOB_GROUP_LABEL = _api_constants.LABEL_GROUP_NAME
JOB_NAME_LABEL = _api_constants.LABEL_JOB_NAME
JOB_TYPE_LABEL = _api_constants.LABEL_REPLICA_TYPE
JOB_INDEX_LABEL = _api_constants.LABEL_REPLICA_INDEX
JOB_ROLE_LABEL = _api_constants.LABEL_JOB_ROLE


class TFJobClient(TPUJobClient):
    """Drop-in alias: the TFJobClient method surface over any cluster backend."""


def log_status(job) -> None:
    """Watch callback printing the reference's status table
    (NAME / STATE / TIME)."""
    state = ""
    for cond in reversed(job.status.conditions):
        if cond.status:
            state = cond.type.value
            break
    print(f"{job.metadata.name:<30} {state or 'Created':<20} "
          f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}")
