"""Subpackage."""
