"""Mixture-of-Experts with expert parallelism over the `ep` mesh axis.

Completes the parallelism matrix (SURVEY.md §2.9: EP absent from the
reference; first-class here).  Design:

  - Top-k gating with capacity factor (Switch/GShard style): each token picks
    its top-k experts; per-expert capacity C = k·T·cf/E bounds the dense
    dispatch so every shape is static (XLA-friendly — no dynamic gathers).
  - Dispatch/combine are einsums against a one-hot dispatch mask — the GShard
    recipe: dense [T, E, C] masks keep the MXU busy and let the SPMD
    partitioner turn the expert dimension into an all-to-all over ICI when
    `ep` is in the mesh.
  - Experts are a stacked FFN [E, d_model, d_ff]; sharding rules place the
    E dimension on `ep` (combined_spec rule below), tokens stay on dp/sp.
  - Load-balancing auxiliary loss (Switch §2.2) returned alongside.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def top_k_gating(
    logits: jax.Array, k: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compute dispatch/combine tensors.

    logits: [tokens, experts].  Returns (dispatch [T,E,C] bool-ish float,
    combine [T,E,C] float, aux_loss scalar).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # aux load-balance loss: E * sum_e (fraction_tokens_e * mean_prob_e)
    top1 = jnp.argmax(probs, axis=-1)
    frac_tokens = jnp.mean(jax.nn.one_hot(top1, e, dtype=jnp.float32), axis=0)
    mean_probs = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(frac_tokens * mean_probs)

    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # Track how many tokens each expert has accepted so far; droppable
    # (over-capacity) tokens simply get no slot (GShard behavior).
    remaining = probs
    fill = jnp.zeros((e,), jnp.int32)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)  # [T]
        gate = jnp.take_along_axis(remaining, choice[:, None], axis=-1)[:, 0]
        remaining = remaining * (1.0 - jax.nn.one_hot(choice, e, dtype=remaining.dtype))
        # position of each token within its chosen expert's queue
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [T, E]
        pos_within = (jnp.cumsum(onehot, axis=0) - 1) * onehot  # [T, E]
        pos = jnp.sum(pos_within, axis=-1) + jnp.take(fill, choice)  # [T]
        fill = fill + jnp.sum(onehot, axis=0)
        keep = pos < capacity
        pos = jnp.clip(pos, 0, capacity - 1)
        slot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)  # [T, C]
        mask = (keep.astype(jnp.float32) * 1.0)[:, None, None]
        contrib = onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :] * mask
        dispatch = dispatch + contrib
        combine = combine + contrib * gate[:, None, None]
    return dispatch, combine, aux_loss


class MoEMLP(nn.Module):
    """Drop-in replacement for the transformer MLP block."""

    d_model: int
    d_ff: int
    num_experts: int = 8
    k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        tokens = x.reshape(b * t, d)
        n_tok = b * t
        capacity = max(1, int(self.k * n_tok * self.capacity_factor / self.num_experts))

        gate_logits = nn.Dense(self.num_experts, dtype=jnp.float32,
                               name="router")(tokens.astype(jnp.float32))
        dispatch, combine, aux_loss = top_k_gating(gate_logits, self.k, capacity)
        self.sow("intermediates", "moe_aux_loss", aux_loss)

        # [E, C, d] expert inputs via dense dispatch einsum (MXU-friendly).
        expert_in = jnp.einsum("td,tec->ecd", tokens.astype(self.dtype),
                               dispatch.astype(self.dtype))
        wi = self.param("wi", nn.initializers.normal(0.02),
                        (self.num_experts, d, self.d_ff))
        wo = self.param("wo", nn.initializers.normal(0.02),
                        (self.num_experts, self.d_ff, d))
        h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(self.dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecf,efd->ecd", h, wo.astype(self.dtype))
        out = jnp.einsum("ecd,tec->td", expert_out, combine.astype(self.dtype))
        return out.reshape(b, t, d).astype(x.dtype)


def moe_aux_loss(intermediates) -> jax.Array:
    """Mean of the sown per-layer aux losses from
    model.apply(..., mutable=['intermediates']).

    Mean (not sum) keeps the effective balancing weight independent of model
    depth — `moe_aux_weight` tunes identically for 2-layer tests and deep
    stacks."""
    losses = []

    def visit(node):
        if isinstance(node, dict):
            for key, value in node.items():
                if key == "moe_aux_loss":
                    losses.extend(value if isinstance(value, (list, tuple)) else [value])
                else:
                    visit(value)

    visit(intermediates)
    if not losses:
        return jnp.zeros(())
    return sum(losses) / len(losses)
