"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence/context parallelism is a first-class capability here
(the reference schedules processes and leaves the math to user containers —
SURVEY.md §2.9/§5 "long-context: absent; build the enabler + the kernels").

Algorithm (Liu et al., "Ring Attention with Blockwise Transformers",
arXiv:2310.01889): the sequence axis is sharded over the `sp` mesh axis; each
device holds a query block and rotates K/V blocks around the ring with
`ppermute` (one ICI hop per step), accumulating exact softmax attention
online in log-sum-exp form.  Compute on each hop overlaps the next transfer;
memory per device is O(T/N · T/N) instead of O(T²).

Causal masking uses global block offsets derived from `lax.axis_index`, so
fully-masked hops contribute zeros without data-dependent control flow
(everything stays jit/scan friendly).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import check_gqa, repeat_kv, flash_attention_lse

try:
    from jax import shard_map as _shard_map  # jax >= 0.8 (check_vma kwarg)

    def shard_map(f, mesh, in_specs, out_specs, check_rep=False):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_rep
        )
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, bias, scale):
    """One q-block × kv-block attention contribution.

    q: [B, H, Tq, D], k/v: [B, H, Tk, D], bias: [Tq, Tk] additive mask.
    Returns (numerator [B,H,Tq,D], row_max [B,H,Tq], row_sumexp [B,H,Tq]).
    """
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    )
    logits = logits * scale + bias[None, None, :, :]
    m = jnp.max(logits, axis=-1)
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def _combine(acc_o, acc_m, acc_l, o, m, l):
    """Merge a new block into the online-softmax accumulator (log-sum-exp)."""
    new_m = jnp.maximum(acc_m, m)
    old_scale = jnp.exp(acc_m - new_m)
    new_scale = jnp.exp(m - new_m)
    new_l = acc_l * old_scale + l * new_scale
    new_o = acc_o * old_scale[..., None] + o * new_scale[..., None]
    return new_o, new_m, new_l


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, scale: float):
    """Per-device body under shard_map: q/k/v are the local sequence shards
    [B, H, T_local, D].  Pure-XLA hop math (O(T_local²) logits per hop) —
    the flash-kernel variant below is the default on TPU."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    t_local = q.shape[2]

    q32 = q.astype(jnp.float32)
    acc_o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    acc_m = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    acc_l = jnp.zeros(q.shape[:3], jnp.float32)

    rows = lax.broadcasted_iota(jnp.int32, (t_local, t_local), 0)
    cols = lax.broadcasted_iota(jnp.int32, (t_local, t_local), 1)

    def step(carry, step_idx):
        acc_o, acc_m, acc_l, k_blk, v_blk = carry
        # The block arriving at step s originated on device (my_idx - s) % n.
        src_idx = (my_idx - step_idx) % n
        if causal:
            # Global positions: query row r lives at my_idx*T+r; key col c at
            # src_idx*T+c.  Allowed iff q_pos >= k_pos.
            q_pos = my_idx * t_local + rows
            k_pos = src_idx * t_local + cols
            bias = jnp.where(q_pos >= k_pos, 0.0, NEG_INF).astype(jnp.float32)
        else:
            bias = jnp.zeros((t_local, t_local), jnp.float32)
        o, m, l = _block_attend(q32, k_blk, v_blk, bias, scale)
        acc = _combine(acc_o, acc_m, acc_l, o, m, l)
        # Rotate K/V one hop around the ring (device i -> i+1), so the next
        # step sees the previous neighbor's block.
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (*acc, k_next, v_next), None

    (acc_o, acc_m, acc_l, _, _), _ = lax.scan(
        step, (acc_o, acc_m, acc_l, k, v), jnp.arange(n)
    )
    # Guard fully-masked rows (can only happen with exotic masks): avoid 0/0.
    denom = jnp.where(acc_l == 0.0, 1.0, acc_l)
    return (acc_o / denom[..., None]).astype(q.dtype)


def _ring_attention_local_flash(q, k, v, *, axis_name: str, causal: bool,
                                scale: float):
    """Per-device body with the Pallas flash kernel as the hop primitive.

    Each hop runs ops/attention.flash_attention_lse on (local q, arriving
    K/V block) — O(block²) score tiles stay in VMEM instead of an
    O(T_local²) logits array in HBM — and the (normalized o, lse) pairs are
    merged in log-sum-exp form.  Under the global causal mask a hop is one
    of three cases, chosen per device per step with lax.switch (both
    branches of every hop are compiled once; each device executes one):

        src block before mine  -> full (non-causal) attention
        src block is mine      -> standard causal diagonal
        src block after mine   -> no contribution (lse = -inf sentinel)

    The lse cotangent flows through the combine weights into the kernel's
    backward (flash_attention_lse is differentiable in both outputs).
    """
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)

    def full_hop(q, k_blk, v_blk):
        o, lse = flash_attention_lse(q, k_blk, v_blk, False, scale)
        return o.astype(jnp.float32), lse

    def diag_hop(q, k_blk, v_blk):
        o, lse = flash_attention_lse(q, k_blk, v_blk, True, scale)
        return o.astype(jnp.float32), lse

    def skip_hop(q, k_blk, v_blk):
        return (jnp.zeros(q.shape[:3] + (v_blk.shape[-1],), jnp.float32),
                jnp.full(q.shape[:3], NEG_INF, jnp.float32))

    def step(carry, step_idx):
        acc_o, acc_lse, k_blk, v_blk = carry
        src_idx = (my_idx - step_idx) % n
        if causal:
            branch = jnp.where(
                src_idx == my_idx, 1, jnp.where(src_idx < my_idx, 0, 2))
            o, lse = lax.switch(
                branch, (full_hop, diag_hop, skip_hop), q, k_blk, v_blk)
        else:
            o, lse = full_hop(q, k_blk, v_blk)
        # log-sum-exp merge of normalized contributions
        new_lse = jnp.logaddexp(acc_lse, lse)
        w_acc = jnp.exp(acc_lse - new_lse)[..., None]
        w_new = jnp.exp(lse - new_lse)[..., None]
        acc_o = acc_o * w_acc + o * w_new
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (acc_o, new_lse, k_next, v_next), None

    acc_o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    acc_lse = jnp.full(q.shape[:3], NEG_INF, jnp.float32)
    (acc_o, _, _, _), _ = lax.scan(
        step, (acc_o, acc_lse, k, v), jnp.arange(n)
    )
    return acc_o.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    use_flash: bool = True,
) -> jax.Array:
    """Exact attention with the sequence axis sharded over `axis_name`.

    Inputs are global arrays [B, H, T, D] (sharded or to-be-sharded on T);
    output matches q's shape/dtype.  T must divide evenly by the sp axis
    size.  use_flash=True (default) runs the Pallas flash kernel per hop on
    TPU (falling back to closed-form XLA off-TPU inside the op);
    use_flash=False keeps the pure-einsum hop math.

    Grouped-query attention: k/v may carry fewer heads than q.  On the
    flash path the grouped blocks travel the ring as-is — each ppermute
    hop moves 1/group of the MHA bytes over ICI and the kernel maps query
    heads to KV heads in VMEM; the einsum path widens k/v up front.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    check_gqa(q, k)
    if not use_flash:
        k, v = repeat_kv(q, k, v)
    local = _ring_attention_local_flash if use_flash else _ring_attention_local
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(
            local, axis_name=axis_name, causal=causal, scale=scale
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal=True, scale=None):
    """Single-device exact attention, the correctness oracle for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = logits.shape[-2:]
        rows = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        cols = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        logits = jnp.where(rows >= cols, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v).astype(q.dtype)
