"""Subpackage."""
