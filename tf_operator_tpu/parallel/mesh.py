"""Device-mesh construction and sharding helpers.

This is the TPU-native half of the topology contract: the controller injects
TPUJOB_MESH_SHAPE / TPUJOB_SLICE_TOPOLOGY (controller/topology.py, the
re-imagined TF_CONFIG single injection point — ref
/root/reference/pkg/controller.v1/tensorflow/pod.go:250-283), and this module
turns it into a `jax.sharding.Mesh` the training runtime lays dp/fsdp/tp/sp/ep
axes onto.  Within a slice the axes ride ICI; XLA inserts the collectives.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names, outermost (slowest / DCN-friendly) first.
AXIS_DP = "dp"      # data parallel (pure replication of params)
AXIS_FSDP = "fsdp"  # data parallel with sharded params/optimizer state
AXIS_TP = "tp"      # tensor (model) parallel
AXIS_SP = "sp"      # sequence/context parallel (ring attention)
AXIS_EP = "ep"      # expert parallel (MoE)
AXIS_PP = "pp"      # pipeline parallel
AXIS_ORDER = (AXIS_DP, AXIS_FSDP, AXIS_PP, AXIS_EP, AXIS_TP, AXIS_SP)


def build_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh from {axis: size}.

    Axis product must equal the device count; axes not mentioned are omitted.
    With axes=None, all devices go on a single dp axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {AXIS_DP: n}
    # Keep canonical order for the axes given; unknown axes go last in
    # insertion order (users may invent axes).
    names = [a for a in AXIS_ORDER if a in axes] + [
        a for a in axes if a not in AXIS_ORDER
    ]
    sizes = [int(axes[a]) for a in names]
    total = int(np.prod(sizes)) if sizes else 1
    if total != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} require {total} devices, "
            f"but {n} are available"
        )
    device_array = np.asarray(devices).reshape(sizes)
    return Mesh(device_array, axis_names=tuple(names))


def mesh_from_env(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the mesh the controller assigned via TPUJOB_MESH_SHAPE."""
    from ..api import constants

    raw = os.environ.get(constants.ENV_MESH_SHAPE, "")
    axes = json.loads(raw) if raw else None
    return build_mesh(axes, devices)


def data_axes(mesh: Mesh) -> tuple:
    """The mesh axes a global batch is split over (dp + fsdp)."""
    return tuple(a for a in (AXIS_DP, AXIS_FSDP) if a in mesh.axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over dp/fsdp, replicate the rest."""
    axes = data_axes(mesh)
    return NamedSharding(mesh, P(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def axis_size(mesh: Mesh, name: str) -> int:
    return mesh.shape[name] if name in mesh.axis_names else 1


def _pick_shard_dim(
    shape: Sequence[int], size: int, prefer: str, taken: Sequence[int] = ()
) -> Optional[int]:
    """The shared dim-picking core behind param_partition_spec and
    free_dim_partition_spec: among dims not in `taken` that the axis size
    divides (and that are >= size, so every shard is non-empty), pick

      prefer="last":    the last candidate (output features usually largest
                        and contiguity-friendly), or
      prefer="largest": the largest candidate, ties broken toward the last
                        occurrence (a square kernel shards its trailing dim).

    Returns None when no dim qualifies.
    """
    if prefer not in ("last", "largest"):
        raise ValueError(f"prefer must be 'last'|'largest', got {prefer!r}")
    taken_set = set(taken)
    candidates = [
        i for i, d in enumerate(shape)
        if i not in taken_set and d % size == 0 and d >= size
    ]
    if not candidates:
        return None
    if prefer == "last":
        return candidates[-1]
    return max(candidates, key=lambda i: (shape[i], i))


def param_partition_spec(
    shape: Sequence[int], mesh: Mesh, fsdp_axis: str = AXIS_FSDP
) -> P:
    """FSDP-style weight sharding: shard the last divisible dim over the
    fsdp axis, replicate otherwise (the ZeRO-3 layout XLA turns into
    all-gather-before-use / reduce-scatter-after-grad; cf. the
    cross-replica weight-update sharding of arXiv:2004.13336)."""
    size = axis_size(mesh, fsdp_axis)
    if size <= 1 or not shape:
        return P()
    dim = _pick_shard_dim(shape, size, "last")
    if dim is None:
        return P()
    spec = [None] * len(shape)
    spec[dim] = fsdp_axis
    return P(*spec)


def free_dim_partition_spec(
    shape: Sequence[int],
    mesh: Mesh,
    axis: str = AXIS_DP,
    *,
    base: P = P(),
    prefer: str = "largest",
) -> P:
    """Lay `axis` onto a *free* dim of an (optionally already-sharded)
    array: the dim the ZeRO-style weight-update sharding (train/zero.py,
    arXiv:2004.13336) splits optimizer state over, on top of whatever
    tp/fsdp layout the param already has.

    A dim is free when `base` leaves it unsharded and the axis size divides
    it; prefer="largest" picks the largest such dim (most even memory
    savings), ties broken toward the last.  Returns `base` unchanged when
    the axis is trivial, already used by `base`, or no dim qualifies.
    """
    size = axis_size(mesh, axis)
    base_entries = list(base) + [None] * (len(shape) - len(base))
    if size <= 1 or not shape:
        return base

    def axes_of(entry):
        if entry is None:
            return ()
        return entry if isinstance(entry, tuple) else (entry,)

    taken = [i for i, e in enumerate(base_entries) if e is not None]
    if any(axis in axes_of(e) for e in base_entries):
        return base
    dim = _pick_shard_dim(shape, size, prefer, taken)
    if dim is None:
        return base
    base_entries[dim] = axis
    return P(*base_entries)


def shard_params(params, mesh: Mesh):
    """Apply param_partition_spec across a pytree and device_put it."""
    def place(x):
        spec = param_partition_spec(getattr(x, "shape", ()), mesh)
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, params)


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    n = 1
    for a in data_axes(mesh):
        n *= axis_size(mesh, a)
    if global_batch % n:
        raise ValueError(f"global batch {global_batch} not divisible by dp size {n}")
    return global_batch // n
