"""Parameter-sharding rules: tensor parallelism + FSDP via GSPMD annotations.

Instead of translating NCCL/megatron-style explicit collectives, parallelism
here is declared: each parameter gets a PartitionSpec over the mesh
('tp' for model-parallel dims, 'fsdp' for ZeRO-style sharding of what's
left), and XLA's SPMD partitioner inserts the all-gathers/reduce-scatters
over ICI (scaling-book recipe: pick a mesh, annotate, let XLA place
collectives).

Rules follow the Megatron pairing so no extra communication appears inside a
block: column-parallel qkv/wi (output-dim sharded) feed row-parallel out/wo
(input-dim sharded), yielding one psum per attention/MLP pair.
"""
from __future__ import annotations

import re
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS_FSDP, AXIS_TP, axis_size

# (path regex, spec builder taking ndim) — first match wins.  Paths are
# '/'-joined flax param paths, e.g. "block_3/attn/query/kernel".
_TP_RULES: Tuple[Tuple[str, dict], ...] = (
    # attention projections: DenseGeneral kernels (d_model, heads, head_dim)
    (r"attn/(query|key|value)/kernel$", {"shard_dim": 1}),
    (r"attn/(query|key|value)/bias$", {"shard_dim": 0}),
    # out projection kernel (heads, head_dim, d_model): shard input heads
    (r"attn/out/kernel$", {"shard_dim": 0}),
    (r"attn/out/bias$", {"shard_dim": None}),
    # MLP: wi (and the SwiGLU gate wg) column-parallel, wo row-parallel
    (r"mlp/(wi|wg)/kernel$", {"shard_dim": 1}),
    (r"mlp/(wi|wg)/bias$", {"shard_dim": 0}),
    (r"mlp/wo/kernel$", {"shard_dim": 0}),
    (r"mlp/wo/bias$", {"shard_dim": None}),
    # embeddings: vocab-sharded
    (r"(wte|tok_emb)/embedding$", {"shard_dim": 0}),
)

# MoE expert weights [E, d_in, d_out]: expert dim shards over `ep`.
_EP_RULES: Tuple[Tuple[str, int], ...] = (
    (r"moe/wi$", 0),
    (r"moe/wo$", 0),
)


def tp_spec_for_path(path: str, shape, mesh: Mesh) -> Optional[P]:
    """The tensor-parallel PartitionSpec for a param path, or None if no
    rule matches / tp axis absent.  A matched dim that the tp axis doesn't
    divide (e.g. a 1-head debug model under tp=2) replicates instead of
    producing an invalid sharding."""
    tp = axis_size(mesh, AXIS_TP)
    if tp <= 1:
        return None
    ndim = len(shape)
    for pattern, rule in _TP_RULES:
        if re.search(pattern, path):
            dim = rule["shard_dim"]
            if dim is None or dim >= ndim or shape[dim] % tp:
                return P()
            spec = [None] * ndim
            spec[dim] = AXIS_TP
            return P(*spec)
    return None


def ep_spec_for_path(path: str, shape, mesh: Mesh) -> Optional[P]:
    from .mesh import AXIS_EP

    ep = axis_size(mesh, AXIS_EP)
    if ep <= 1:
        return None
    ndim = len(shape)
    for pattern, dim in _EP_RULES:
        if re.search(pattern, path):
            spec = [None] * ndim
            if dim < ndim and shape[dim] % ep == 0:
                spec[dim] = AXIS_EP
            return P(*spec)
    return None


def combined_spec(path: str, shape, mesh: Mesh) -> P:
    """EP/TP rule first; then FSDP-shard the largest remaining divisible dim."""
    ndim = len(shape)
    spec = ep_spec_for_path(path, shape, mesh)
    if spec is None:
        spec = tp_spec_for_path(path, shape, mesh)
    parts = list(spec) if spec is not None else [None] * ndim
    while len(parts) < ndim:
        parts.append(None)
    fsdp = axis_size(mesh, AXIS_FSDP)
    if fsdp > 1:
        candidates = [
            i for i, d in enumerate(shape)
            if parts[i] is None and d % fsdp == 0 and d >= fsdp
        ]
        if candidates:
            # Largest dim gives the most memory savings.
            dim = max(candidates, key=lambda i: shape[i])
            parts[dim] = AXIS_FSDP
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def _flatten_paths(tree):
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    for key_path, leaf in flat:
        path = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
        )
        yield path, leaf


def make_param_shardings(params, mesh: Mesh):
    """Pytree of NamedShardings matching `params` (tp + fsdp rules)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    shardings = []
    for key_path, leaf in flat:
        path = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in key_path
        )
        spec = combined_spec(path, getattr(leaf, "shape", ()), mesh)
        shardings.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, shardings)


def shard_params(params, mesh: Mesh):
    """device_put params according to the combined tp+fsdp rules."""
    return jax.device_put(params, make_param_shardings(params, mesh))
