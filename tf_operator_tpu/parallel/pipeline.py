"""Pipeline parallelism: SPMD GPipe over the `pp` mesh axis.

Completes the parallelism matrix (SURVEY.md §2.9: PP absent from the
reference).  Collective-based GPipe, not per-device programs: every device
runs the same jitted program; stage s of the model lives on pp-rank s
(stage-stacked params sharded on their leading dim), and activations hop one
ICI neighbor per step via `ppermute`.  With M microbatches and P stages the
schedule takes M+P-1 steps (bubble fraction (P-1)/(M+P-1)); all shapes are
static and the whole schedule is a single `lax.fori_loop` under `shard_map`
— XLA sees one compiled program per device, compiler-friendly by
construction.

stage_fn must be shape-preserving (activation in == activation out), which
transformer blocks are; embedding/head run outside the pipelined region.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import shard_map


def _stage_param_specs(stacked_params, axis: str):
    """P(axis) on the leading (stage) dim of every stage-stacked leaf —
    the one sharding contract all three schedules share."""
    return jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )


def _split_microbatches(x: jax.Array, num_microbatches: int) -> jax.Array:
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by microbatches {num_microbatches}")
    return x.reshape(num_microbatches, batch // num_microbatches, *x.shape[1:])


def gpipe(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Run `stage_fn` as a P-stage pipeline.

    stacked_params: pytree whose leaves have leading dim P (one slice per
    stage), sharded over `axis`.  x: [batch, ...] activations entering stage
    0.  Returns activations leaving stage P-1, same shape as x.
    """
    num_stages = mesh.shape[axis]
    batch = x.shape[0]
    x_mb = _split_microbatches(x, num_microbatches)

    def local(params, x_mb):
        rank = lax.axis_index(axis)
        num_mb = x_mb.shape[0]
        params = jax.tree_util.tree_map(lambda p: p[0], params)  # squeeze stage dim
        out = jnp.zeros_like(x_mb)
        carry_in = jnp.zeros_like(x_mb[0])
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def step(s, state):
            carry_in, out = state
            feed = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(s, 0, num_mb - 1), axis=0, keepdims=False
            )
            inp = jnp.where(rank == 0, feed, carry_in)
            act = stage_fn(params, inp)
            valid = jnp.logical_and(s - rank >= 0, s - rank < num_mb)
            act = jnp.where(valid, act, jnp.zeros_like(act))
            write_idx = jnp.clip(s - (num_stages - 1), 0, num_mb - 1)
            current = lax.dynamic_index_in_dim(out, write_idx, axis=0, keepdims=False)
            is_writer = jnp.logical_and(valid, rank == num_stages - 1)
            new_row = jnp.where(is_writer, act, current)
            out = lax.dynamic_update_index_in_dim(out, new_row, write_idx, axis=0)
            carry_next = lax.ppermute(act, axis, perm)
            return carry_next, out

        _, out = lax.fori_loop(0, num_mb + num_stages - 1, step, (carry_in, out))
        return lax.psum(out, axis)

    param_specs = _stage_param_specs(stacked_params, axis)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_rep=False,
    )
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape(batch, *x.shape[1:])


def gpipe_interleaved(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Interleaved (virtual-stage) pipeline forward: each rank holds V model
    CHUNKS instead of one contiguous stage (chunk g = v·P + r lives on rank
    r as its v-th slice), so activations traverse the ring V times and each
    pipeline step costs 1/V of a full stage.  Total steps V·P + M − 1 at
    1/V stage-cost each ≈ (P + (M−1)/V)·T_stage wall-clock vs GPipe's
    (M + P − 1)·T_stage — the warmup/cooldown bubble shrinks by ~V (the
    Megatron-LM interleaved-schedule idea, arXiv:2104.04473 §2.2).

    stacked_params: leaves [P, V, ...] (P sharded over `axis`); stage_fn
    receives one chunk's [...] slice and must be shape-preserving.

    Schedule invariant: work item (microbatch m, chunk-phase v) runs on
    rank r at step s = v·P + r + m.  Requiring M <= P makes the item per
    (rank, step) UNIQUE (two candidates would need microbatch indices P
    apart), so every rank runs one chunk per step with the same single
    ppermute ring as gpipe — rank P−1's chunk-v output arrives at rank 0
    exactly when it becomes that microbatch's chunk-(v+1) input.  For
    M > P use gpipe (or raise V so M = P covers the batch).
    """
    num_stages = mesh.shape[axis]
    virtual = jax.tree_util.tree_leaves(stacked_params)[0].shape[1]
    batch = x.shape[0]
    if num_microbatches > num_stages:
        raise ValueError(
            f"interleaved schedule needs microbatches ({num_microbatches}) "
            f"<= pipeline stages ({num_stages}); the conflict-free step "
            "assignment (item uniqueness per rank per step) depends on it — "
            "use gpipe for deeper microbatching")
    x_mb = _split_microbatches(x, num_microbatches)

    def local(params, x_mb):
        rank = lax.axis_index(axis)
        num_mb = x_mb.shape[0]
        # [1, V, ...] -> [V, ...] per-rank chunk stack
        chunks = jax.tree_util.tree_map(lambda p: p[0], params)
        out = jnp.zeros_like(x_mb)
        carry = jnp.zeros_like(x_mb[0])
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def step(s, state):
            carry, out = state
            q = s - rank
            v = jnp.floor_divide(q, num_stages)
            m = q - v * num_stages  # in [0, P) when q >= 0
            valid = jnp.logical_and(
                jnp.logical_and(v >= 0, v < virtual), m < num_mb)
            m_idx = jnp.clip(m, 0, num_mb - 1)
            v_idx = jnp.clip(v, 0, virtual - 1)
            feed = lax.dynamic_index_in_dim(x_mb, m_idx, 0, keepdims=False)
            # chunk-0 inputs at rank 0 come from the data; every other
            # (rank, chunk) consumes the ring carry — including rank 0's
            # chunk v>0, which is rank P-1's chunk v-1 output (same m, by
            # the schedule invariant)
            inp = jnp.where(jnp.logical_and(rank == 0, v_idx == 0),
                            feed, carry)
            chunk = jax.tree_util.tree_map(
                lambda c: lax.dynamic_index_in_dim(c, v_idx, 0,
                                                   keepdims=False),
                chunks)
            act = stage_fn(chunk, inp)
            act = jnp.where(valid, act, jnp.zeros_like(act))
            is_writer = jnp.logical_and(
                valid,
                jnp.logical_and(rank == num_stages - 1,
                                v_idx == virtual - 1))
            cur = lax.dynamic_index_in_dim(out, m_idx, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(is_writer, act, cur), m_idx, 0)
            return lax.ppermute(act, axis, perm), out

        _, out = lax.fori_loop(
            0, virtual * num_stages + num_mb - 1, step, (carry, out))
        return lax.psum(out, axis)

    param_specs = _stage_param_specs(stacked_params, axis)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_rep=False,
    )
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape(batch, *x.shape[1:])


# ---------------------------------------------------------------------------
# 1F1B (one-forward-one-backward) schedule


def one_f_one_b(
    stage_fn: Callable,
    head_loss_fn: Callable,
    stacked_params,
    head_params,
    x: jax.Array,
    y: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Fused-forward/backward pipeline: returns the scalar mean loss.

    Where `gpipe` pipelines the forward and leaves the backward to autodiff
    (which replays all M microbatches' residuals — O(M) activation memory),
    this schedule interleaves one forward and one backward per cycle in a
    single `lax.fori_loop`: rank r runs the forward of microbatch c-r and
    the backward of microbatch c-2(P-1)+r at cycle c, so at most O(P)
    microbatch inputs are ever live (a circular 2P-slot buffer); each
    backward re-runs its stage forward from the saved input to build the
    VJP (recompute-style, the TPU-friendly trade of FLOPs for HBM).  The
    last rank computes `head_loss_fn` and seeds the backward in the same
    cycle its forward finishes — the 1F1B property.  Total cycles:
    M + 2(P-1).

    stage_fn(params_r, act) -> act (shape-preserving, as for gpipe).
    head_loss_fn(head_params, act, y_mb) -> scalar mean loss per microbatch.
    x: [batch, ...] activations entering stage 0 (embedding applied by the
    caller so its gradient flows through x's cotangent, weight tying
    included).  y: [batch, ...] targets, any dtype (int fine).

    Implemented as a custom_vjp whose forward computes loss AND all grads in
    the fused loop; the backward just scales them by the (scalar) cotangent,
    so the op composes with outer autodiff/jit like any other loss term.
    """
    num_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(
            f"batch {batch} not divisible by microbatches {num_microbatches}")
    mb = batch // num_microbatches
    x_mb = x.reshape(num_microbatches, mb, *x.shape[1:])
    y_mb = y.reshape(num_microbatches, mb, *y.shape[1:])

    def _fused(stages, head, x_mb, y_mb):
        """shard_map body: (loss, dstages_local, dhead, dx) on every rank."""
        rank = lax.axis_index(axis)
        num_mb = x_mb.shape[0]
        params_r = jax.tree_util.tree_map(lambda p: p[0], stages)
        is_last = rank == num_stages - 1
        nbuf = 2 * num_stages
        perm_f = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        perm_b = [(i, (i - 1) % num_stages) for i in range(num_stages)]
        inv_m = jnp.float32(1.0 / num_mb)

        def cycle(c, state):
            (res_buf, dx_buf, dstages, dhead, carry_f, carry_b,
             loss_acc) = state
            # ---- forward of microbatch f = c - rank ----------------------
            f = c - rank
            f_valid = jnp.logical_and(f >= 0, f < num_mb)
            f_idx = jnp.clip(f, 0, num_mb - 1)
            feed = lax.dynamic_index_in_dim(x_mb, f_idx, 0, keepdims=False)
            inp = jnp.where(rank == 0, feed, carry_f)
            act = stage_fn(params_r, inp)
            act = jnp.where(f_valid, act, jnp.zeros_like(act))
            # save the stage INPUT for the recompute-VJP at backward time;
            # an invalid (warmup/cooldown) forward must leave the slot
            # untouched — its clipped index aliases a live microbatch's slot
            slot_f = lax.rem(f_idx, nbuf)
            cur_slot = lax.dynamic_index_in_dim(
                res_buf, slot_f, 0, keepdims=False)
            res_buf = lax.dynamic_update_index_in_dim(
                res_buf, jnp.where(f_valid, inp, cur_slot), slot_f, 0)
            # ---- head (last rank only): loss + seed cotangent ------------
            y_f = lax.dynamic_index_in_dim(y_mb, f_idx, 0, keepdims=False)

            def do_head(a):
                lv, vjp_h = jax.vjp(
                    lambda hp, aa: head_loss_fn(hp, aa, y_f), head, a)
                dh, seed = vjp_h(inv_m)  # 1/M folds the mean over microbatches
                return lv, dh, seed

            def skip_head(a):
                return (jnp.float32(0.0),
                        jax.tree_util.tree_map(jnp.zeros_like, head),
                        jnp.zeros_like(a))

            lv, dh_f, seed = lax.cond(is_last, do_head, skip_head, act)
            ok_head = jnp.logical_and(f_valid, is_last)
            loss_acc = loss_acc + jnp.where(ok_head, lv, 0.0)
            dhead = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(ok_head, g, jnp.zeros_like(g)),
                dhead, dh_f)
            # ---- backward of microbatch b = c - 2(P-1) + rank ------------
            b = c - 2 * (num_stages - 1) + rank
            b_valid = jnp.logical_and(b >= 0, b < num_mb)
            b_idx = jnp.clip(b, 0, num_mb - 1)
            saved_inp = lax.dynamic_index_in_dim(
                res_buf, lax.rem(b_idx, nbuf), 0, keepdims=False)
            # last rank: b == f this cycle, seed is fresh; others: from ring
            cot = jnp.where(is_last, seed.astype(carry_b.dtype), carry_b)
            _, vjp_s = jax.vjp(
                lambda pr, i: stage_fn(pr, i), params_r, saved_inp)
            dpr, dinp = vjp_s(cot.astype(act.dtype))
            dstages = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(b_valid, g, jnp.zeros_like(g)),
                dstages, dpr)
            dinp = jnp.where(b_valid, dinp, jnp.zeros_like(dinp))
            # rank 0's dinp is the cotangent of x_mb[b]
            cur = lax.dynamic_index_in_dim(dx_buf, b_idx, 0, keepdims=False)
            row = jnp.where(jnp.logical_and(rank == 0, b_valid), dinp, cur)
            dx_buf = lax.dynamic_update_index_in_dim(dx_buf, row, b_idx, 0)
            # ---- ring hops -----------------------------------------------
            carry_f = lax.ppermute(act, axis, perm_f)
            carry_b = lax.ppermute(dinp, axis, perm_b)
            return (res_buf, dx_buf, dstages, dhead, carry_f, carry_b,
                    loss_acc)

        init = (
            jnp.zeros((nbuf, *x_mb.shape[1:]), x_mb.dtype),
            jnp.zeros_like(x_mb),
            jax.tree_util.tree_map(jnp.zeros_like, params_r),
            jax.tree_util.tree_map(jnp.zeros_like, head),
            jnp.zeros(x_mb.shape[1:], x_mb.dtype),
            jnp.zeros(x_mb.shape[1:], x_mb.dtype),
            jnp.float32(0.0),
        )
        total = num_mb + 2 * (num_stages - 1)
        (_, dx_buf, dstages, dhead, _, _, loss_acc) = lax.fori_loop(
            0, total, cycle, init)
        loss = lax.psum(loss_acc, axis) * inv_m
        dhead = lax.psum(dhead, axis)
        dx = lax.psum(dx_buf, axis)
        # each rank's stage grads go back stacked on the pp axis
        dstages = jax.tree_util.tree_map(lambda t: t[None], dstages)
        return loss, dstages, dhead, dx

    param_specs = _stage_param_specs(stacked_params, axis)
    head_specs = jax.tree_util.tree_map(lambda p: P(), head_params)
    fused = shard_map(
        _fused,
        mesh=mesh,
        in_specs=(param_specs, head_specs, P(), P()),
        out_specs=(P(), param_specs, head_specs, P()),
        check_rep=False,
    )

    @jax.custom_vjp
    def pipeline_loss(stages, head, x_mb):
        # Primal (loss-only) path: a plain forward pipeline — the fused
        # loop's grad accumulators are loop-carried state XLA cannot
        # dead-code-eliminate, so running it here would pay ~3x forward
        # FLOPs for an evaluation.  The fused loop runs only under
        # differentiation (pipeline_loss_fwd).
        x_flat = x_mb.reshape(batch, *x_mb.shape[2:])
        acts = gpipe(stage_fn, stages, x_flat, mesh, num_microbatches, axis)
        acts_mb = acts.reshape(num_microbatches, mb, *acts.shape[1:])
        per_mb = jax.vmap(lambda a, t: head_loss_fn(head, a, t))(acts_mb, y_mb)
        return jnp.mean(per_mb)

    def pipeline_loss_fwd(stages, head, x_mb):
        loss, dstages, dhead, dx = fused(stages, head, x_mb, y_mb)
        return loss, (dstages, dhead, dx)

    def pipeline_loss_bwd(res, g):
        dstages, dhead, dx = res
        scale = lambda t: (t * g).astype(t.dtype)  # noqa: E731
        return (jax.tree_util.tree_map(scale, dstages),
                jax.tree_util.tree_map(scale, dhead),
                scale(dx))

    pipeline_loss.defvjp(pipeline_loss_fwd, pipeline_loss_bwd)
    return pipeline_loss(stacked_params, head_params, x_mb)
