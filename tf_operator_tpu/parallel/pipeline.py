"""Pipeline parallelism: SPMD GPipe over the `pp` mesh axis.

Completes the parallelism matrix (SURVEY.md §2.9: PP absent from the
reference).  Collective-based GPipe, not per-device programs: every device
runs the same jitted program; stage s of the model lives on pp-rank s
(stage-stacked params sharded on their leading dim), and activations hop one
ICI neighbor per step via `ppermute`.  With M microbatches and P stages the
schedule takes M+P-1 steps (bubble fraction (P-1)/(M+P-1)); all shapes are
static and the whole schedule is a single `lax.fori_loop` under `shard_map`
— XLA sees one compiled program per device, compiler-friendly by
construction.

stage_fn must be shape-preserving (activation in == activation out), which
transformer blocks are; embedding/head run outside the pipelined region.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import shard_map


def gpipe(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Run `stage_fn` as a P-stage pipeline.

    stacked_params: pytree whose leaves have leading dim P (one slice per
    stage), sharded over `axis`.  x: [batch, ...] activations entering stage
    0.  Returns activations leaving stage P-1, same shape as x.
    """
    num_stages = mesh.shape[axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by microbatches {num_microbatches}")
    x_mb = x.reshape(num_microbatches, batch // num_microbatches, *x.shape[1:])

    def local(params, x_mb):
        rank = lax.axis_index(axis)
        num_mb = x_mb.shape[0]
        params = jax.tree_util.tree_map(lambda p: p[0], params)  # squeeze stage dim
        out = jnp.zeros_like(x_mb)
        carry_in = jnp.zeros_like(x_mb[0])
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        def step(s, state):
            carry_in, out = state
            feed = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(s, 0, num_mb - 1), axis=0, keepdims=False
            )
            inp = jnp.where(rank == 0, feed, carry_in)
            act = stage_fn(params, inp)
            valid = jnp.logical_and(s - rank >= 0, s - rank < num_mb)
            act = jnp.where(valid, act, jnp.zeros_like(act))
            write_idx = jnp.clip(s - (num_stages - 1), 0, num_mb - 1)
            current = lax.dynamic_index_in_dim(out, write_idx, axis=0, keepdims=False)
            is_writer = jnp.logical_and(valid, rank == num_stages - 1)
            new_row = jnp.where(is_writer, act, current)
            out = lax.dynamic_update_index_in_dim(out, new_row, write_idx, axis=0)
            carry_next = lax.ppermute(act, axis, perm)
            return carry_next, out

        _, out = lax.fori_loop(0, num_mb + num_stages - 1, step, (carry_in, out))
        return lax.psum(out, axis)

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params
    )
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
        check_rep=False,
    )
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape(batch, *x.shape[1:])
