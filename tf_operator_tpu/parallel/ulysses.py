"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second first-class long-context strategy next to ring attention
(parallel/ring_attention.py).  Where the ring keeps queries resident and
rotates K/V blocks hop by hop (N ppermute steps, O(T/N · T/N) score memory),
Ulysses (DeepSpeed-Ulysses, arXiv:2309.14509) performs ONE all-to-all that
re-shards activations from sequence-sharded [B, H, T/N, D] to head-sharded
[B, H/N, T, D], runs full-sequence attention locally on the private heads
(the Pallas flash kernel on TPU), and all-to-alls back.  Trade-offs:

- collectives: 3 all-to-alls in + 1 out per attention vs N ppermutes —
  fewer, larger transfers; on a TPU torus all-to-all rides ICI efficiently.
- constraint: the head axes must divide by the sp axis size (ring has no
  head constraint; it shards T only).
- memory: full-T scores per private head — flash keeps that O(block·T), so
  both strategies stay linear in T per device with the kernel.

Which wins depends on interconnect and shape; the framework exposes both
behind `TransformerConfig.seq_parallel = "ring" | "ulysses"` and the same
`sp` mesh axis, so switching strategies is a config flip, not a rewrite.

GQA: if kv heads also divide by sp they stay grouped end-to-end (each
device attends its private query heads against its private kv heads —
query-to-kv-group alignment is preserved because the all-to-all splits both
head axes by the same factor in order).  If kv_heads < sp (can't split),
K/V are widened to query heads first — correct, at repeat-in-HBM cost.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import check_gqa, flash_attention, repeat_kv, xla_attention
from .ring_attention import shard_map


def _local_attend(q, k, v, causal: bool, scale: float, use_flash: bool):
    if use_flash:
        return flash_attention(q, k, v, causal, scale)
    return xla_attention(q, *repeat_kv(q, k, v), causal=causal, scale=scale)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    scale: Optional[float] = None,
    use_flash: bool = True,
) -> jax.Array:
    """Exact attention with the sequence axis sharded over `axis_name`,
    exchanged to head-sharding for the local compute.

    Inputs are global arrays [B, H, T, D] (sharded or to-be-sharded on T);
    output matches q's shape/dtype.  Requires T % sp == 0 and
    num_heads % sp == 0; grouped k/v heads must divide by sp too, else they
    are widened to q's head count before the exchange.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    check_gqa(q, k)
    sp = mesh.shape[axis_name]
    b, h, t, d = q.shape
    if h % sp:
        raise ValueError(
            f"ulysses_attention needs num_heads ({h}) divisible by the "
            f"{axis_name!r} axis size ({sp}); use ring attention for "
            "head-count-constrained shapes")
    if t % sp:
        raise ValueError(f"sequence length {t} not divisible by {axis_name} "
                         f"axis size {sp}")
    if k.shape[1] % sp:
        # kv group too small to split across sp: widen to MHA up front.
        k, v = repeat_kv(q, k, v)

    spec = P(None, None, axis_name, None)

    def local(q_blk, k_blk, v_blk):
        # [B, H, T/N, D] -> (split heads, gather sequence) -> [B, H/N, T, D]
        qh, kh, vh = (
            lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                           tiled=True)
            for x in (q_blk, k_blk, v_blk)
        )
        out = _local_attend(qh, kh, vh, causal, scale, use_flash)
        # [B, H/N, T, D] -> (split sequence, gather heads) -> [B, H, T/N, D]
        return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    return shard_map(
        local, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
