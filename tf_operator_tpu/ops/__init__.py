"""Subpackage."""
