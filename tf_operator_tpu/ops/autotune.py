"""Flash-kernel block-size autotuning.

The Pallas kernels (ops/attention.py) take block_q/block_k grid parameters;
128x128 is a reasonable static default for the v5e MXU/VMEM, but the best
tiling depends on sequence length, head count, and dtype — and a wrong
tiling can leave the kernel slower than stock XLA attention.  This module
measures instead of guessing: it times compiled fwd+bwd at candidate block
shapes on the CURRENT backend and returns the winner.

Tuned blocks propagate two ways:

- explicitly: `flash_attention(..., block_q=bq, block_k=bk)`;
- ambiently: `TPUJOB_FLASH_BLOCK_Q` / `TPUJOB_FLASH_BLOCK_K` env vars, read
  by `default_blocks()` in ops/attention.py when callers leave the block
  arguments at their defaults — so a workload picks up a tuned config
  without any plumbing through model/config layers (the env is read at
  trace time, consistent within a compiled program).

Results are cached in-process by shape signature and, when
`TPUJOB_AUTOTUNE_CACHE` names a JSON file, across processes — the bench's
attention ladder (bench.py child_attention) tunes automatically when the
default tiling fails to beat XLA on chip and records both numbers.

Candidates keep the Mosaic tiling contract: every block dimension is a
multiple of (8, 128) for the (sublane, lane) axes — see
/opt/skills/guides/pallas_guide.md and the round-2 lse BlockSpec bug.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Tuple

# (block_q, block_k) search space: powers of two in the lane-legal range.
# 128 is the lane width; larger blocks amortize grid overhead but raise
# VMEM pressure (block_q*d + block_k*d + block_q*block_k scratch).
DEFAULT_CANDIDATES: List[Tuple[int, int]] = [
    (128, 128), (256, 128), (128, 256), (256, 256),
    (512, 128), (128, 512), (512, 256), (256, 512), (512, 512),
]

# shape signature -> result dict
_CACHE: Dict[tuple, dict] = {}

# memoized kernel-source digest (None = not yet computed)
_KERNEL_HASH: Optional[str] = None


def _cache_path() -> Optional[str]:
    # bench-operator-set cache location, never injected by gen_tpu_env
    return os.environ.get("TPUJOB_AUTOTUNE_CACHE") or None  # contract: exempt(knob-chain)


def _kernel_source_hash() -> str:
    """sha256 (truncated) over ops/attention.py's source bytes.  Part of
    every cache key: tuned block shapes are only valid for the kernel
    they were measured on, and a persisted TPUJOB_AUTOTUNE_CACHE entry
    silently reused across a kernel edit is a perf heisenbug factory —
    the edit changes VMEM footprint/grid behavior but the stale winner
    keeps being applied."""
    global _KERNEL_HASH
    if _KERNEL_HASH is None:
        try:
            from . import attention

            with open(attention.__file__, "rb") as f:
                _KERNEL_HASH = hashlib.sha256(f.read()).hexdigest()[:16]
        except (OSError, ImportError):
            # pyc-only / frozen installs: the guard is inactive, which must
            # not be silent — stale tuned blocks would survive kernel
            # upgrades with no signal.
            import logging

            logging.getLogger(__name__).warning(
                "autotune: ops/attention.py source unreadable; kernel-edit "
                "cache invalidation is DISABLED for this process (persisted "
                "TPUJOB_AUTOTUNE_CACHE entries may be stale across kernel "
                "changes)")
            _KERNEL_HASH = "unknown"
    return _KERNEL_HASH


def _signature(backend, b, h, kv_h, t, d, causal, dtype,
               candidates, reps) -> tuple:
    # backend is part of the key: a CPU run times the XLA fallback (every
    # candidate ties, winner is noise) and must never be served to a TPU
    # run from a shared cache file; candidates/reps too — a result is only
    # valid for the search it came from; the kernel-source hash so a
    # kernel edit invalidates every persisted entry.
    return (backend, b, h, kv_h, t, d, bool(causal), str(dtype),
            tuple(map(tuple, candidates)), reps, _kernel_source_hash())


def _load_persistent(sig: tuple) -> Optional[dict]:
    path = _cache_path()
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            table = json.load(f)
        return table.get(json.dumps(list(sig)))
    except (OSError, ValueError):
        return None


def _store_persistent(sig: tuple, result: dict) -> None:
    path = _cache_path()
    if not path:
        return
    table = {}
    try:
        if os.path.exists(path):
            with open(path) as f:
                table = json.load(f)
    except (OSError, ValueError):
        table = {}
    table[json.dumps(list(sig))] = result
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1)
        os.replace(tmp, path)
    except OSError:
        pass


def tune_flash_blocks(
    b: int, h: int, t: int, d: int,
    *,
    kv_h: Optional[int] = None,
    causal: bool = True,
    dtype=None,
    reps: int = 3,
    candidates: Optional[List[Tuple[int, int]]] = None,
) -> dict:
    """Time compiled flash fwd+bwd per candidate block shape; return
    {"block_q", "block_k", "ms", "table": [{"block_q","block_k","ms"|"error"}]}.

    Runs on whatever backend is active — only meaningful on TPU (off-TPU the
    public entry point bypasses the kernel entirely; this function times the
    custom-vjp'd kernel path directly so CPU tests exercise the machinery).
    Results are cached by shape signature (in-process + optional JSON file).
    """
    import jax
    import jax.numpy as jnp

    from .attention import _flash_attention_tpu, _on_tpu, xla_attention

    dtype = dtype or jnp.bfloat16
    kv_h = kv_h or h
    sig = _signature(jax.default_backend(), b, h, kv_h, t, d, causal,
                     jnp.dtype(dtype).name, candidates or DEFAULT_CANDIDATES,
                     reps)
    if sig in _CACHE:
        return _CACHE[sig]
    persisted = _load_persistent(sig)
    if persisted is not None:
        _CACHE[sig] = persisted
        return persisted

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, h, t, d)).astype(dtype)
    k = jax.random.normal(kk, (b, kv_h, t, d)).astype(dtype)
    v = jax.random.normal(kv, (b, kv_h, t, d)).astype(dtype)

    if _on_tpu():
        def attend(q, k, v, bq, bk):
            return _flash_attention_tpu(q, k, v, causal, None, bq, bk)
    else:
        # Off-TPU there is no kernel to tune; time the fallback so the
        # harness itself stays testable (all candidates tie, modulo noise).
        def attend(q, k, v, bq, bk):
            from .attention import repeat_kv

            return xla_attention(q, *repeat_kv(q, k, v), causal=causal)

    table = []
    best = None
    for bq, bk in candidates or DEFAULT_CANDIDATES:
        if bq > t or bk > t:
            continue
        try:
            grad = jax.jit(jax.grad(
                lambda q, k, v, bq=bq, bk=bk: jnp.sum(
                    attend(q, k, v, bq, bk).astype(jnp.float32)),
                argnums=(0, 1, 2)))
            out = grad(q, k, v)  # compile
            jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
            t0 = time.perf_counter()
            for _ in range(reps):
                out = grad(q, k, v)
            jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
            ms = (time.perf_counter() - t0) / reps * 1e3
            table.append({"block_q": bq, "block_k": bk, "ms": round(ms, 3)})
            if best is None or ms < best[0]:
                best = (ms, bq, bk)
        except Exception as e:  # noqa: BLE001  # lint: allow(swallow) — the error is recorded in the table row below, not dropped
            table.append({"block_q": bq, "block_k": bk,
                          "error": repr(e)[:160]})
    if best is None:
        result = {"error": "no candidate compiled", "table": table}
    else:
        result = {"block_q": best[1], "block_k": best[2],
                  "ms": round(best[0], 3), "table": table}
    _CACHE[sig] = result
    _store_persistent(sig, result)
    return result
