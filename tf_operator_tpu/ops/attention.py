"""Fused (flash) attention for TPU via Pallas, with a pure-XLA fallback.

The reference operator ships no kernels (its math lives in user containers —
SURVEY.md §2); this framework owns the compute path, so the hot op gets a
TPU kernel: blockwise online-softmax attention (Flash-style) that keeps the
O(T²) score matrix out of HBM, tiled to the MXU (128-aligned blocks, bf16
inputs, f32 accumulation).

Layout: q/k/v are [batch, heads, seq, head_dim]. The grid maps one program
per (batch·head, q-block); K/V for that head stay resident in VMEM and are
walked block-by-block with `lax.fori_loop` (static trip count — no dynamic
shapes under jit).

The backward pass currently recomputes through the XLA fallback (correct,
O(T²) memory at grad time); a Pallas backward is a planned optimization.
Sequence-parallel long-context attention lives in parallel/ring_attention.py
and composes with this kernel per-shard.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU backend only
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool,
                  block_q: int, block_k: int, seq_len: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
    num_kb = seq_len // block_k

    rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(kb, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + rows
            k_pos = kb * block_k + cols
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q_ref.shape[-1]), jnp.float32)
    if causal:
        # Blocks strictly above the diagonal contribute nothing; bound the
        # walk at the q-block's last row (static grid, traced bound is fine
        # for fori_loop).
        num_iters = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        num_iters = jnp.minimum(num_iters, num_kb)
    else:
        num_iters = num_kb
    m, l, acc = lax.fori_loop(0, num_iters, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_forward(q, k, v, scale: float, causal: bool,
                   block_q: int, block_k: int, interpret: bool):
    batch, heads, seq_len, head_dim = q.shape
    bh = batch * heads
    qf = q.reshape(bh, seq_len, head_dim)
    kf = k.reshape(bh, seq_len, head_dim)
    vf = v.reshape(bh, seq_len, head_dim)

    block_q = min(block_q, seq_len)
    block_k = min(block_k, seq_len)
    if seq_len % block_q or seq_len % block_k:
        raise ValueError(f"seq_len {seq_len} must be divisible by block sizes")

    grid = (bh, seq_len // block_q)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, seq_len=seq_len,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, seq_len, head_dim), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, seq_len, head_dim), lambda b, i: (b, 0, 0)),
    ]
    out_spec = pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0))
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_spec,
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(batch, heads, seq_len, head_dim)


def xla_attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None):
    """Plain-XLA attention (fallback + backward recompute path)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        t_q, t_k = logits.shape[-2:]
        rows = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        cols = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        logits = jnp.where(rows >= cols, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v).astype(q.dtype)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:  # pragma: no cover
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=True, scale=None, block_q=128, block_k=128):
    """Fused attention; Pallas kernel on TPU, XLA fallback elsewhere."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if _on_tpu():
        return _flash_forward(q, k, v, s, causal, block_q, block_k, interpret=False)
    return xla_attention(q, k, v, causal=causal, scale=s)


def _fwd(q, k, v, causal, scale, block_q, block_k):
    out = flash_attention(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v)


def _bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: xla_attention(q, k, v, causal=causal, scale=scale), q, k, v
    )
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def flash_attention_interpret(q, k, v, causal=True, scale=None,
                              block_q=128, block_k=128):
    """Interpreter-mode kernel execution (CPU correctness tests)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_forward(q, k, v, s, causal, block_q, block_k, interpret=True)
