"""Fused (flash) attention for TPU via Pallas, with a pure-XLA fallback.

The reference operator ships no kernels (its math lives in user containers —
SURVEY.md §2); this framework owns the compute path, so the hot op gets a
TPU kernel: blockwise online-softmax attention (Flash-style) that keeps the
O(T²) score matrix out of HBM, tiled to the MXU (128-aligned blocks, bf16
inputs, f32 accumulation).

Layout: q/k/v are [batch, heads, seq, head_dim]. Each kernel runs on a 3-D
grid — (batch·head, q-block, k-block) for the forward and dq, (batch·head,
k-block, q-block) for dk/dv — with the reduction dimension innermost and
"arbitrary" semantics: running state (online-softmax m/l/acc, grad
accumulators) lives in f32 VMEM scratch that persists across the innermost
grid steps, is initialised when the reduction index is 0 and written out on
its last step. Only one (block, head_dim) tile of each operand is resident
per step, so VMEM use is independent of sequence length and the DMA
pipeline overlaps the next block's fetch with the current block's matmuls
(the same structure as jax's stock TPU flash kernel). Causally-dead blocks
skip their FLOPs via pl.when but still advance the pipeline.

Backward is a Pallas kernel pair (FlashAttention-2 style, recompute-free in
HBM terms): the forward saves per-row logsumexp; dq accumulates over
K-blocks per Q-block, dk/dv accumulate over Q-blocks per K-block, each
rebuilding P from (q,k,lse) in VMEM so the O(T²) probability matrix never
materializes at grad time. Off-TPU the whole op (fwd+bwd) is plain XLA.

Per-row scalars (lse, delta) cross the kernel boundary **lane-replicated**
as [batch·heads, seq, 128] tiles: Mosaic requires the last two dims of
every block to be (multiple-of-8, multiple-of-128) or equal to the array
dims, so a [rows] vector per q-block is stored as a (block_q, 128) tile
with the value repeated across lanes — the same layout jax's reference TPU
flash kernel uses for its l/m outputs. A (1, block_q) row-block violates
the tiling constraint and fails Mosaic lowering (round-2 VERDICT finding;
repro log in artifacts/flash_repro_r03_before.log). Between fwd and bwd the
lse residual is carried compact at [bh, Tp] (lane 0 sliced off right after
the forward pallas_call) and re-broadcast at the backward's boundary, so
the replication never inflates saved-activation HBM.

Sequence lengths that don't divide the block size are zero-padded to the
next block boundary; padded key positions are masked with -inf inside the
kernels and padded query rows are sliced off, so any seq_len works.

Grouped-query attention is native: k/v may carry fewer heads than q
(heads % kv_heads == 0) and the kernels map each query head to its KV head
through the BlockSpec index maps — k/v are never repeated in HBM, and
dk/dv accumulate over the whole query group inside the dk/dv kernel (its
innermost grid dim runs group × q-blocks), so the fwd+bwd K/V traffic is
1/group of the repeat-outside approach the pure-XLA fallback uses.

Sliding-window (local) attention is a first-class mask mode: `window=w`
restricts each query to its last w keys (requires causal).  The reduction
grids themselves are BANDED when the window is shorter than the sequence
(_k_band/_q_band): each q-block's grid only iterates the static-length
band of k-blocks that can contain live positions (and dk/dv's per-k-block
grid only its q-band), with the true block index recovered from the grid
step and the overhang (up to band-1 steps where the band hangs off the
array edge) clamped in the index map and skipped by pl.when.  Out-of-band
blocks are therefore never even DMA'd — both FLOPs and K/V traffic drop
from O(T^2) to O(T*w), which is the long-context win on TPU (VMEM use was
already sequence-independent).

Attention sinks (`sink=s`, StreamingLLM-style) keep the first s absolute
positions visible to every query on top of the window.  The fwd/dq grids
gain a ceil(s/block_k)-step PREFIX mapping to the sink blocks before the
band (with a dedup guard where they overlap), so a tiny sink costs one
extra grid step; dk/dv reverts to the full grid + liveness skip (sink
k-blocks are attended by every q-block, so no contiguous q-band exists).

Sequence-parallel long-context attention lives in parallel/ring_attention.py
and composes with this kernel per-shard.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# Importable on any platform (CPU interpret mode included); only kernel
# *compilation* needs TPU hardware.
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANE = 128  # TPU vector lane width; minor dim of every row-scalar tile


def _cols(x, width: int):
    """Expand a lane-replicated [rows, LANE] tile to [rows, width].

    Every lane holds the same per-row scalar, so slicing or tiling along
    lanes preserves the value while matching the target tile's width.
    """
    lanes = x.shape[-1]
    if width == lanes:
        return x
    if width < lanes:
        return x[:, :width]
    reps = (width + lanes - 1) // lanes
    return jnp.tile(x, (1, reps))[:, :width]


def _causal_live(qi, ki, block_q: int, block_k: int):
    """Whether block (qi, ki) has any unmasked position under the causal
    mask: its last q row sees at least the first k column."""
    return (qi + 1) * block_q - 1 >= ki * block_k


def _block_live(qi, ki, block_q: int, block_k: int, causal: bool,
                window: Optional[int], sink: int = 0):
    """Whether block (qi, ki) has any unmasked position under the causal
    and/or sliding-window(+sink) masks — the grid-level FLOP-skip
    predicate.

    The sliding window keeps q→k distances 0 <= q_pos - k_pos < window
    (Mistral-style local attention; window implies causal — enforced at
    the public entries).  A block is window-live when its *smallest*
    achievable distance, first q row minus last k column, is < window;
    with both masks, compute per q-block touches O(window) keys instead
    of O(T), so the kernel's work drops from O(T^2) to O(T*window).

    `sink` (StreamingLLM-style attention sinks) additionally keeps the
    first `sink` absolute key positions live for every query: a block
    overlapping [0, sink) stays live regardless of distance."""
    live = _causal_live(qi, ki, block_q, block_k) if causal else True
    if window is not None:
        in_band = qi * block_q - (ki * block_k + block_k - 1) < window
        if sink:
            in_band = jnp.logical_or(in_band, ki * block_k < sink)
        live = jnp.logical_and(live, in_band)
    return live


def _k_band(window: Optional[int], block_q: int, block_k: int,
            num_kb: int, sink: int = 0) -> Optional[int]:
    """Length of the banded reduction grid over k-blocks for one q-block
    under the sliding window, or None for the full grid.  The live
    k-blocks for q-block i span kb_lo..kb_hi with
    kb_hi = ((i+1)*block_q - 1) // block_k and
    kb_lo = max(0, (i*block_q - window + 1) // block_k), so their count
    is bounded by (block_q + window - 2) // block_k + 2 independent of i —
    a STATIC grid length; the kernels recover the true k-block index from
    (i, j) and skip the overhang (up to k_band-1 steps at the array edge).
    Banding the grid — rather than pl.when alone — is what saves the K/V
    DMA, not just the FLOPs: blocks outside the band are never fetched.

    With attention sinks the reduction grid gets a PREFIX of
    ceil(sink/block_k) steps that map straight to the first k-blocks (the
    sink region), followed by the diagonal band — so a canonical tiny
    sink costs one extra grid step, not the whole O(T^2) grid.  Returns
    the band length EXCLUDING the prefix; callers add _sink_blocks()."""
    if window is None:
        return None
    band = (block_q + window - 2) // block_k + 2
    return band if _sink_blocks(sink, block_k) + band < num_kb else None


def _sink_blocks(sink: int, block_k: int) -> int:
    """Number of k-blocks overlapping the sink prefix [0, sink)."""
    return -(-sink // block_k) if sink else 0


def _q_band(window: Optional[int], block_q: int, block_k: int,
            num_qb: int, sink: int = 0) -> Optional[int]:
    """Banded grid length over q-blocks for one k-block (the dk/dv
    reduction): live q-blocks span qb_lo = (k*block_k) // block_q up to
    the last row within the window, a count bounded by
    (block_k + window - 2) // block_q + 2.  Disabled when sinks are on
    (sink k-blocks are attended by EVERY q-block — no contiguous band)."""
    if window is None or sink:
        return None
    band = (block_k + window - 2) // block_q + 2
    return band if band < num_qb else None


def _band_kb(qi, ki, block_q: int, block_k: int, k_band: int):
    """True k-block index for banded-grid reduction step ki at q-block qi:
    the band ends at the diagonal block kb_hi and extends k_band steps back.
    SHARED by the fwd/dq kernels and their K/V BlockSpec index maps — the
    mask and the DMA must agree on which block a grid step means (the maps
    clamp negative overhang to 0; the kernels skip it via kb >= 0)."""
    return ((qi + 1) * block_q - 1) // block_k - (k_band - 1) + ki


def _recover_kb(qi, ki, block_q: int, block_k: int,
                k_band: Optional[int], sink: int):
    """Grid step -> true k-block index for the fwd/dq kernels: identity on
    the full grid; under a band, sink-prefix steps map straight to the
    first blocks and the rest to the diagonal band."""
    if k_band is None:
        return ki
    sb = _sink_blocks(sink, block_k)
    banded = _band_kb(qi, ki - sb, block_q, block_k, k_band)
    return jnp.where(ki < sb, ki, banded) if sb else banded


def _reduction_live(qi, kb, ki, block_q: int, block_k: int, causal: bool,
                    window: Optional[int], k_band: Optional[int], sink: int):
    """Shared fwd/dq compute-skip predicate: mask liveness for the true
    block kb, plus — on a banded grid — skipping the pre-array overhang
    and any block the sink prefix already processed (dedup)."""
    live = _block_live(qi, kb, block_q, block_k, causal, window, sink)
    if k_band is not None:
        sb = _sink_blocks(sink, block_k)
        live = jnp.logical_and(live, jnp.logical_or(ki < sb, kb >= sb))
    return live


def _kv_block_spec(block_q: int, block_k: int, head_dim: int, group: int,
                   k_band: Optional[int], sink: int = 0):
    """K/V BlockSpec for a (bh, q-block, k-step) grid — full reduction or
    banded.  One definition for the forward and dq passes so their DMA
    index math cannot drift."""
    if k_band is None:
        return pl.BlockSpec(
            (1, block_k, head_dim), lambda b, i, j: (b // group, j, 0)
        )
    sb = _sink_blocks(sink, block_k)

    def kv_map(b, i, j):
        banded = jnp.maximum(
            _band_kb(i, j - sb, block_q, block_k, k_band), 0)
        kb = jnp.where(j < sb, j, banded) if sb else banded
        return (b // group, kb, 0)

    return pl.BlockSpec((1, block_k, head_dim), kv_map)


def _pad_seq(x, block: int):
    """Zero-pad dim -2 (seq) up to a multiple of `block`."""
    seq = x.shape[-2]
    pad = (-seq) % block
    if pad == 0:
        return x
    widths = [(0, 0)] * (x.ndim - 2) + [(0, pad), (0, 0)]
    return jnp.pad(x, widths)


def _compiler_params(interpret: bool, semantics):
    """dimension_semantics hint (parallel/arbitrary per grid dim); ignored
    in interpret mode and absent off-TPU."""
    if interpret:
        return {}
    return {
        "compiler_params": pltpu.CompilerParams(dimension_semantics=semantics)
    }


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale: float,
                causal: bool, window: Optional[int], block_q: int,
                block_k: int, num_kb: int, real_len: int, seq_len: int,
                k_band: Optional[int] = None, sink: int = 0):
    # rest = optional lse output ref, then the 3 VMEM scratch refs
    # (pallas passes refs positionally: inputs, outputs, scratch)
    # num_kb is the reduction-grid LENGTH (the k-band under a sliding
    # window); k_band set means grid step ki maps to true k-block index
    # kb = _band_kb(qi, ki, ...), where negative kb is the (clamped,
    # skipped) overhang — up to k_band-1 steps — before the band enters
    # the array.
    maybe_lse_ref, (m_scr, l_scr, acc_scr) = rest[:-3], rest[-3:]
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    kb = _recover_kb(qi, ki, block_q, block_k, k_band, sink)
    head_dim = q_ref.shape[-1]

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
        k_blk = k_ref[0].astype(jnp.float32)      # [block_k, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        k_pos = kb * block_k + cols
        if causal:
            q_pos = qi * block_q + rows
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if window is not None:
                keep = q_pos - k_pos < window
                if sink:
                    keep = jnp.logical_or(keep, k_pos < sink)
                s = jnp.where(keep, s, NEG_INF)
        if real_len < seq_len:
            s = jnp.where(k_pos < real_len, s, NEG_INF)  # padded keys
        m_prev = m_scr[...]                       # [block_q, LANE] replicated
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)           # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(m_cur, m_prev.shape))
        alpha = jnp.exp(m_prev - m_new)                      # replicated
        p = jnp.exp(s - _cols(m_new, block_k))
        l_new = alpha * l_prev + jnp.broadcast_to(
            jnp.sum(p, axis=-1, keepdims=True), l_prev.shape
        )
        acc_scr[...] = acc_scr[...] * _cols(alpha, head_dim) + (
            jax.lax.dot_general(
                p, v_blk,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        m_scr[...] = m_new
        l_scr[...] = l_new

    if causal:
        # Dead blocks skip FLOPs; pipeline + init/write guards still advance.
        pl.when(_reduction_live(qi, kb, ki, block_q, block_k, causal,
                                window, k_band, sink))(_compute)
    else:
        _compute()

    @pl.when(ki == num_kb - 1)
    def _write():
        m = m_scr[...]
        l = l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / _cols(l_safe, head_dim)).astype(o_ref.dtype)
        if maybe_lse_ref:  # omitted entirely on the primal-only path
            # padded/empty rows keep m=-inf -> store 0 (unused downstream)
            maybe_lse_ref[0][0] = jnp.where(l > 0.0, m + jnp.log(l_safe), 0.0)


def _flash_forward(q, k, v, scale: float, causal: bool,
                   block_q: int, block_k: int, interpret: bool,
                   save_lse: bool = True, window: Optional[int] = None,
                   sink: int = 0):
    """Returns (out [B,H,T,D], lse [B*H, Tp] or None) — lse on the padded
    grid, compacted to one lane outside the kernel (the kernel emits the
    Mosaic-legal lane-replicated tile; carrying the residual at [bh, Tp]
    keeps fwd→bwd HBM at 1/LANE of the tile form). With save_lse=False the
    lse output is omitted entirely (primal-only path writes nothing).

    GQA: k/v may have kv_heads < heads; each query head reads kv head
    h // group through the k/v index maps (flattened: kv index b // group,
    exact because b = bi*H + h and H = Hkv*group)."""
    block_q, block_k = default_blocks(block_q, block_k)
    batch, heads, real_len, head_dim = q.shape
    kv_heads = k.shape[1]
    group = heads // kv_heads
    block_q = min(block_q, max(real_len, 1))
    block_k = min(block_k, max(real_len, 1))
    qf = _pad_seq(q.reshape(batch * heads, real_len, head_dim), block_q)
    kf = _pad_seq(k.reshape(batch * kv_heads, real_len, head_dim), block_k)
    vf = _pad_seq(v.reshape(batch * kv_heads, real_len, head_dim), block_k)
    # one padded length for both axes so the kernel's seq_len is square
    seq_len = max(qf.shape[1], kf.shape[1])
    qf = _pad_seq(qf, seq_len)
    kf = _pad_seq(kf, seq_len)
    vf = _pad_seq(vf, seq_len)
    bh = batch * heads
    num_kb = seq_len // block_k
    # Sliding window: iterate only the k-band per q-block (static length),
    # so out-of-band K/V blocks are never DMA'd — see _k_band.
    k_band = _k_band(window, block_q, block_k, num_kb, sink)
    grid_k = (_sink_blocks(sink, block_k) + k_band
              if k_band is not None else num_kb)

    grid = (bh, seq_len // block_q, grid_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, window=window,
        block_q=block_q, block_k=block_k, num_kb=grid_k, real_len=real_len,
        seq_len=seq_len, k_band=k_band, sink=sink,
    )
    out_shape = [jax.ShapeDtypeStruct(qf.shape, q.dtype)]
    out_specs = [
        pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0))
    ]
    if save_lse:
        out_shape.append(jax.ShapeDtypeStruct((bh, seq_len, LANE), jnp.float32))
        out_specs.append(
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0))
        )
    scratch = [
        pltpu.VMEM((block_q, LANE), jnp.float32),       # m
        pltpu.VMEM((block_q, LANE), jnp.float32),       # l
        pltpu.VMEM((block_q, head_dim), jnp.float32),   # acc
    ]
    kvspec = _kv_block_spec(block_q, block_k, head_dim, group, k_band,
                            sink)
    res = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            kvspec,
            kvspec,
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=scratch,
        interpret=interpret,
        **_compiler_params(interpret, ("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf)
    out = res[0]
    lse = res[1][:, :, 0] if save_lse else None
    out = out[:, :real_len, :].reshape(batch, heads, real_len, head_dim)
    return out, lse


# ---------------------------------------------------------------------------
# backward (FlashAttention-2 style)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *,
                   scale: float, causal: bool, window: Optional[int],
                   block_q: int, block_k: int,
                   num_kb: int, real_len: int, seq_len: int,
                   k_band: Optional[int] = None, sink: int = 0):
    # num_kb is the reduction-grid length; under a k-band (sliding window)
    # the true k-block index is recovered from (qi, ki) as in _fwd_kernel.
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    kb = _recover_kb(qi, ki, block_q, block_k, k_band, sink)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = _cols(lse_ref[0], block_k)     # [block_q, block_k] replicated
        delta = _cols(delta_ref[0], block_k)
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * scale, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        k_pos = kb * block_k + cols
        if causal:
            q_pos = qi * block_q + rows
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if window is not None:
                keep = q_pos - k_pos < window
                if sink:
                    keep = jnp.logical_or(keep, k_pos < sink)
                s = jnp.where(keep, s, NEG_INF)
        if real_len < seq_len:
            s = jnp.where(k_pos < real_len, s, NEG_INF)
        p = jnp.exp(s - lse)                 # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dq_scr[...] = dq_scr[...] + jax.lax.dot_general(
            ds, k_blk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Dead blocks skip FLOPs; pipeline + init/write guards still advance.
        pl.when(_reduction_live(qi, kb, ki, block_q, block_k, causal,
                                window, k_band, sink))(_compute)
    else:
        _compute()

    @pl.when(ki == num_kb - 1)
    def _write():
        dq_ref[0] = (dq_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                    causal: bool, window: Optional[int], block_q: int,
                    block_k: int, num_qb: int,
                    group: int, real_len: int, seq_len: int,
                    q_band: Optional[int] = None,
                    num_qb_total: Optional[int] = None, sink: int = 0):
    # Innermost grid dim fuses (group member, q-block) group-major: dk/dv
    # for a KV head accumulate over every q-block of every query head in
    # its group before the single write-out.  num_qb is the per-member
    # grid length (the q-band under a sliding window); with q_band set,
    # the true q-block index is qb_lo + (j % q_band) where
    # qb_lo = (ki*block_k) // block_q, and steps past num_qb_total-1 are
    # clamped overhang (skipped).
    ki = pl.program_id(1)
    j = pl.program_id(2)
    if q_band is None:
        qi = j % num_qb
    else:
        if num_qb_total is None:
            raise ValueError("q_band requires num_qb_total (the real "
                             "q-block count) for the overhang skip")
        qi = (ki * block_k) // block_q + (j % q_band)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    rows = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    def _compute():
        k_blk = k_ref[0].astype(jnp.float32)     # [block_k, D]
        v_blk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)         # [block_q, D]
        do = do_ref[0].astype(jnp.float32)
        lse = _cols(lse_ref[0], block_k)
        delta = _cols(delta_ref[0], block_k)
        s = jax.lax.dot_general(
            q * scale, k_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        q_pos = qi * block_q + rows
        k_pos = ki * block_k + cols
        if causal:
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if window is not None:
                keep = q_pos - k_pos < window
                if sink:
                    keep = jnp.logical_or(keep, k_pos < sink)
                s = jnp.where(keep, s, NEG_INF)
        if real_len < seq_len:
            # padded q rows: lse=0 would make p=exp(s) garbage; mask them
            s = jnp.where(q_pos < real_len, s, NEG_INF)
            s = jnp.where(k_pos < real_len, s, NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[...] = dv_scr[...] + jax.lax.dot_general(
            p, do,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_k, D]
        dp = jax.lax.dot_general(
            do, v_blk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)                    # [block_q, block_k]
        dk_scr[...] = dk_scr[...] + jax.lax.dot_general(
            ds, q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Dead blocks skip FLOPs; pipeline + init/write guards still advance.
        live = _block_live(qi, ki, block_q, block_k, causal, window, sink)
        if q_band is not None:
            live = jnp.logical_and(live, qi <= num_qb_total - 1)
        pl.when(live)(_compute)
    else:
        _compute()

    @pl.when(j == num_qb * group - 1)
    def _write():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, scale: float, causal: bool,
                    block_q: int, block_k: int, interpret: bool,
                    g_lse=None, window: Optional[int] = None, sink: int = 0):
    """dq/dk/dv for cotangent g on the output — and, when `g_lse` [bh, T] is
    given, also for a cotangent on the lse auxiliary output.  dlse folds
    into the existing row-scalar plumbing with no kernel change:
    ds = p·(dp − delta + dlse) = p·(dp − (delta − dlse)), since
    ∂lse_i/∂s_ij = p_ij — so the kernels just receive delta' = delta − dlse."""
    block_q, block_k = default_blocks(block_q, block_k)
    batch, heads, real_len, head_dim = q.shape
    kv_heads = k.shape[1]
    group = heads // kv_heads
    block_q = min(block_q, max(real_len, 1))
    block_k = min(block_k, max(real_len, 1))
    bh = batch * heads

    def flat(x, block):
        return _pad_seq(
            x.reshape(batch * x.shape[1], real_len, head_dim), block
        )

    qf = flat(q, block_q)
    kf = flat(k, block_k)
    vf = flat(v, block_k)
    dof = flat(g, block_q)
    seq_len = max(qf.shape[1], kf.shape[1])
    qf, kf, vf, dof = (_pad_seq(x, seq_len) for x in (qf, kf, vf, dof))
    # delta = rowsum(dO * O): tiny elementwise reduce. Both per-row scalars
    # (delta, lse [bh, Tp]) are lane-replicated to the [bh, Tp, LANE] tile
    # layout the kernels read (module docstring) only here, at the kernel
    # boundary, so the fwd→bwd residual stays compact.
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).reshape(bh, real_len)
    if g_lse is not None:
        delta = delta - g_lse.reshape(bh, real_len).astype(jnp.float32)
    pad = seq_len - real_len
    if pad:
        delta = jnp.pad(delta, ((0, 0), (0, pad)))
        # lse comes from the forward on the same padded grid already
    lse = lse[:, :seq_len] if lse.shape[1] >= seq_len else jnp.pad(
        lse, ((0, 0), (0, seq_len - lse.shape[1]))
    )
    delta = jnp.broadcast_to(delta[:, :, None], (bh, seq_len, LANE))
    lse = jnp.broadcast_to(lse[:, :, None], (bh, seq_len, LANE))

    num_qb = seq_len // block_q
    num_kb = seq_len // block_k
    common = dict(scale=scale, causal=causal, window=window, sink=sink,
                  block_q=block_q,
                  block_k=block_k, real_len=real_len, seq_len=seq_len)
    # Sliding window: both backward passes iterate only their band (see
    # _k_band/_q_band) so out-of-band blocks are never DMA'd.
    k_band = _k_band(window, block_q, block_k, num_kb, sink)
    grid_k = (_sink_blocks(sink, block_k) + k_band
              if k_band is not None else num_kb)
    # dq pass: grid (bh, q-block, k-block), K innermost (reduction);
    # GQA maps each query head to its KV head, as in the forward
    qspec = pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0))
    kspec_j = _kv_block_spec(block_q, block_k, head_dim, group, k_band,
                             sink)
    rowspec_q = pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, num_kb=grid_k, k_band=k_band,
                          **common),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(bh, num_qb, grid_k),
        in_specs=[qspec, kspec_j, kspec_j, qspec, rowspec_q, rowspec_q],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
        **_compiler_params(interpret, ("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf, dof, lse, delta)

    # dk/dv pass: grid (B*Hkv, k-block, group×q-block), Q innermost
    # (reduction over every q-block of every query head in the group).
    # From kv index b: q flat index = (b//Hkv)*H + (b%Hkv)*group + member.
    q_band = _q_band(window, block_q, block_k, num_qb, sink)
    grid_q = q_band if q_band is not None else num_qb

    def q_side(b, i, j):
        member = j // grid_q
        qb = j % grid_q
        if q_band is not None:
            qb = jnp.minimum(
                (i * block_k) // block_q + qb, num_qb - 1
            )
        return ((b // kv_heads) * heads + (b % kv_heads) * group + member,
                qb, 0)

    qspec_j = pl.BlockSpec((1, block_q, head_dim), q_side)
    kspec_i = pl.BlockSpec((1, block_k, head_dim), lambda b, i, j: (b, i, 0))
    rowspec_j = pl.BlockSpec((1, block_q, LANE), q_side)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, num_qb=grid_q, group=group,
                          q_band=q_band, num_qb_total=num_qb,
                          **common),
        out_shape=(
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ),
        grid=(batch * kv_heads, num_kb, grid_q * group),
        in_specs=[qspec_j, kspec_i, kspec_i, qspec_j, rowspec_j, rowspec_j],
        out_specs=(kspec_i, kspec_i),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
        **_compiler_params(interpret, ("parallel", "parallel", "arbitrary")),
    )(qf, kf, vf, dof, lse, delta)

    def unflat(x, h):
        return x[:, :real_len, :].reshape(batch, h, real_len, head_dim)

    return unflat(dq, heads), unflat(dk, kv_heads), unflat(dv, kv_heads)


# ---------------------------------------------------------------------------
# public op


def xla_attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
                  window: Optional[int] = None, sink: int = 0):
    """Plain-XLA attention (fallback + reference for kernel tests)."""
    return xla_attention_lse(q, k, v, causal=causal, scale=scale,
                             window=window, sink=sink)[0]


def check_sink(window: Optional[int], sink: int) -> int:
    """Normalize the attention-sink knob: 0 = none; positive requires a
    sliding window (sinks only change behavior when distant context is
    otherwise masked off)."""
    if not sink:
        return 0
    if sink < 0:
        raise ValueError(f"sink must be >= 0, got {sink}")
    if window is None:
        raise ValueError(
            "attention sinks require a sliding window (without one every "
            "position already attends the first tokens)")
    return int(sink)


def check_window(causal: bool, window: Optional[int]) -> Optional[int]:
    """Normalize the sliding-window knob: None/0 -> full attention; a
    positive window requires causal (Mistral-style local attention is a
    causal mask restriction — bidirectional windows are not supported)."""
    if not window:
        return None
    if window < 0:
        raise ValueError(f"window must be positive, got {window}")
    if not causal:
        raise ValueError("sliding-window attention requires causal=True")
    return int(window)


def repeat_kv(q, k, v):
    """Widen GQA k/v to q's head count (the repeat-in-HBM fallback the
    Pallas kernels avoid via index maps)."""
    group = q.shape[1] // k.shape[1]
    if group == 1:
        return k, v
    return jnp.repeat(k, group, axis=1), jnp.repeat(v, group, axis=1)


def check_gqa(q, k):
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"q heads {q.shape[1]} must be a multiple of kv heads {k.shape[1]}"
        )


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:  # pragma: no cover
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_attention_tpu(q, k, v, causal=True, scale=None,
                         block_q=128, block_k=128, window=None, sink=0):
    """The custom-vjp'd kernel path; flash_attention only routes here when
    _on_tpu() — no fallback branch, so a refactor that reaches this off-TPU
    fails loudly instead of silently paying the remat tax."""
    check_gqa(q, k)
    s = scale if scale is not None else q.shape[-1] ** -0.5
    out, _ = _flash_forward(q, k, v, s, causal, block_q, block_k,
                            interpret=False, save_lse=False, window=window,
                            sink=sink)
    return out


def _env_block(name: str, multiple: int) -> int:
    import os

    raw = os.environ.get(name, "128")
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer (this env var is the "
            "autotune propagation channel — see ops/autotune.py)") from None
    if value <= 0 or value % multiple:
        raise ValueError(
            f"{name}={value} must be a positive multiple of {multiple} "
            f"(Mosaic tiling: blocks are (mult-of-8, mult-of-128) tiles)")
    return value


def default_blocks(block_q, block_k):
    """Resolve kernel block defaults: explicit args win; otherwise the
    TPUJOB_FLASH_BLOCK_Q/K env (how autotuned configs reach workloads
    without config plumbing — ops/autotune.py); otherwise 128.  Read at
    trace time, so consistent within any one compiled program; a bad env
    value fails here, naming the variable, not deep inside Mosaic.
    Resolution lives ONLY at the _flash_forward/_flash_backward
    chokepoints so every public entry (flash_attention,
    flash_attention_lse, the interpret helpers) shares one rule."""
    # tuned-config handoff knobs: written by the autotune bench / the user,
    # not by gen_tpu_env (ops/autotune.py module docstring)
    if block_q is None:
        block_q = _env_block("TPUJOB_FLASH_BLOCK_Q", 8)  # contract: exempt(knob-chain)
    if block_k is None:
        block_k = _env_block("TPUJOB_FLASH_BLOCK_K", 128)  # contract: exempt(knob-chain)
    return block_q, block_k


def flash_attention(q, k, v, causal=True, scale=None, block_q=None,
                    block_k=None, window=None, sink=0):
    """Fused attention; Pallas kernels (fwd + bwd) on TPU, XLA elsewhere.
    k/v may carry fewer (grouped-query) heads than q — the kernels never
    repeat them in HBM; the XLA fallback widens them explicitly.

    `window` (Mistral-style sliding window, requires causal) restricts
    each query to its last `window` keys; on TPU the kernels skip every
    block outside the band, so compute and K/V traffic drop from O(T^2)
    to O(T*window).

    The platform dispatch happens OUTSIDE the custom_vjp: off-TPU the
    fallback runs plain xla_attention under standard autodiff.  Routing it
    through the kernel's custom_vjp would recompute the whole forward inside
    the backward (flash attention's memory-for-FLOPs remat trade) with no
    memory payoff — a measurable pure-overhead tax on the CPU arm
    (bench.py's CPU LM vs_baseline read ~0.97 from exactly this)."""
    window = check_window(causal, window)
    sink = check_sink(window, sink)
    if not _on_tpu():
        check_gqa(q, k)
        s = scale if scale is not None else q.shape[-1] ** -0.5
        return xla_attention(q, *repeat_kv(q, k, v), causal=causal, scale=s,
                             window=window, sink=sink)
    return _flash_attention_tpu(q, k, v, causal, scale, block_q, block_k,
                                window, sink)


def _fwd(q, k, v, causal, scale, block_q, block_k, window, sink):
    check_gqa(q, k)
    s = scale if scale is not None else q.shape[-1] ** -0.5
    out, lse = _flash_forward(q, k, v, s, causal, block_q, block_k,
                              interpret=False, window=window, sink=sink)
    return out, (q, k, v, out, lse)


def _bwd(causal, scale, block_q, block_k, window, sink, res, g):
    q, k, v, o, lse = res
    s = scale if scale is not None else q.shape[-1] ** -0.5
    return _flash_backward(q, k, v, o, lse, g, s, causal,
                           block_q, block_k, interpret=False, window=window,
                           sink=sink)


_flash_attention_tpu.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# (output, logsumexp) variant — the building block ring attention combines
# across devices: per-shard normalized output + per-row lse of the scaled
# scores, merged in log-sum-exp form (parallel/ring_attention.py).


def xla_attention_lse(q, k, v, *, causal: bool = True,
                      scale: Optional[float] = None,
                      window: Optional[int] = None, sink: int = 0):
    """Closed-form (o, lse [B,H,T] f32) — fallback + oracle for the kernel."""
    # same contract as the kernel path: window implies causal (a silently
    # ignored window in the reference would let oracle and kernel diverge)
    window = check_window(causal, window)
    sink = check_sink(window, sink)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        t_q, t_k = logits.shape[-2:]
        rows = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        cols = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        logits = jnp.where(rows >= cols, logits, NEG_INF)
        if window is not None:
            keep = rows - cols < window
            if sink:
                keep = jnp.logical_or(keep, cols < sink)
            logits = jnp.where(keep, logits, NEG_INF)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    probs = jnp.exp(logits - lse[..., None]).astype(v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(q, k, v, causal=True, scale=None,
                        block_q=None, block_k=None):
    """Fused attention returning (o, lse [B,H,T] f32); Pallas on TPU, XLA
    elsewhere.  Differentiable in BOTH outputs (the lse cotangent folds into
    the backward's delta term — see _flash_backward).  GQA k/v supported as
    in flash_attention."""
    check_gqa(q, k)
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if _on_tpu():
        batch, heads, t, _ = q.shape
        out, lse = _flash_forward(q, k, v, s, causal, block_q, block_k,
                                  interpret=False)
        return out, lse[:, :t].reshape(batch, heads, t)
    return xla_attention_lse(q, *repeat_kv(q, k, v), causal=causal, scale=s)


def _fwd_lse(q, k, v, causal, scale, block_q, block_k):
    check_gqa(q, k)
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if _on_tpu():
        batch, heads, t, _ = q.shape
        out, lse = _flash_forward(q, k, v, s, causal, block_q, block_k,
                                  interpret=False)
        return (out, lse[:, :t].reshape(batch, heads, t)), (q, k, v, out, lse)
    out, lse = xla_attention_lse(q, *repeat_kv(q, k, v), causal=causal, scale=s)
    return (out, lse), (q, k, v, None, None)


def _bwd_lse(causal, scale, block_q, block_k, res, gs):
    q, k, v, o, lse = res
    g_o, g_lse = gs
    s = scale if scale is not None else q.shape[-1] ** -0.5
    if lse is not None:
        return _flash_backward(q, k, v, o, lse, g_o, s, causal,
                               block_q, block_k, interpret=False,
                               g_lse=g_lse)
    _, vjp = jax.vjp(
        lambda q, k, v: xla_attention_lse(
            q, *repeat_kv(q, k, v), causal=causal, scale=s
        ),
        q, k, v,
    )
    return vjp((g_o, g_lse))


flash_attention_lse.defvjp(_fwd_lse, _bwd_lse)


# ---------------------------------------------------------------------------
# interpret-mode entry points (CPU correctness tests for the kernels)


def flash_attention_interpret(q, k, v, causal=True, scale=None,
                              block_q=128, block_k=128, window=None, sink=0):
    """Interpreter-mode forward kernel execution (the same primal-only
    no-lse variant the TPU compiles)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    window = check_window(causal, window)
    sink = check_sink(window, sink)
    out, _ = _flash_forward(q, k, v, s, causal, block_q, block_k,
                            interpret=True, save_lse=False, window=window,
                            sink=sink)
    return out


def flash_attention_grads_interpret(q, k, v, g, causal=True, scale=None,
                                    block_q=128, block_k=128, window=None,
                                    sink=0):
    """Interpreter-mode fwd+bwd kernel execution: returns (out, dq, dk, dv)
    for cotangent g — the CPU-testable path through the SAME kernel code the
    TPU compiles."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    window = check_window(causal, window)
    sink = check_sink(window, sink)
    out, lse = _flash_forward(q, k, v, s, causal, block_q, block_k,
                              interpret=True, window=window, sink=sink)
    dq, dk, dv = _flash_backward(q, k, v, out, lse, g, s, causal,
                                 block_q, block_k, interpret=True,
                                 window=window, sink=sink)
    return out, dq, dk, dv


def flash_attention_lse_grads_interpret(q, k, v, g_o, g_lse, causal=True,
                                        scale=None, block_q=128, block_k=128):
    """Interpreter-mode (o, lse) fwd + bwd with cotangents on BOTH outputs —
    the CPU-testable path through the kernels the TPU compiles for ring
    attention's per-shard step."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    batch, heads, t, _ = q.shape
    out, lse2 = _flash_forward(q, k, v, s, causal, block_q, block_k,
                               interpret=True)
    dq, dk, dv = _flash_backward(q, k, v, out, lse2, g_o, s, causal,
                                 block_q, block_k, interpret=True,
                                 g_lse=g_lse)
    return out, lse2[:, :t].reshape(batch, heads, t), dq, dk, dv
