"""Train state: params + optimizer + mutable model collections."""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import optax


@flax.struct.dataclass
class TrainState:
    """Like flax.training.train_state.TrainState plus batch_stats (for
    BatchNorm models) and an explicit apply_fn kept out of the pytree."""

    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any = None
    apply_fn: Callable = flax.struct.field(pytree_node=False, default=None)
    tx: optax.GradientTransformation = flax.struct.field(
        pytree_node=False, default=None
    )
    # ZeRO-style weight-update sharding plan (train/zero.py), carried out of
    # the pytree so checkpointing can persist it alongside the arrays and a
    # resumed process on a different dp size can re-shard deliberately.
    zero_plan: Any = flax.struct.field(pytree_node=False, default=None)

    def apply_gradients(self, grads, new_batch_stats=None) -> "TrainState":
        updates, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, updates)
        if self.zero_plan is not None and self.zero_plan.mesh is not None:
            # Pin the all-gather of the updated shards on the params
            # themselves: the apply_updates add has no annotation, and
            # XLA's propagation would otherwise keep the output in the
            # weight-update layout — correct, but a per-step layout flip
            # against the forward pass (see docs/zero-sharding.md).
            from .zero import constrain_to_base

            new_params = constrain_to_base(
                new_params, self.zero_plan, self.zero_plan.mesh)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=(
                new_batch_stats if new_batch_stats is not None else self.batch_stats
            ),
        )


def create_train_state(
    rng: jax.Array,
    model,
    tx: optax.GradientTransformation,
    example_input,
    extra_init_args: tuple = (),
    init_kwargs: Optional[dict] = None,
    zero_plan: Any = None,
) -> TrainState:
    variables = model.init(rng, example_input, *extra_init_args, **(init_kwargs or {}))
    params = variables["params"]
    batch_stats = variables.get("batch_stats")
    import jax.numpy as jnp

    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        batch_stats=batch_stats,
        apply_fn=model.apply,
        tx=tx,
        zero_plan=zero_plan,
    )
