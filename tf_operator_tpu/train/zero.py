"""Cross-replica sharded weight update (ZeRO-style) for data-parallel axes.

Implements "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (arXiv:2004.13336, PAPERS.md) for the runtime's
train path: AdamW keeps two full-precision moments per parameter, and in
plain data parallelism every dp replica holds a full copy of both — the
optimizer state is usually the single largest resident HBM block after the
params themselves (docs/roofline.md's memory model).  Sharding the moments
and the weight-update computation across the dp axis cuts that to ~1/dp
per device with no change to the math: each replica updates only its shard
and the updated param shards are all-gathered back to the params' layout.

Mechanically this is GSPMD layout annotation, not explicit collectives
(the same recipe as train/step.py): gradients are constrained to the
sharded layout before the inner optimizer runs (XLA turns the dp grad
psum into a reduce-scatter), the moments it produces are constrained to
stay sharded, and the updates are constrained back to the params' base
layout (XLA inserts the all-gather).  Numerics are identical up to f32
reduction order — tolerance story in docs/zero-sharding.md.

The *plan* is the searchable artifact: one JSON-serializable record per
param naming the dim the dp axis lands on (chosen by
parallel/mesh.free_dim_partition_spec — largest free dim, ties toward the
last), layered on top of whatever tp/fsdp layout the param already has.
The controller stamps the strategy-level plan into the job status
(api/types.zero_sharding_plan_doc) so the future AMP planner (ROADMAP
item 3) can search over it.

Moments are matched to params by tree-path **suffix + shape** — never
shape alone: two different params can share a shape, but an optimizer
state leaf that mirrors a param always carries the param's full tree path
as the tail of its own (``.../0/mu/block_0/mlp/wi/kernel`` ends with
``block_0/mlp/wi/kernel``).  Leaves that match no param path (step
counts, empty states) replicate.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import AXIS_DP, axis_size, free_dim_partition_spec


def _key_str(k) -> str:
    """One tree-path element as a string (DictKey/GetAttrKey/SequenceKey)."""
    for attr in ("key", "name", "idx"):
        v = getattr(k, attr, None)
        if v is not None:
            return str(v)
    return str(k)


def path_parts(key_path) -> Tuple[str, ...]:
    return tuple(_key_str(k) for k in key_path)


def _spec_entries(spec: P, ndim: int) -> Tuple:
    entries = tuple(spec)
    return entries + (None,) * (ndim - len(entries))


def _spec_to_json(spec: P, ndim: int) -> List:
    out: List = []
    for e in _spec_entries(spec, ndim):
        out.append(list(e) if isinstance(e, tuple) else e)
    return out


def _spec_from_json(raw: Sequence) -> P:
    entries = [tuple(e) if isinstance(e, list) else e for e in raw]
    while entries and entries[-1] is None:  # normalize: P(None) == P() here
        entries.pop()
    return P(*entries)


@dataclasses.dataclass(frozen=True)
class PlanEntry:
    path: Tuple[str, ...]  # param tree path, e.g. ("block_0", "mlp", "wi", "kernel")
    shape: Tuple[int, ...]
    dim: Optional[int]  # dim the dp axis shards, None = replicated over dp
    base: P  # the param's own (tp/fsdp) layout
    spec: P  # base + dp axis on `dim` — the optimizer-state layout
    # Bucketed-overlap intent (ROADMAP item 4a): True declares that this
    # entry's weight-update collectives are expected to run asynchronously
    # (start/done pairs overlapping compute).  Nothing sets it yet — the
    # compiled-HLO lint (analysis/hlo.py, `hlo-sync-collective`) enforces
    # it the day the overlap work lands, so the flag ships ahead of the
    # scheduler change as a checked contract, not a comment.
    overlap: bool = False


@dataclasses.dataclass(frozen=True)
class ZeroShardingPlan:
    """Per-param weight-update sharding over one data-parallel mesh axis."""

    axis: str
    num_shards: int
    entries: Tuple[PlanEntry, ...]
    # The mesh the plan was built for — layout context, not part of the
    # serialized plan (a restored plan gets its mesh from the caller).
    # TrainState.apply_gradients uses it to pin the all-gather of updated
    # params; compare=False so plans are equal across equivalent meshes.
    mesh: Optional[Mesh] = dataclasses.field(default=None, compare=False)

    def __post_init__(self):
        # Longest path first so suffix matching prefers the most specific
        # param when one param's path is a suffix of another's.
        by_shape: Dict[Tuple[int, ...], List[PlanEntry]] = {}
        for e in sorted(self.entries, key=lambda e: -len(e.path)):
            by_shape.setdefault(e.shape, []).append(e)
        object.__setattr__(self, "_by_shape", by_shape)

    def match(self, parts: Sequence[str], shape) -> Optional[PlanEntry]:
        return match_param_suffix(parts, shape, self._by_shape)

    def to_json(self) -> str:
        return json.dumps(
            {
                "axis": self.axis,
                "numShards": self.num_shards,
                "params": [
                    {
                        "path": "/".join(e.path),
                        "shape": list(e.shape),
                        "dim": e.dim,
                        "base": _spec_to_json(e.base, len(e.shape)),
                        # emitted only when set: older readers (and every
                        # committed checkpoint) keep parsing unchanged
                        **({"overlap": True} if e.overlap else {}),
                    }
                    for e in self.entries
                ],
            },
            separators=(",", ":"),
        )

    @classmethod
    def from_json(cls, text: str, mesh: Optional[Mesh] = None) -> "ZeroShardingPlan":
        raw = json.loads(text)
        axis, num = raw["axis"], int(raw["numShards"])
        entries = []
        for p in raw["params"]:
            base = _spec_from_json(p["base"])
            shape = tuple(int(d) for d in p["shape"])
            dim = p["dim"]
            if dim is None:
                spec = base
            else:
                spec_entries = list(_spec_entries(base, len(shape)))
                spec_entries[dim] = axis
                spec = P(*spec_entries)
            entries.append(
                PlanEntry(
                    path=tuple(p["path"].split("/")),
                    shape=shape,
                    dim=dim,
                    base=base,
                    spec=spec,
                    overlap=bool(p.get("overlap", False)),
                )
            )
        return cls(axis=axis, num_shards=num, entries=tuple(entries),
                   mesh=mesh)

    def with_overlap(self) -> "ZeroShardingPlan":
        """A copy whose sharded entries are marked overlappable — the
        declaration the `hlo-sync-collective` rule (analysis/hlo.py)
        enforces against the compiled program."""
        return dataclasses.replace(
            self,
            entries=tuple(
                dataclasses.replace(e, overlap=True) if e.dim is not None
                else e
                for e in self.entries
            ),
        )


def match_param_suffix(
    parts: Sequence[str], shape, by_shape: Dict[Tuple[int, ...], List[PlanEntry]]
) -> Optional[PlanEntry]:
    """The moment↔param matching rule: an opt-state leaf belongs to the
    param whose full tree path is a suffix of the leaf's path AND whose
    shape matches — never shape alone.  Longest path wins on ambiguity."""
    shape = tuple(shape) if shape is not None else ()
    parts = tuple(parts)
    for entry in by_shape.get(shape, ()):
        n = len(entry.path)
        if n and parts[-n:] == entry.path:
            return entry
    return None


def _base_spec_of(leaf, base_spec) -> P:
    if base_spec is not None:
        if isinstance(base_spec, NamedSharding):
            return base_spec.spec
        return base_spec
    sharding = getattr(leaf, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return sharding.spec
    return P()


def build_zero_plan(
    params,
    mesh: Mesh,
    axis: str = AXIS_DP,
    base_specs=None,
) -> ZeroShardingPlan:
    """Choose the weight-update shard dim for every param.

    `params` may be live arrays or `jax.eval_shape` structs.  `base_specs`
    (a matching pytree of PartitionSpec/NamedSharding, e.g. from
    tp_rules.make_param_shardings) names each param's existing layout; when
    omitted it is read off live arrays' NamedShardings, else replicated.
    The dp dim is the largest free dim divisible by the axis size, ties
    toward the last (mesh.free_dim_partition_spec).
    """
    num = axis_size(mesh, axis)
    base_flat = None
    if base_specs is not None:
        base_flat = jax.tree_util.tree_flatten(
            base_specs, is_leaf=lambda x: isinstance(x, (P, NamedSharding))
        )[0]
    entries = []
    for i, (key_path, leaf) in enumerate(
        jax.tree_util.tree_flatten_with_path(params)[0]
    ):
        shape = tuple(getattr(leaf, "shape", ()))
        base = _base_spec_of(leaf, base_flat[i] if base_flat is not None else None)
        spec = free_dim_partition_spec(
            shape, mesh, axis, base=base, prefer="largest"
        )
        dim = None
        if spec is not base:
            for d, (b, s) in enumerate(
                zip(_spec_entries(base, len(shape)), _spec_entries(spec, len(shape)))
            ):
                if b != s:
                    dim = d
                    break
        entries.append(
            PlanEntry(path=path_parts(key_path), shape=shape, dim=dim,
                      base=base, spec=spec)
        )
    return ZeroShardingPlan(axis=axis, num_shards=num, entries=tuple(entries),
                            mesh=mesh)


def base_placement_plan(params, mesh: Mesh, base_specs=None) -> ZeroShardingPlan:
    """A degenerate plan (no dp axis, num_shards=1) whose entries carry only
    the params' own layouts — the suffix+shape matcher train/step.py uses to
    place *dense* optimizer state, so moments never match by shape alone."""
    return build_zero_plan(params, mesh, axis="", base_specs=base_specs)


# ---------------------------------------------------------------------------
# Applying the plan to trees

def _map_with_plan(tree, plan: ZeroShardingPlan, fn):
    """fn(leaf, entry_or_None) over leaves, matching by path suffix+shape."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for key_path, leaf in flat:
        entry = plan.match(path_parts(key_path), getattr(leaf, "shape", ()))
        out.append(fn(leaf, entry))
    return jax.tree_util.tree_unflatten(treedef, out)


def constrain_to_plan(tree, plan: ZeroShardingPlan, mesh: Mesh):
    """Annotate matching leaves with their sharded (base+dp) layout — the
    reduce-scatter point for gradients inside a jitted step."""
    return _map_with_plan(
        tree, plan,
        lambda leaf, e: leaf if e is None else jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, e.spec)),
    )


def constrain_to_base(tree, plan: ZeroShardingPlan, mesh: Mesh):
    """Annotate matching leaves with the params' own layout — the
    all-gather point for the updated shards."""
    return _map_with_plan(
        tree, plan,
        lambda leaf, e: leaf if e is None else jax.lax.with_sharding_constraint(
            leaf, NamedSharding(mesh, e.base)),
    )


def place_opt_state(opt_state, plan: ZeroShardingPlan, mesh: Mesh):
    """device_put moments onto their sharded layout (init-time, outside
    jit); unmatched leaves (counts, empty states) replicate."""
    repl = NamedSharding(mesh, P())
    return _map_with_plan(
        opt_state, plan,
        lambda leaf, e: jax.device_put(
            leaf, repl if e is None else NamedSharding(mesh, e.spec))
        if hasattr(leaf, "shape") else leaf,
    )


def zero_shard_optimizer(
    inner: optax.GradientTransformation,
    plan: ZeroShardingPlan,
    mesh: Mesh,
) -> optax.GradientTransformation:
    """Wrap `inner` so its state and update computation shard over the
    plan's dp axis.

    init: inner state with moments device_put onto the sharded layout.
    update (inside the jitted train step): grads and params are viewed in
    the sharded layout (reduce-scatter), the inner chain — clipping
    included: arrays stay logically global, so the global norm is exact —
    runs on shards, new moments stay sharded, and the updates are
    constrained back to the params' base layout (all-gather).
    """

    def init(params):
        return place_opt_state(inner.init(params), plan, mesh)

    def update(grads, state, params=None, **extra):
        g = constrain_to_plan(grads, plan, mesh)
        p = constrain_to_plan(params, plan, mesh) if params is not None else None
        updates, new_state = inner.update(g, state, p, **extra)
        new_state = constrain_to_plan(new_state, plan, mesh)
        return constrain_to_base(updates, plan, mesh), new_state

    return optax.GradientTransformation(init, update)


# ---------------------------------------------------------------------------
# Accounting (the bench/roofline hook)

def _shard_factor(entry: PlanEntry, plan: ZeroShardingPlan) -> int:
    """How many ways this entry's moments are split.  With the plan's mesh
    at hand the factor is exact over EVERY axis in the layout (the base
    tp/fsdp axes shard the moments too — shard_train_state places them on
    the full entry.spec); a mesh-less plan (from_json without a mesh) can
    only count the dp axis it knows the width of."""
    if plan.mesh is not None:
        factor = 1
        for e in entry.spec:
            for a in (e if isinstance(e, tuple) else (e,)):
                if a is not None:
                    factor *= axis_size(plan.mesh, a)
        return factor
    return plan.num_shards if entry.dim is not None else 1


def opt_state_bytes_per_device(
    plan: Optional[ZeroShardingPlan], params, moments_per_param: int = 2
) -> int:
    """Resident optimizer-moment bytes per device under `plan` (None =
    fully replicated moments).  AdamW keeps `moments_per_param`=2 (mu, nu)
    leaves mirroring each param in the param dtype; each entry costs its
    dense footprint divided by every mesh axis its layout shards over.

    For the true dense baseline on a mesh with tp/fsdp axes (where even
    plan-less moments follow the params' layout), pass
    `base_placement_plan(params, mesh, base_specs)` instead of None —
    plan=None prices pure replication."""
    total = 0
    for key_path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        n = int(np.prod(shape, initial=1)) * dtype.itemsize * moments_per_param
        entry = plan.match(path_parts(key_path), shape) if plan else None
        if entry is not None:
            n //= _shard_factor(entry, plan)
        total += n
    return total
