"""Subpackage."""
