"""Asynchronous parameter-server runtime (CPU-side, socket transport).

The reference's PS strategy is delivered by TF's gRPC runtime inside user
containers (SURVEY.md §2.9 — the operator only wires addresses).  This
framework owns the training runtime, so it ships a real PS implementation:
parameter shards live on PS processes; workers pull, compute grads locally
(JAX), and push asynchronously (Hogwild-style downpour SGD).

Honest TPU note: async PS is a CPU/heterogeneous-cluster pattern — on a TPU
slice, synchronous allreduce over ICI dominates it and is the default path
(train/step.py).  This module exists for capability parity with reference
dist-mnist jobs (examples/v1/dist-mnist/dist_mnist.py:98-143) and for
CPU-parameter-server topologies.

Protocol: length-prefixed pickled tuples over TCP.
  ("pull",)              -> {name: np.ndarray}  (this shard's params)
  ("push", {name: grad}) -> ("ok", version)     (applies SGD update)
  ("shutdown",)          -> ("ok",)
Param leaves are assigned to PS replicas round-robin by sorted name.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import locks

_LEN = struct.Struct("!Q")


def _send(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv(sock: socket.socket):
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    return pickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def shard_names(all_names: List[str], num_ps: int, ps_index: int) -> List[str]:
    """Round-robin leaf assignment (deterministic on sorted names)."""
    return [n for i, n in enumerate(sorted(all_names)) if i % num_ps == ps_index]


class ParameterServer(socketserver.ThreadingTCPServer):
    """Holds one shard; applies pushed grads with plain SGD (downpour)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address: Tuple[str, int], params: Dict[str, np.ndarray],
                 lr: float = 0.1) -> None:
        self.params = {k: np.asarray(v, np.float32).copy() for k, v in params.items()}
        self.lr = lr
        self.version = 0
        self.lock = locks.new_lock("ps-shard")
        self._shutdown_requested = threading.Event()
        super().__init__(address, _PSHandler)

    def serve_until_shutdown(self) -> None:
        thread = threading.Thread(target=self.serve_forever,
                                  name="tpujob-ps-serve", daemon=True)
        thread.start()
        self._shutdown_requested.wait()
        self.shutdown()


class _PSHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: ParameterServer = self.server  # type: ignore[assignment]
        try:
            while True:
                msg = _recv(self.request)
                op = msg[0]
                if op == "pull":
                    with server.lock:
                        _send(self.request, (dict(server.params), server.version))
                elif op == "push":
                    grads = msg[1]
                    with server.lock:
                        for name, grad in grads.items():
                            if name in server.params:
                                server.params[name] -= server.lr * np.asarray(grad)
                        server.version += 1
                        _send(self.request, ("ok", server.version))
                elif op == "shutdown":
                    _send(self.request, ("ok",))
                    server._shutdown_requested.set()
                    return
                else:
                    _send(self.request, ("err", f"unknown op {op!r}"))
        except (ConnectionError, EOFError):
            return


class BasePSClient:
    """Worker-side view over all PS shards — the transport-agnostic shell
    (socket pool, pull-learned routing, partial-push fan-out, shutdown).
    Subclasses supply the wire protocol via the three _shard hooks; the
    pickle transport below and the binary one (train/native_ps.py) share
    everything else."""

    def __init__(self, addresses: List[str], timeout: float = 30.0) -> None:
        self.addresses = addresses
        self._socks: List[Optional[socket.socket]] = [None] * len(addresses)
        self.timeout = timeout
        # name -> shard index, learned from pull(); authoritative routing.
        self._routes: Dict[str, int] = {}

    # -- transport hooks --

    def _pull_shard(self, i: int) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def _push_shard(self, i: int, grads: Dict[str, np.ndarray]) -> None:
        raise NotImplementedError

    def _shutdown_shard(self, i: int) -> None:
        raise NotImplementedError

    # -- shared behavior --

    def _sock(self, i: int) -> socket.socket:
        if self._socks[i] is None:
            host, _, port = self.addresses[i].rpartition(":")
            sock = socket.create_connection((host, int(port)), timeout=self.timeout)
            self._socks[i] = sock
        return self._socks[i]

    def pull(self) -> Dict[str, np.ndarray]:
        merged: Dict[str, np.ndarray] = {}
        for i in range(len(self.addresses)):
            shard = self._pull_shard(i)
            for name in shard:
                self._routes[name] = i
            merged.update(shard)
        return merged

    def push(self, grads: Dict[str, np.ndarray]) -> None:
        # Route by the servers' actual shard assignment (learned on pull).
        # Re-deriving routes from sorted(grads) would mis-shard any partial
        # push (e.g. frozen layers excluded) and the server would silently
        # drop the misrouted grads.
        if not self._routes:
            self.pull()
        unknown = [n for n in grads if n not in self._routes]
        if unknown:
            raise KeyError(f"params not hosted by any PS shard: {unknown}")
        by_shard: Dict[int, Dict[str, np.ndarray]] = {}
        for name, grad in grads.items():
            by_shard.setdefault(self._routes[name], {})[name] = grad
        for i, mine in by_shard.items():
            self._push_shard(i, mine)

    def shutdown_servers(self) -> None:
        for i in range(len(self.addresses)):
            try:
                self._shutdown_shard(i)
            except (OSError, ConnectionError):
                pass

    def close(self) -> None:
        for sock in self._socks:
            if sock is not None:
                sock.close()
        self._socks = [None] * len(self.addresses)


class PSClient(BasePSClient):
    """Pickle-protocol transport (matches ParameterServer above)."""

    def _pull_shard(self, i: int) -> Dict[str, np.ndarray]:
        _send(self._sock(i), ("pull",))
        shard, _version = _recv(self._sock(i))
        return shard

    def _push_shard(self, i: int, grads: Dict[str, np.ndarray]) -> None:
        _send(self._sock(i), ("push", grads))
        _recv(self._sock(i))

    def _shutdown_shard(self, i: int) -> None:
        _send(self._sock(i), ("shutdown",))
        _recv(self._sock(i))


def flatten_params(params, prefix="") -> Dict[str, np.ndarray]:
    out = {}
    for key, value in params.items():
        path = f"{prefix}/{key}" if prefix else str(key)
        if isinstance(value, dict):
            out.update(flatten_params(value, path))
        else:
            out[path] = np.asarray(value, np.float32)
    return out


def unflatten_params(flat: Dict[str, np.ndarray]):
    tree: Dict = {}
    for path, value in flat.items():
        parts = path.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree


def serve_shard(flat_init: Dict[str, np.ndarray], ps_addresses: List[str],
                task_id: int, lr: float, native: bool = False):
    """Stand up THIS replica's parameter-server shard and block until a
    client sends shutdown.  Shared by every PS-strategy workload (dist_mnist,
    estimator) so transport selection and shard/port wiring cannot drift
    between them.  Returns 0 (exit code)."""
    my_names = shard_names(sorted(flat_init), len(ps_addresses), task_id)
    shard = {n: flat_init[n] for n in my_names}
    _, _, port = ps_addresses[task_id].rpartition(":")
    if native:
        from . import native_ps

        server = native_ps.NativeParameterServer(
            ("0.0.0.0", int(port)), shard, lr=lr)
    else:
        server = ParameterServer(("0.0.0.0", int(port)), shard, lr=lr)
    print(f"ps {task_id} ({'native' if native else 'python'}) serving "
          f"{len(shard)} leaves on :{port}", flush=True)
    server.serve_until_shutdown()
    print("ps shutdown", flush=True)
    return 0


def connect_with_retry(ps_addresses: List[str], native: bool = False,
                       attempts: int = 60, delay: float = 1.0):
    """Client to all PS shards, retrying the first pull until the servers
    come up (PS pods may start after workers).  Returns (client, first_flat)
    or raises ConnectionError after `attempts`."""
    for _ in range(attempts):
        if native:
            from . import native_ps

            client = native_ps.NativePSClient(ps_addresses)
        else:
            client = PSClient(ps_addresses)
        try:
            return client, client.pull()
        except (OSError, ConnectionError):
            client.close()
            time.sleep(delay)
    raise ConnectionError(
        f"could not reach parameter servers {ps_addresses} "
        f"after {attempts} attempts")
