"""Sharded train/eval steps.

The SPMD recipe (scaling-book): place the global batch over the dp/fsdp mesh
axes, place params by the tp+fsdp rules, jit the step with donated state, and
let XLA turn sharding mismatches into ICI collectives (grad psum over dp,
all-gather/reduce-scatter for fsdp, per-block psum for tp).  No explicit
collective calls appear in the training step.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.mesh import batch_sharding, data_axes, replicated
from ..parallel.tp_rules import make_param_shardings
from .state import TrainState


def softmax_cross_entropy(logits, labels) -> jax.Array:
    """labels: int class ids. Mean loss in f32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def chunked_softmax_xent(hidden, table, targets, chunk: int) -> jax.Array:
    """Weight-tied LM cross-entropy computed in T-chunks so the full
    [B, T, vocab] logits never materialize — at vocab 32k and t 2048 the
    f32 logits alone are ~1 GB of HBM per example batch, usually the peak
    of LM training memory.  Each chunk's logits are built inside a
    rematerialized scan body: the forward keeps only the running scalar,
    and the backward recomputes one chunk's logits at a time, so peak
    logits memory is B * chunk * vocab regardless of T.

    `hidden` [B, T, D] is the model's pre-readout activations, already
    cast to the model dtype (TransformerLM(..., return_hidden=True)
    applies the same rounding the full readout does); `table` [vocab, D]
    is the readout matrix.  Each chunk's readout uses the exact
    formulation of the full path's nn.Embed.attend — promote query and
    table to their common dtype, then jnp.dot — so chunked and full
    losses agree up to reduction order (pinned at 2e-5 in tests and in
    the multichip dryrun), never at a lower precision."""
    if chunk <= 0:
        raise ValueError(f"chunk must be positive, got {chunk}")
    b, t, d = hidden.shape
    n = -(-t // chunk)
    pad = n * chunk - t
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        targets = jnp.pad(targets, ((0, 0), (0, pad)))
    mask = (jnp.arange(n * chunk) < t)[None, :]
    hc = hidden.reshape(b, n, chunk, d).transpose(1, 0, 2, 3)
    yc = targets.reshape(b, n, chunk).transpose(1, 0, 2)
    mc = jnp.broadcast_to(mask, (b, n * chunk)).reshape(
        b, n, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def chunk_nll(hx, yy, mm):
        # same formulation as nn.Embed.attend (promote, then dot): bf16
        # hidden x f32 table runs as an f32 matmul, not a bf16 one
        logits = jnp.dot(hx, table.T).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, yy[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(mm, -ll, 0.0))

    def body(acc, args):
        return acc + chunk_nll(*args), None

    total, _ = lax.scan(body, jnp.zeros((), jnp.float32), (hc, yc, mc))
    return total / (b * t)


def _tied_table(params):
    """Default readout-table accessor for the chunked loss: TransformerLM's
    weight-tied embedding.  Raising here (rather than risking a silent
    wrong-matrix lookup) is the contract for models with a different
    layout — they pass their own accessor."""
    try:
        return params["wte"]["embedding"]
    except KeyError as exc:
        raise ValueError(
            "loss_chunk needs the model's readout table; the default "
            "accessor expects TransformerLM's tied "
            "params['wte']['embedding'] — pass table_fn= for other "
            "layouts") from exc


def lm_loss_fn(apply_fn, moe_aux_weight: float = 0.0, loss_chunk: int = 0,
               table_fn: Optional[Callable] = None):
    """Next-token prediction loss for TransformerLM.

    With moe_aux_weight > 0, the Switch-style load-balancing losses sown by
    MoE blocks (parallel/moe.py) are collected via the intermediates
    collection and added to the objective — without this the router gets no
    balancing gradient and experts collapse.

    With loss_chunk > 0 the cross-entropy is computed via
    chunked_softmax_xent (pre-readout hidden states + readout table),
    holding peak logits memory to B * loss_chunk * vocab instead of the
    full sequence.  The model must support `return_hidden=True` with a
    weight-tied readout; `table_fn(params)` overrides the default
    TransformerLM table accessor for other param layouts."""
    if loss_chunk < 0:
        raise ValueError(
            f"loss_chunk must be >= 0, got {loss_chunk} (0 disables "
            "chunking; a negative value silently ignored would leave the "
            "full-logits memory peak in place)")
    get_table = table_fn or _tied_table

    def unwrap(out):
        return out if isinstance(out, tuple) else (out, None)

    def ce(params, tokens, **apply_kwargs):
        if loss_chunk > 0:
            hidden, state = unwrap(apply_fn(
                {"params": params}, tokens[:, :-1], return_hidden=True,
                **apply_kwargs))
            # hidden arrives already cast to the model dtype (the same
            # rounding the full readout applies before the tied matmul)
            return chunked_softmax_xent(
                hidden, get_table(params), tokens[:, 1:], loss_chunk), state
        logits, state = unwrap(apply_fn(
            {"params": params}, tokens[:, :-1], **apply_kwargs))
        return softmax_cross_entropy(logits, tokens[:, 1:]), state

    def loss(params, batch, rngs=None):
        tokens = batch["tokens"]
        if moe_aux_weight > 0.0:
            from ..parallel.moe import moe_aux_loss

            ce_val, state = ce(params, tokens, mutable=["intermediates"])
            aux = moe_aux_loss(state["intermediates"])
            return ce_val + moe_aux_weight * aux, {"moe_aux_loss": aux}
        ce_val, _ = ce(params, tokens)
        return ce_val, {}

    return loss


def _logits(out):
    """Unwrap a model output: dict heads expose 'logits', plain arrays are
    the logits already."""
    return out["logits"] if isinstance(out, dict) else out


def classification_loss_fn(apply_fn, has_batch_stats: bool = False,
                           model_kwargs: Optional[dict] = None):
    """Image/sequence classification loss; threads BatchNorm stats."""
    model_kwargs = dict(model_kwargs or {})

    def loss(params, batch, batch_stats=None, rngs=None):
        variables = {"params": params}
        if has_batch_stats:
            variables["batch_stats"] = batch_stats
            out, updates = apply_fn(
                variables, batch["x"], mutable=["batch_stats"],
                rngs=rngs, **model_kwargs,
            )
            logits = _logits(out)
            return softmax_cross_entropy(logits, batch["label"]), {
                "batch_stats": updates["batch_stats"]
            }
        out = apply_fn(variables, batch["x"], rngs=rngs, **model_kwargs)
        logits = _logits(out)
        return softmax_cross_entropy(logits, batch["label"]), {}

    return loss


def make_train_step(loss_fn, has_batch_stats: bool = False, donate: bool = True,
                    jit: bool = True, grad_accum: int = 1):
    """Build `step(state, batch, rng) -> (state, metrics)` under jit.

    jit=False returns the raw traceable step for callers that embed it in a
    larger compiled region (e.g. `lax.scan` over steps in bench harnesses).

    grad_accum > 1 splits the batch's leading dim into that many
    microbatches and accumulates their mean gradient in a `lax.scan` before
    the single optimizer update — same optimizer math as one big batch
    (exact for mean-reduced losses), HBM held to one microbatch of
    activations.  The per-device trade XLA sees: grad_accum× smaller
    live activation sets, same MXU work."""
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def step(state: TrainState, batch, rng=None):
        def compute(params, mb, bs, rngs):
            if has_batch_stats:
                loss, aux = loss_fn(params, mb, bs, rngs=rngs)
            else:
                loss, aux = loss_fn(params, mb, rngs=rngs)
            return loss, aux

        if grad_accum == 1:
            rngs = {"dropout": rng} if rng is not None else None
            (loss, aux), grads = jax.value_and_grad(compute, has_aux=True)(
                state.params, batch, state.batch_stats, rngs)
            new_state = state.apply_gradients(grads, aux.get("batch_stats"))
            metrics = {"loss": loss}
            if "moe_aux_loss" in aux:
                metrics["moe_aux_loss"] = aux["moe_aux_loss"]
            return new_state, metrics

        def split(x):
            shape = getattr(x, "shape", ())
            if not shape:
                return x
            if shape[0] % grad_accum:
                raise ValueError(
                    f"batch leading dim {shape[0]} must divide by "
                    f"grad_accum={grad_accum}"
                )
            return x.reshape((grad_accum, shape[0] // grad_accum) + shape[1:])

        micro = jax.tree_util.tree_map(split, batch)
        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, state.params)
        has_moe = []  # set at trace time (scan traces body once)

        def body(carry, xs):
            gsum, loss_sum, aux_sum, bs = carry
            mb, idx = xs
            rngs = (
                {"dropout": jax.random.fold_in(rng, idx)}
                if rng is not None else None
            )
            (loss, aux), g = jax.value_and_grad(compute, has_aux=True)(
                state.params, mb, bs, rngs)
            gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
            if has_batch_stats:
                bs = aux["batch_stats"]
            if "moe_aux_loss" in aux:  # Python-level: aux keys are static
                has_moe.append(True)
                aux_sum = aux_sum + aux["moe_aux_loss"]
            return (gsum, loss_sum + loss, aux_sum, bs), None

        (gsum, loss_sum, aux_sum, bs), _ = jax.lax.scan(
            body,
            (zero_grads, jnp.float32(0.0), jnp.float32(0.0), state.batch_stats),
            (micro, jnp.arange(grad_accum)),
        )
        grads = jax.tree_util.tree_map(lambda g: g / grad_accum, gsum)
        new_state = state.apply_gradients(
            grads, bs if has_batch_stats else None)
        metrics = {"loss": loss_sum / grad_accum}
        if has_moe:
            metrics["moe_aux_loss"] = aux_sum / grad_accum
        return new_state, metrics

    if not jit:
        return step
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def classification_metrics(apply_fn, model_kwargs: Optional[dict] = None):
    """Eval-side metric fn: loss + accuracy from a forward pass (running
    batch stats used read-only — pair with e.g. model_kwargs={'train': False}
    for BatchNorm models)."""
    model_kwargs = dict(model_kwargs or {})

    def metric_fn(params, batch, batch_stats=None):
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
        out = apply_fn(variables, batch["x"], **model_kwargs)
        logits = _logits(out)
        labels = batch["label"]
        return {
            "loss": softmax_cross_entropy(logits, labels),
            "accuracy": jnp.mean(
                (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
            ),
        }

    return metric_fn


def make_eval_step(metric_fn, jit: bool = True):
    """Build `eval_step(state, batch) -> metrics` — forward-only (no grads,
    no state mutation), jitted by default."""

    def step(state: TrainState, batch):
        return metric_fn(state.params, batch, state.batch_stats)

    return jax.jit(step) if jit else step


def shard_train_state(
    state: TrainState, mesh: Mesh, zero_plan=None
) -> TrainState:
    """Place params/opt_state per tp+fsdp rules, everything else replicated.

    Optimizer moments (mu/nu) mirror the param tree; each moment leaf is
    matched to its param by tree-path suffix + shape (train/zero.py — never
    shape alone: two params can share a shape) and placed on that param's
    layout.  With `zero_plan` (ZeRO-style weight-update sharding), moments
    additionally shard over the plan's dp axis; unmatched leaves (step
    counts, empty states) replicate either way.
    """
    from .zero import base_placement_plan, place_opt_state

    param_sh = make_param_shardings(state.params, mesh)
    params = jax.device_put(state.params, param_sh)

    plan = zero_plan
    if plan is None:
        plan = base_placement_plan(state.params, mesh, base_specs=param_sh)
    opt_state = place_opt_state(state.opt_state, plan, mesh)
    batch_stats = (
        jax.device_put(state.batch_stats, replicated(mesh))
        if state.batch_stats is not None
        else None
    )
    return state.replace(
        step=jax.device_put(state.step, replicated(mesh)),
        params=params,
        opt_state=opt_state,
        batch_stats=batch_stats,
        zero_plan=zero_plan if zero_plan is not None else state.zero_plan,
    )


def shard_batch(batch, mesh: Mesh):
    # Fail with the actual constraint, not a device_put internals traceback:
    # the leading dim of every leaf must divide the mesh's data axes.
    # Rank-0 leaves (e.g. a scalar loss weight) have no batch dim and are
    # replicated instead.
    n_data = int(np.prod([mesh.shape[a] for a in data_axes(mesh)], initial=1))
    if n_data > 1:
        for path, leaf in jax.tree_util.tree_flatten_with_path(batch)[0]:
            shape = getattr(leaf, "shape", ())
            if shape and shape[0] % n_data:
                name = "/".join(str(getattr(k, "key", k)) for k in path)
                raise ValueError(
                    f"batch leaf {name!r} has leading dim {shape[0]}, which "
                    f"the mesh's data axes (size {n_data}, mesh "
                    f"{dict(mesh.shape)}) don't divide — use a batch that "
                    f"is a multiple of {n_data}"
                )
    data = batch_sharding(mesh)
    repl = replicated(mesh)
    return jax.tree_util.tree_map(
        lambda leaf: jax.device_put(
            leaf, data if getattr(leaf, "shape", ()) else repl
        ),
        batch,
    )
