"""Data pipelines: synthetic, learnable datasets for hermetic training.

The sandbox has zero egress, so real MNIST/ImageNet are unavailable; these
generators produce *learnable* class-conditional data (not noise) so tests
can assert that loss actually decreases — the analogue of the reference's
controllable fake workload strategy (SURVEY.md §4 Tier 3).
"""
from __future__ import annotations

from typing import Dict, Iterator

import jax
import jax.numpy as jnp
import numpy as np


def synthetic_mnist(batch_size: int, seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """28x28 'digits': class-dependent stripe/checker patterns + noise."""
    rng = np.random.RandomState(seed)
    ys, xs = np.mgrid[0:28, 0:28]
    templates = np.stack(
        [np.sin(xs * (c + 1) * 0.35 + ys * (9 - c) * 0.15) for c in range(10)]
    ).astype(np.float32)
    while True:
        labels = rng.randint(0, 10, size=batch_size)
        images = templates[labels] + rng.randn(batch_size, 28, 28).astype(np.float32) * 0.3
        yield {"x": images.reshape(batch_size, 784), "label": labels.astype(np.int32)}


def synthetic_images(batch_size: int, image_size: int = 224, num_classes: int = 1000,
                     seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """ImageNet-shaped class-conditional images (for ResNet benchmarking)."""
    rng = np.random.RandomState(seed)
    freq = (np.arange(num_classes) % 13 + 1).astype(np.float32)
    ys = np.linspace(0, np.pi * 2, image_size, dtype=np.float32)
    while True:
        labels = rng.randint(0, num_classes, size=batch_size)
        base = np.sin(ys[None, :, None] * freq[labels][:, None, None])
        images = (
            base[..., None]
            + rng.randn(batch_size, image_size, image_size, 3).astype(np.float32) * 0.5
        )
        yield {"x": images.astype(np.float32), "label": labels.astype(np.int32)}


def prefetch_to_device(it: Iterator, mesh=None, size: int = 2) -> Iterator:
    """Overlap host->device transfer with compute: keep up to `size` batches
    resident on device ahead of the consumer.  jax transfers are async, so
    issuing the device_put for batch N+1 before the consumer needs it hides
    the PCIe/host copy behind step N's device work — the input-pipeline half
    of the HBM-bandwidth story (the dispatch itself is cheap; the win is the
    copy running concurrently with the step).

    With a mesh, batches are placed via shard_batch (leading dim over the
    data axes); without, a plain device_put.
    """
    import collections

    from .step import shard_batch

    def place(batch):
        if mesh is not None:
            return shard_batch(batch, mesh)
        return jax.tree_util.tree_map(jax.device_put, batch)

    queue = collections.deque()
    for batch in it:
        queue.append(place(batch))
        if len(queue) > size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


def synthetic_tokens(batch_size: int, seq_len: int, vocab_size: int = 32000,
                     seed: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Markov-ish token streams with learnable bigram structure."""
    rng = np.random.RandomState(seed)
    next_tok = (np.arange(vocab_size) * 31 + 7) % vocab_size
    while True:
        start = rng.randint(0, vocab_size, size=batch_size)
        toks = np.empty((batch_size, seq_len), dtype=np.int32)
        toks[:, 0] = start
        for t in range(1, seq_len):
            noise = rng.rand(batch_size) < 0.1
            toks[:, t] = np.where(
                noise, rng.randint(0, vocab_size, size=batch_size), next_tok[toks[:, t - 1]]
            )
        yield {"tokens": toks}
