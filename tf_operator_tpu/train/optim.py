"""Optimizer construction for the LM workloads.

The reference delegates all of this to user containers; here the runtime
owns the training loop, so it ships the standard modern-LM recipe: AdamW
with linear warmup + cosine decay, global-norm gradient clipping, and
weight decay applied only to matrices (biases, norm scales and other
rank<2 params are excluded — decaying a RMSNorm scale toward zero is a
bug, not regularization).
"""
from __future__ import annotations

from typing import Optional

import jax
import optax


def decay_mask(params):
    """True for leaves weight decay applies to: rank >= 2 (matmul kernels,
    embeddings); biases / norm scales / scalars are excluded."""
    return jax.tree_util.tree_map(
        lambda p: getattr(p, "ndim", 0) >= 2, params
    )


def lr_schedule(peak_lr: float, *, schedule: str = "constant",
                warmup_steps: int = 0, total_steps: Optional[int] = None,
                end_fraction: float = 0.1):
    """A learning-rate schedule: linear warmup from 0 over `warmup_steps`,
    then constant, or cosine decay to `end_fraction * peak_lr` by
    `total_steps` (required for cosine)."""
    if schedule not in ("constant", "cosine"):
        raise ValueError(f"schedule must be 'constant'|'cosine', got {schedule!r}")
    if schedule == "cosine":
        if not total_steps:
            raise ValueError("cosine schedule needs total_steps")
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=peak_lr, warmup_steps=warmup_steps,
            decay_steps=total_steps, end_value=peak_lr * end_fraction,
        )
    if warmup_steps:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, peak_lr, warmup_steps),
             optax.constant_schedule(peak_lr)],
            [warmup_steps],
        )
    return optax.constant_schedule(peak_lr)


def lm_optimizer(peak_lr: float, *, schedule: str = "constant",
                 warmup_steps: int = 0, total_steps: Optional[int] = None,
                 weight_decay: float = 0.1, grad_clip: float = 1.0,
                 b1: float = 0.9, b2: float = 0.95,
                 zero_plan=None, mesh=None):
    """AdamW + clipping + masked decay under the configured schedule.

    With `zero_plan` (a train/zero.py ZeroShardingPlan) and its `mesh`, the
    whole chain is wrapped so optimizer state and the weight update shard
    over the plan's dp axis (ZeRO-style, arXiv:2004.13336) — clipping stays
    inside the wrapper, so the global norm is computed once over the
    logically-global gradients, not per shard."""
    sched = lr_schedule(peak_lr, schedule=schedule,
                        warmup_steps=warmup_steps, total_steps=total_steps)
    parts = []
    if grad_clip:
        parts.append(optax.clip_by_global_norm(grad_clip))
    parts.append(optax.adamw(sched, b1=b1, b2=b2,
                             weight_decay=weight_decay, mask=decay_mask))
    tx = optax.chain(*parts)
    if zero_plan is not None:
        if mesh is None:
            raise ValueError("zero_plan needs the mesh it was built for")
        from .zero import zero_shard_optimizer

        tx = zero_shard_optimizer(tx, zero_plan, mesh)
    return tx
