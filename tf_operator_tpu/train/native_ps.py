"""ctypes bindings + binary-protocol client for the native (C++) PS shard.

Builds native/ps_server.cpp on first use (g++, cached as
native/libtpujob_ps.so).  `NativeParameterServer` hosts a shard on C++
threads (no pickle, no GIL on the serve path); `NativePSClient` is
API-compatible with train/ps.py's `PSClient` (pull/push/shutdown_servers/
close) and speaks the length-prefixed binary tensor protocol documented in
native/ps_server.cpp.  The Python PS remains the reference implementation;
callers pick the transport via `make_ps_client` / `native_ps_available`.

Reference analogue: none — the reference's PS data path is TF's gRPC runtime
inside user containers (SURVEY.md §2.9); this is the framework-owned native
equivalent.
"""
from __future__ import annotations

import ctypes
import os
import socket
import struct
from typing import Dict, List, Optional

import numpy as np

from ..utils import locks
from ..utils.native_build import load_native_lib
from .ps import BasePSClient

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "ps_server.cpp"))
_LIB = os.path.abspath(os.path.join(_NATIVE_DIR, "libtpujob_ps.so"))

_OP_PULL = 1
_OP_PUSH = 2
_OP_SHUTDOWN = 3

_FRAME = struct.Struct("<BQ")  # op, payload length
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

_lock = locks.new_lock("native-ps-build")
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _lock
_build_failed = False  # guarded-by: _lock


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib = load_native_lib(_SRC, _LIB)
        if lib is None:
            _build_failed = True
            return None
        lib.tpujob_ps_create.restype = ctypes.c_void_p
        lib.tpujob_ps_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_float]
        lib.tpujob_ps_add_param.restype = ctypes.c_int
        lib.tpujob_ps_add_param.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.tpujob_ps_get_param.restype = ctypes.c_int
        lib.tpujob_ps_get_param.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_uint64,
        ]
        lib.tpujob_ps_start.restype = ctypes.c_int
        lib.tpujob_ps_start.argtypes = [ctypes.c_void_p]
        lib.tpujob_ps_port.restype = ctypes.c_int
        lib.tpujob_ps_port.argtypes = [ctypes.c_void_p]
        lib.tpujob_ps_version.restype = ctypes.c_uint64
        lib.tpujob_ps_version.argtypes = [ctypes.c_void_p]
        lib.tpujob_ps_wait.argtypes = [ctypes.c_void_p]
        lib.tpujob_ps_stop.argtypes = [ctypes.c_void_p]
        lib.tpujob_ps_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_ps_available() -> bool:
    return _load() is not None


class NativeParameterServer:
    """One C++-hosted PS shard (same role as ps.ParameterServer)."""

    def __init__(self, address, params: Dict[str, np.ndarray],
                 lr: float = 0.1) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native PS library unavailable (g++ build failed)")
        self._lib = lib
        host, port = address
        self._handle = lib.tpujob_ps_create(
            (host or "0.0.0.0").encode(), int(port), float(lr)
        )
        self._shapes: Dict[str, tuple] = {}
        for name, value in params.items():
            arr = np.ascontiguousarray(value, np.float32)
            self._shapes[name] = arr.shape
            lib.tpujob_ps_add_param(
                self._handle, name.encode(),
                arr.ctypes.data_as(ctypes.c_void_p), arr.size,
            )
        if lib.tpujob_ps_start(self._handle) != 0:
            lib.tpujob_ps_destroy(self._handle)
            raise OSError(f"native PS failed to bind {host}:{port}")

    @property
    def port(self) -> int:
        return self._lib.tpujob_ps_port(self._handle)

    @property
    def version(self) -> int:
        return int(self._lib.tpujob_ps_version(self._handle))

    def get_param(self, name: str) -> np.ndarray:
        shape = self._shapes[name]
        out = np.empty(shape, np.float32)
        rc = self._lib.tpujob_ps_get_param(
            self._handle, name.encode(),
            out.ctypes.data_as(ctypes.c_void_p), out.size,
        )
        if rc != 0:
            raise KeyError(name)
        return out

    def serve_until_shutdown(self) -> None:
        self._lib.tpujob_ps_wait(self._handle)
        self._lib.tpujob_ps_stop(self._handle)

    def stop(self) -> None:
        self._lib.tpujob_ps_stop(self._handle)

    def close(self) -> None:
        if self._handle:
            self._lib.tpujob_ps_stop(self._handle)
            self._lib.tpujob_ps_destroy(self._handle)
            self._handle = None


def _pack_tensors(tensors: Dict[str, np.ndarray]) -> bytes:
    parts = [_U32.pack(len(tensors))]
    for name, value in tensors.items():
        arr = np.ascontiguousarray(value, np.float32)
        encoded = name.encode()
        parts.append(_U16.pack(len(encoded)))
        parts.append(encoded)
        parts.append(_U64.pack(arr.size))
        parts.append(arr.tobytes())
    return b"".join(parts)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _read_tensors(sock: socket.socket) -> Dict[str, np.ndarray]:
    (count,) = _U32.unpack(_recv_exact(sock, 4))
    out: Dict[str, np.ndarray] = {}
    for _ in range(count):
        (nlen,) = _U16.unpack(_recv_exact(sock, 2))
        name = _recv_exact(sock, nlen).decode()
        (elems,) = _U64.unpack(_recv_exact(sock, 8))
        data = _recv_exact(sock, elems * 4)
        out[name] = np.frombuffer(data, np.float32).copy()
    return out


class NativePSClient(BasePSClient):
    """Binary-protocol transport over the shared client shell (routing,
    partial-push fan-out, shutdown live in ps.BasePSClient).

    Note the flat-vector difference from the Python transport: the wire
    carries shapeless float32 buffers, so pulled params come back 1-D and the
    caller reshapes against its local tree (ps.unflatten_params users already
    reshape via the model's init shapes)."""

    def _request(self, i: int, op: int, payload: bytes = b"") -> socket.socket:
        sock = self._sock(i)
        sock.sendall(_FRAME.pack(op, len(payload)) + payload)
        return sock

    def _pull_shard(self, i: int) -> Dict[str, np.ndarray]:
        sock = self._request(i, _OP_PULL)
        _version = _U64.unpack(_recv_exact(sock, 8))[0]
        return _read_tensors(sock)

    def _push_shard(self, i: int, grads: Dict[str, np.ndarray]) -> None:
        sock = self._request(i, _OP_PUSH, _pack_tensors(grads))
        _U64.unpack(_recv_exact(sock, 8))

    def _shutdown_shard(self, i: int) -> None:
        sock = self._request(i, _OP_SHUTDOWN)
        _recv_exact(sock, 8)
