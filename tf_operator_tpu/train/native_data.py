"""ctypes bindings for the native (C++) prefetching data loader.

Builds native/dataloader.cpp on first use (g++, cached as
native/libtpujob_data.so) and exposes iterators matching train/data.py's
shapes.  Falls back cleanly: callers should use `native_available()` or the
`*_or_fallback` helpers — the Python generators remain the reference
implementation.
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict, Iterator, Optional

import numpy as np

from ..utils import locks
from ..utils.native_build import load_native_lib

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SRC = os.path.abspath(os.path.join(_NATIVE_DIR, "dataloader.cpp"))
_LIB = os.path.abspath(os.path.join(_NATIVE_DIR, "libtpujob_data.so"))

_KIND_IMAGES = 0
_KIND_MNIST = 1
_KIND_TOKENS = 2

_lock = locks.new_lock("native-data-build")
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _lock
_build_failed = False  # guarded-by: _lock


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib = load_native_lib(_SRC, _LIB)
        if lib is None:
            _build_failed = True
            return None
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [ctypes.c_int] * 5 + [
            ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ]
        lib.dl_next.restype = ctypes.c_int
        lib.dl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.dl_x_size.restype = ctypes.c_int64
        lib.dl_x_size.argtypes = [ctypes.c_void_p]
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


class _NativeIterator:
    def __init__(self, kind: int, batch: int, dim1: int, num_classes: int,
                 seed: int, x_shape, has_labels: bool, key_x: str,
                 prefetch_depth: int = 4, num_threads: int = 2) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native dataloader unavailable")
        self._lib = lib
        self._handle = lib.dl_create(
            kind, batch, dim1, 0, num_classes, seed & 0xFFFFFFFF,
            prefetch_depth, num_threads,
        )
        self._batch = batch
        self._x_shape = x_shape
        self._has_labels = has_labels
        self._key_x = key_x
        self._x_buf = np.empty(int(lib.dl_x_size(self._handle)), np.float32)
        self._y_buf = np.empty(batch, np.int32)

    def __iter__(self):
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        if self._handle is None:
            raise StopIteration  # closed: a NULL handle would segfault in C++
        rc = self._lib.dl_next(
            self._handle,
            self._x_buf.ctypes.data_as(ctypes.c_void_p),
            self._y_buf.ctypes.data_as(ctypes.c_void_p) if self._has_labels else None,
        )
        if rc != 0:
            raise StopIteration
        x = self._x_buf.reshape(self._x_shape).copy()
        if self._key_x == "tokens":
            return {"tokens": x.astype(np.int32)}
        out = {self._key_x: x}
        if self._has_labels:
            out["label"] = self._y_buf.copy()
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.dl_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:  # lint: allow(swallow) — interpreter-shutdown teardown; logging machinery may already be torn down
            pass


def native_synthetic_images(batch_size: int, image_size: int = 224,
                            num_classes: int = 1000, seed: int = 0,
                            num_threads: int = 4) -> _NativeIterator:
    return _NativeIterator(
        _KIND_IMAGES, batch_size, image_size, num_classes, seed,
        (batch_size, image_size, image_size, 3), True, "x",
        num_threads=num_threads,
    )


def native_synthetic_mnist(batch_size: int, seed: int = 0) -> _NativeIterator:
    return _NativeIterator(
        _KIND_MNIST, batch_size, 28, 10, seed, (batch_size, 784), True, "x"
    )


def native_synthetic_tokens(batch_size: int, seq_len: int,
                            vocab_size: int = 32000, seed: int = 0) -> _NativeIterator:
    return _NativeIterator(
        _KIND_TOKENS, batch_size, seq_len, vocab_size, seed,
        (batch_size, seq_len), False, "tokens"
    )


def images_or_fallback(batch_size: int, image_size: int = 224,
                       num_classes: int = 1000, seed: int = 0) -> Iterator:
    if native_available():
        return native_synthetic_images(batch_size, image_size, num_classes, seed)
    from .data import synthetic_images

    return synthetic_images(batch_size, image_size, num_classes, seed)
