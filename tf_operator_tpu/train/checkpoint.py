"""Checkpoint / resume.

The reference deliberately keeps checkpointing out of the operator and relies
on (a) stable pod identity and (b) volume passthrough so user containers can
save/restore (SURVEY.md §5).  This framework owns the training runtime, so it
ships the other half: orbax-backed save/restore of TrainState keyed by step,
with the same contract the restart state machine needs — a preempted gang
that restarts (ExitCode/137) resumes from the latest step.

Orbax handles sharded arrays natively: on restore the target shardings come
from the live TrainState template, so a checkpoint written on one mesh can be
read on another (elastic resume).
"""
from __future__ import annotations

import os
from typing import Any, Optional

from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._mgr = None

    def _manager(self):
        if self._mgr is None:
            import orbax.checkpoint as ocp

            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.max_to_keep, create=True
                ),
            )
        return self._mgr

    def save(self, state: TrainState, step: Optional[int] = None, wait: bool = True) -> int:
        import jax
        import orbax.checkpoint as ocp

        step = int(state.step) if step is None else step
        payload = {
            "params": state.params,
            "opt_state": state.opt_state,
            "step": state.step,
        }
        if state.batch_stats is not None:
            payload["batch_stats"] = state.batch_stats
        self._manager().save(step, args=ocp.args.StandardSave(payload))
        if wait:
            self._manager().wait_until_finished()
        return step

    def latest_step(self) -> Optional[int]:
        return self._manager().latest_step()

    def restore(self, template: TrainState, step: Optional[int] = None) -> TrainState:
        """Restore into the template's structure/shardings; returns a new
        TrainState (template unchanged if no checkpoint exists)."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            return template
        target = {
            "params": template.params,
            "opt_state": template.opt_state,
            "step": template.step,
        }
        if template.batch_stats is not None:
            target["batch_stats"] = template.batch_stats
        restored = self._manager().restore(
            step, args=ocp.args.StandardRestore(target)
        )
        return template.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            step=restored["step"],
            batch_stats=restored.get("batch_stats", template.batch_stats),
        )

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
