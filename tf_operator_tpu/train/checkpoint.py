"""Checkpoint / resume.

The reference deliberately keeps checkpointing out of the operator and relies
on (a) stable pod identity and (b) volume passthrough so user containers can
save/restore (SURVEY.md §5).  This framework owns the training runtime, so it
ships the other half: orbax-backed save/restore of TrainState keyed by step,
with the same contract the restart state machine needs — a preempted gang
that restarts (ExitCode/137) resumes from the latest step.

Orbax handles sharded arrays natively: on restore the target shardings come
from the live TrainState template, so a checkpoint written on one mesh can be
read on another (elastic resume).  That same contract covers ZeRO-sharded
optimizer state (train/zero.py): moments saved sharded over dp=N restore
onto a template whose plan was built for dp=M — the template's shardings ARE
the new plan's layout, so the restore re-shards (docs/zero-sharding.md).
The plan a checkpoint was written under is persisted as a JSON sidecar
(`zero_plan-<step>.json`) next to the step directory, so a resuming process
can inspect what layout the bytes describe before deciding its own.
"""
from __future__ import annotations

import os
from typing import Any, Optional

from .state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._mgr = None

    def _manager(self):
        if self._mgr is None:
            import orbax.checkpoint as ocp

            self._mgr = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self.max_to_keep, create=True
                ),
            )
        return self._mgr

    def save(self, state: TrainState, step: Optional[int] = None, wait: bool = True) -> int:
        import jax
        import orbax.checkpoint as ocp

        step = int(state.step) if step is None else step
        payload = {
            "params": state.params,
            "opt_state": state.opt_state,
            "step": state.step,
        }
        if state.batch_stats is not None:
            payload["batch_stats"] = state.batch_stats
        self._manager().save(step, args=ocp.args.StandardSave(payload))
        if state.zero_plan is not None:
            # Sidecar, not part of the orbax payload: the plan is layout
            # metadata about the arrays, not an array, and must stay
            # readable without materializing a template.
            with open(self._plan_path(step), "w") as f:
                f.write(state.zero_plan.to_json())
        self._prune_plan_sidecars(keep_also=step)
        if wait:
            self._manager().wait_until_finished()
        return step

    def _plan_path(self, step: int) -> str:
        return os.path.join(self.directory, f"zero_plan-{step}.json")

    def _prune_plan_sidecars(self, keep_also: int) -> None:
        """Follow orbax's max_to_keep GC: a sidecar must not outlive its
        step directory (saved_zero_plan would describe deleted bytes).
        The just-saved step is kept even while its async write is in
        flight (all_steps may not list it yet)."""
        keep = set(self._manager().all_steps()) | {keep_also}
        for name in os.listdir(self.directory):
            if not (name.startswith("zero_plan-") and name.endswith(".json")):
                continue
            try:
                step = int(name[len("zero_plan-"):-len(".json")])
            except ValueError:
                continue
            if step not in keep:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass  # lint: allow(swallow)

    def saved_zero_plan(self, step: Optional[int] = None, mesh=None):
        """The ZeroShardingPlan checkpoint `step` (default latest) was
        written under, or None for dense checkpoints.  Pass the resuming
        process's `mesh` when the plan will be installed on a TrainState:
        a mesh-less plan cannot pin the updated-params all-gather in
        apply_gradients (the per-step layout flip that pin exists to
        prevent — docs/zero-sharding.md)."""
        from .zero import ZeroShardingPlan

        step = self.latest_step() if step is None else step
        if step is None or not os.path.exists(self._plan_path(step)):
            return None
        with open(self._plan_path(step)) as f:
            return ZeroShardingPlan.from_json(f.read(), mesh=mesh)

    def latest_step(self) -> Optional[int]:
        return self._manager().latest_step()

    def restore(self, template: TrainState, step: Optional[int] = None) -> TrainState:
        """Restore into the template's structure/shardings; returns a new
        TrainState (template unchanged if no checkpoint exists)."""
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            return template
        target = {
            "params": template.params,
            "opt_state": template.opt_state,
            "step": template.step,
        }
        if template.batch_stats is not None:
            target["batch_stats"] = template.batch_stats
        restored = self._manager().restore(
            step, args=ocp.args.StandardRestore(target)
        )
        return template.replace(
            params=restored["params"],
            opt_state=restored["opt_state"],
            step=restored["step"],
            batch_stats=restored.get("batch_stats", template.batch_stats),
        )

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.close()
