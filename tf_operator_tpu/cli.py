"""tpujob CLI: kubectl-style verbs against the operator's REST API.

Usage:
  python -m tf_operator_tpu.cli apply -f job.yaml
  python -m tf_operator_tpu.cli get [NAME] [-n NS] [-o json]
  python -m tf_operator_tpu.cli wait NAME [--timeout 300]
  python -m tf_operator_tpu.cli logs NAME [--replica-type worker]
  python -m tf_operator_tpu.cli delete NAME
  python -m tf_operator_tpu.cli events NAME

The reference offers kubectl + its Python SDK for this surface
(docs/quick-start-v1.md); this CLI folds both into the framework.
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def _client(args):
    from .sdk.client import TPUJobClient
    from .sdk.remote import RemoteCluster

    cluster = RemoteCluster(args.server)
    return TPUJobClient(cluster, namespace=args.namespace)


def _format_age(ts):
    if not ts:
        return "-"
    secs = int(time.time() - ts)
    if secs < 120:
        return f"{secs}s"
    if secs < 7200:
        return f"{secs // 60}m"
    return f"{secs // 3600}h"


def cmd_apply(args) -> int:
    from .api.serialization import job_from_manifest

    client = _client(args)
    with (sys.stdin if args.filename == "-" else open(args.filename)) as f:
        job = job_from_manifest(f.read())
    created = client.create(job)
    print(f"tpujob.{created.metadata.namespace}/{created.metadata.name} created")
    return 0


def cmd_get(args) -> int:
    from .api.serialization import job_to_dict

    client = _client(args)
    if args.name:
        jobs = [client.get(args.name)]
    else:
        jobs = client.cluster.list_jobs(args.namespace)
    if args.output == "json":
        payload = [job_to_dict(j) for j in jobs]
        print(json.dumps(payload[0] if args.name else payload, indent=2))
        return 0
    print(f"{'NAME':30} {'STATE':12} {'AGE':6}")
    for job in jobs:
        state = ""
        for cond in reversed(job.status.conditions):
            if cond.status:
                state = cond.type.value
                break
        print(f"{job.metadata.name:30} {state or 'Pending':12} "
              f"{_format_age(job.metadata.creation_timestamp):6}")
    return 0


def cmd_wait(args) -> int:
    client = _client(args)
    job = client.wait_for_job(args.name, timeout=args.timeout)
    state = client.get_job_status(args.name)
    print(f"tpujob {args.name}: {state}")
    return 0 if state == "Succeeded" else 1


def cmd_logs(args) -> int:
    client = _client(args)
    logs = client.get_logs(args.name, replica_type=args.replica_type)
    for pod, text in logs.items():
        print(f"==> {pod} <==")
        print(text)
    return 0


def cmd_delete(args) -> int:
    client = _client(args)
    client.delete(args.name)
    print(f"tpujob {args.name} deleted")
    return 0


def cmd_events(args) -> int:
    client = _client(args)
    for event in client.get_events(args.name):
        print(f"{event.event_type:8} {event.reason:24} {event.message}")
    return 0


def cmd_watch(args) -> int:
    from tf_operator_tpu.sdk.watch import watch

    client = _client(args)
    try:
        watch(client, args.name, timeout=args.timeout)
    except TimeoutError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    return 0 if client.is_job_succeeded(args.name) else 1


def cmd_version(args) -> int:
    from tf_operator_tpu.version import version_string

    print(version_string())
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("tpujob")
    parser.add_argument("--server", default="http://127.0.0.1:8008")
    parser.add_argument("-n", "--namespace", default="default")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("apply")
    p.add_argument("-f", "--filename", required=True)
    p.set_defaults(fn=cmd_apply)

    p = sub.add_parser("get")
    p.add_argument("name", nargs="?")
    p.add_argument("-o", "--output", choices=("wide", "json"), default="wide")
    p.set_defaults(fn=cmd_get)

    p = sub.add_parser("wait")
    p.add_argument("name")
    p.add_argument("--timeout", type=float, default=300.0)
    p.set_defaults(fn=cmd_wait)

    p = sub.add_parser("logs")
    p.add_argument("name")
    p.add_argument("--replica-type", default=None)
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("delete")
    p.add_argument("name")
    p.set_defaults(fn=cmd_delete)

    p = sub.add_parser("events")
    p.add_argument("name")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("watch")
    p.add_argument("name")
    p.add_argument("--timeout", type=float, default=600.0)
    p.set_defaults(fn=cmd_watch)

    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
