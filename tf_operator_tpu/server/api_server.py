"""REST API: the apiserver-shaped surface of the operator.

The reference's SDK talks to the Kubernetes CustomObjects REST API
(/root/reference/sdk/python/kubeflow/tfjob/api/tf_job_client.py) and its E2E
suite reaches pods through the apiserver proxy.  This module provides the
equivalent HTTP surface for the local runtime so out-of-process clients
(sdk.remote.RemoteCluster, the tpujob CLI) can submit and watch jobs:

  POST   /apis/v1/namespaces/{ns}/tpujobs            create (JSON manifest)
  GET    /apis/v1/namespaces/{ns}/tpujobs            list
  GET    /apis/v1/namespaces/{ns}/tpujobs/{name}     get
  PUT    /apis/v1/namespaces/{ns}/tpujobs/{name}     replace spec
  DELETE /apis/v1/namespaces/{ns}/tpujobs/{name}     delete
  GET    /apis/v1/namespaces/{ns}/pods[?selector=k=v,...]
  GET    /apis/v1/namespaces/{ns}/pods/{name}/log
  GET    /apis/v1/namespaces/{ns}/events[?object=name]
"""
from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..api.serialization import job_from_dict, job_to_dict
from ..runtime.cluster import AlreadyExists, ClusterInterface, NotFound
from .probes import probe_response

_JOB_RE = re.compile(r"^/apis/v1/namespaces/([^/]+)/tpujobs(?:/([^/]+))?$")
_POD_RE = re.compile(r"^/apis/v1/namespaces/([^/]+)/pods(?:/([^/]+)(/log)?)?$")
_EVENT_RE = re.compile(r"^/apis/v1/namespaces/([^/]+)/events$")


def _pod_to_dict(pod) -> dict:
    return {
        "metadata": {
            "name": pod.metadata.name,
            "namespace": pod.metadata.namespace,
            "labels": dict(pod.metadata.labels),
            "annotations": dict(pod.metadata.annotations),
        },
        "status": {
            "phase": pod.status.phase.value,
            "startTime": pod.status.start_time,
            "containerStatuses": [
                {
                    "name": cs.name,
                    "restartCount": cs.restart_count,
                    "running": cs.running,
                    "terminated": cs.terminated,
                    "exitCode": cs.exit_code,
                }
                for cs in pod.status.container_statuses
            ],
        },
    }


def make_handler(cluster: ClusterInterface, health_provider=None):
    class ApiHandler(BaseHTTPRequestHandler):
        server_version = "tpu-operator-api"

        # ------------------------------------------------------------------
        def _send(self, code: int, payload) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_error(self, code: int, message: str) -> None:
            self._send(code, {"error": message})

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")

        # ------------------------------------------------------------------
        def do_GET(self):  # noqa: N802
            parsed = urlparse(self.path)
            query = parse_qs(parsed.query)

            m = _JOB_RE.match(parsed.path)
            if m:
                ns, name = m.group(1), m.group(2)
                try:
                    if name:
                        self._send(200, job_to_dict(cluster.get_job(ns, name)))
                    else:
                        self._send(200, {
                            "items": [job_to_dict(j) for j in cluster.list_jobs(ns)]
                        })
                except NotFound as err:
                    self._send_error(404, str(err))
                return

            m = _POD_RE.match(parsed.path)
            if m:
                ns, name, want_log = m.group(1), m.group(2), m.group(3)
                try:
                    if name and want_log:
                        getter = getattr(cluster, "pod_logs", None)
                        text = getter(ns, name) if getter else ""
                        self._send(200, {"log": text})
                    elif name:
                        self._send(200, _pod_to_dict(cluster.get_pod(ns, name)))
                    else:
                        selector = None
                        if "selector" in query:
                            selector = dict(
                                part.split("=", 1)
                                for part in query["selector"][0].split(",")
                                if "=" in part
                            )
                        pods = cluster.list_pods(ns, selector)
                        self._send(200, {"items": [_pod_to_dict(p) for p in pods]})
                except NotFound as err:
                    self._send_error(404, str(err))
                return

            m = _EVENT_RE.match(parsed.path)
            if m:
                ns = m.group(1)
                obj = query.get("object", [None])[0]
                events = cluster.list_events(ns, obj)
                self._send(200, {"items": [
                    {"type": e.event_type, "reason": e.reason, "message": e.message,
                     "object": e.object_name, "timestamp": e.timestamp}
                    for e in events
                ]})
                return

            if parsed.path in ("/healthz", "/livez", "/readyz"):
                # Deep health when a controller is wired (docs/self-healing.md):
                # the aggregated live/ready report, with the status code per
                # the k8s probe contract (probes.probe_response, shared with
                # the monitoring port).  Provider-less servers (bare API over
                # a cluster) stay ok.
                self._send(*probe_response(parsed.path, health_provider))
                return
            self._send_error(404, f"unknown path {parsed.path}")

        def do_POST(self):  # noqa: N802
            m = _JOB_RE.match(urlparse(self.path).path)
            if not (m and not m.group(2)):
                self._send_error(404, "POST only supported on the tpujobs collection")
                return
            ns = m.group(1)
            try:
                job = job_from_dict(self._body())
            except (ValueError, KeyError) as err:
                self._send_error(400, f"bad manifest: {err}")
                return
            job.metadata.namespace = ns
            try:
                created = cluster.create_job(job)
            except AlreadyExists as err:
                self._send_error(409, str(err))
                return
            self._send(201, job_to_dict(created))

        def do_PUT(self):  # noqa: N802
            m = _JOB_RE.match(urlparse(self.path).path)
            if not (m and m.group(2)):
                self._send_error(404, "PUT requires a job name")
                return
            ns, name = m.group(1), m.group(2)
            try:
                current = cluster.get_job(ns, name)
                incoming = job_from_dict(self._body())
                current.spec = incoming.spec
                updated = cluster.update_job(current)
                self._send(200, job_to_dict(updated))
            except NotFound as err:
                self._send_error(404, str(err))

        def do_DELETE(self):  # noqa: N802
            m = _JOB_RE.match(urlparse(self.path).path)
            if not (m and m.group(2)):
                self._send_error(404, "DELETE requires a job name")
                return
            try:
                cluster.delete_job(m.group(1), m.group(2))
                self._send(200, {"status": "deleted"})
            except NotFound as err:
                self._send_error(404, str(err))

        def log_message(self, fmt, *args):
            pass

    return ApiHandler


def start_api_server(cluster: ClusterInterface, port: int,
                     host: str = "127.0.0.1",
                     health_provider=None) -> ThreadingHTTPServer:
    server = ThreadingHTTPServer(
        (host, port), make_handler(cluster, health_provider=health_provider))
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="tpujob-api-server")
    thread.start()
    return server
