"""Operator server: flags, metrics endpoint, leader election, controller run.

Re-architecture of the reference's process entry point
(/root/reference/cmd/tf-operator.v1/main.go:32-68 and app/server.go:71-187):
same operational surface — `--namespace`, `--threadiness`,
`--enable-gang-scheduling`, `--monitoring-port`, `--resync-period`,
`--json-log-format`, leader election with an is-leader gauge, /metrics +
/healthz HTTP — with the substrate behind ClusterInterface (local process
runtime by default here; a Kubernetes backend slots in unchanged).
"""
from __future__ import annotations

import argparse
import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .. import __version__
from ..controller.controller import TPUJobController
from ..controller.health import SelfHealingConfig
from ..runtime.shardlease import ShardLeaseConfig
from .probes import probe_response
from ..runtime.cluster import ClusterInterface, InMemoryCluster
from ..runtime.local import LocalProcessCluster
from ..runtime.reconciler import ReconcilerConfig
from ..utils import logging as tpulog
from ..utils import metrics

# Leader-election timing (ref: server.go:53-58).
LEASE_DURATION = 15.0
RENEW_PERIOD = 5.0
RETRY_PERIOD = 3.0
LEASE_NAME = "tpu-operator-leader"


class _DeprecatedResycPeriod(argparse.Action):
    """The reference's misspelled flag, kept as a hidden alias: stores into
    resync_period like the canonical flag, warns exactly once per parse."""

    def __call__(self, parser, namespace, values, option_string=None):
        tpulog.logger_for_key("server").warning(
            "%s is deprecated (the reference's typo, options.go:79); "
            "use --resync-period", option_string)
        setattr(namespace, self.dest, values)


def build_arg_parser() -> argparse.ArgumentParser:
    """(ref: ServerOption.AddFlags, app/options/options.go:53-83)"""
    parser = argparse.ArgumentParser("tpu-operator")
    parser.add_argument("--namespace", default="",
                        help="namespace to watch; empty = all namespaces")
    parser.add_argument("--threadiness", type=int, default=1)
    parser.add_argument("--version", action="version",
                        version=f"tpu-operator {__version__}")
    parser.add_argument("--json-log-format", action="store_true", default=True)
    parser.add_argument("--no-json-log-format", dest="json_log_format",
                        action="store_false")
    parser.add_argument("--enable-gang-scheduling", action="store_true")
    parser.add_argument("--gang-scheduler-name", default="tpu-gang")
    parser.add_argument("--gang-mechanism",
                        choices=("podgroup", "volcano", "pdb"),
                        default="podgroup",
                        help="podgroup: all-or-nothing slice admission by "
                        "the operator's in-process gang scheduler; volcano: "
                        "delegate admission to a cluster-installed Volcano "
                        "(schedulerName volcano + scheduling.k8s.io/"
                        "group-name, the reference's exact shapes); "
                        "pdb: default scheduler + disruption budget "
                        "(ref: SyncPodGroup vs SyncPdb)")
    parser.add_argument("--slice-chips", type=float, default=None,
                        help="total TPU chips the gang scheduler may admit "
                             "(default unlimited)")
    parser.add_argument("--slice-inventory", default=None,
                        help="slice fabric inventory as "
                             "accelerator:topology:count[,...] (e.g. "
                             "v5litepod-32:4x8:2); enables slice-shaped "
                             "all-or-nothing allocation")
    parser.add_argument("--monitoring-port", type=int, default=8443)
    parser.add_argument("--api-port", type=int, default=8008,
                        help="REST API port; 0 disables")
    parser.add_argument("--resync-period", type=float, default=15.0)
    # The reference's actual spelling is the typo'd --resyc-period
    # (options.go:79); accept it as a hidden deprecated alias so reference
    # Deployment args run unmodified, without advertising it in --help.
    # --resync-period is the canonical name; using the typo logs a
    # deprecation warning once per parse.
    parser.add_argument("--resyc-period", dest="resync_period", type=float,
                        action=_DeprecatedResycPeriod,
                        default=argparse.SUPPRESS, help=argparse.SUPPRESS)
    parser.add_argument("--enable-leader-election", action="store_true")
    parser.add_argument("--workdir", default=".tpujob-local",
                        help="local runtime workdir (logs, state)")
    parser.add_argument("--runtime", choices=("local", "memory", "k8s"),
                        default="local",
                        help="pod substrate: local processes, in-memory "
                             "(tests), or a Kubernetes apiserver")
    parser.add_argument("--kubeconfig", default=None,
                        help="kubeconfig path for --runtime k8s (default: "
                             "in-cluster service account, then $KUBECONFIG, "
                             "then ~/.kube/config — ref: server.go:94-99)")
    parser.add_argument("--master", default=None,
                        help="apiserver address override for --runtime k8s "
                             "(takes precedence over the kubeconfig host, "
                             "ref: options.go:44-47)")
    parser.add_argument("--qps", type=float, default=5.0,
                        help="maximum QPS to the apiserver from this client; "
                             "<=0 disables throttling (ref: options.go:81)")
    parser.add_argument("--burst", type=int, default=10,
                        help="maximum burst for throttle (ref: options.go:82)")
    # Self-healing knobs (docs/self-healing.md; no reference analogue — the
    # reference controller cannot observe its own failure modes at all).
    parser.add_argument("--quarantine-threshold", type=int, default=5,
                        help="consecutive sync failures before a job is "
                             "quarantined out of the hot queue")
    parser.add_argument("--quarantine-probation", type=float, default=60.0,
                        help="seconds a quarantined job waits between sync "
                             "probes (spec changes and resync ticks probe "
                             "earlier)")
    parser.add_argument("--stuck-sync-deadline", type=float, default=60.0,
                        help="seconds after which an in-flight sync is "
                             "reported stuck (flips /healthz to not-ready)")
    parser.add_argument("--watch-stale-deadline", type=float, default=300.0,
                        help="seconds without any watch event/heartbeat "
                             "before a watch stream is force-reconnected")
    # Control-plane scale knobs (docs/informer-cache.md; no reference
    # analogue — client-go gives the reference informers for free, and it
    # never shards its workqueue).
    parser.add_argument("--reconcile-shards", type=int, default=1,
                        help="independent reconcile shards (workqueue + "
                             "worker pool each, keys assigned by stable "
                             "hash); --threadiness is workers PER shard. "
                             "1 preserves the single-queue behavior exactly")
    parser.add_argument("--informer-relist-period", type=float, default=300.0,
                        help="seconds between informer store repair relists "
                             "(<=0 disables the periodic relist; watch "
                             "streams and stale-watch kicks still keep the "
                             "cache fresh)")
    parser.add_argument("--no-informer", dest="use_informer",
                        action="store_false", default=True,
                        help="disable the shared informer cache: every sync "
                             "reads the apiserver directly (pre-informer "
                             "behavior; for debugging and A/B only)")
    # Federated fleet (runtime/shardlease.py, docs/federation.md): N
    # controller replicas split the shard space via per-shard leases with
    # deterministic rebalancing; replica death hands its shards to
    # survivors within --shard-lease-duration.
    parser.add_argument("--replicas", type=int, default=1,
                        help="controller replicas to run IN THIS PROCESS, "
                             "federated via shard leases (memory/local "
                             "runtimes; on Kubernetes run one replica per "
                             "pod with --enable-shard-leases instead). "
                             ">1 implies shard leases")
    parser.add_argument("--enable-shard-leases", action="store_true",
                        help="participate in a cross-process fleet: sync "
                             "only the shards whose coordination.k8s.io "
                             "leases this replica holds (supersedes "
                             "--enable-leader-election's 1-owns-all model)")
    parser.add_argument("--shard-lease-duration", type=float, default=15.0,
                        help="seconds a shard/replica lease lives without "
                             "renewal; bounds crash-failover latency")
    parser.add_argument("--shard-lease-renew", type=float, default=5.0,
                        help="seconds between shard lease renew/rebalance "
                             "ticks (keep well under the duration)")
    parser.add_argument("--full-resync-every", type=int, default=4,
                        help="every Nth resync tick enqueues ALL jobs; the "
                             "ticks between skip jobs whose last sync was "
                             "a verified no-op (event-driven reconcile: "
                             "idle jobs cost zero CPU). 1 restores the "
                             "classic enqueue-everything tick")
    return parser


class MonitoringHandler(BaseHTTPRequestHandler):
    server_version = "tpu-operator"

    def do_GET(self):  # noqa: N802
        if self.path == "/metrics":
            body = metrics.REGISTRY.render().encode()
            ctype = "text/plain; version=0.0.4"
        elif self.path in ("/healthz", "/livez", "/readyz"):
            # Deep health (docs/self-healing.md): the controller's aggregated
            # live/ready report — workers, hung syncs, watch freshness, queue
            # pressure, quarantine, degraded episodes.  Status codes per the
            # k8s probe contract (see probes.probe_response, shared with the
            # REST API port).
            provider = getattr(self.server, "health_provider", None)
            code, report = probe_response(self.path, provider)
            body = json.dumps(report).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        elif self.path == "/debug/threads":
            # The pprof-parity endpoint (ref: main.go:21 net/http/pprof).
            # Loopback-only: the server binds all interfaces so the kubelet
            # can probe and Prometheus can scrape, but stack traces are a
            # debugging surface, not a pod-network one.
            if self.client_address[0] not in ("127.0.0.1", "::1"):
                self.send_response(403)
                self.end_headers()
                return
            import sys, traceback  # noqa: E401

            frames = sys._current_frames()
            lines = []
            for t in threading.enumerate():
                lines.append(f"--- {t.name} ({t.ident}) ---")
                frame = frames.get(t.ident)
                if frame:
                    lines.extend(traceback.format_stack(frame))
            body = "\n".join(lines).encode()
            ctype = "text/plain"
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request spam
        pass


def start_monitoring(port: int, host: str = "0.0.0.0",
                     health_provider=None) -> ThreadingHTTPServer:
    """(ref: startMonitoring, main.go:39-50).  `health_provider` is a
    zero-arg callable returning the aggregated health report
    (TPUJobController.health_report); /healthz falls back to a static ok
    without one.  Port 0 binds an ephemeral port (tests).  Binds all
    interfaces by default: the kubelet probes /healthz and /livez via the
    pod IP (manifests/deployment.yaml), which a loopback-only bind would
    refuse — turning the livenessProbe into a restart loop."""
    server = ThreadingHTTPServer((host, port), MonitoringHandler)
    server.health_provider = health_provider
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="tpujob-monitoring")
    thread.start()
    return server


def fleet_health_provider(controllers):
    """Aggregate /healthz across an in-process federated fleet
    (--replicas N, docs/federation.md): live/ready only when EVERY replica
    is — a wedged peer must flip the probe even though the primary is
    fine, or its shards go unsynced behind a green readiness gate.  Each
    replica's full report rides along under `replicas`, keyed by
    identity, with reasons prefixed so a 503 names the offender."""

    def provider() -> dict:
        reports = {c.identity: c.health_report() for c in controllers}
        live = all(r.get("live") for r in reports.values())
        ready = all(r.get("ready") for r in reports.values())
        reasons = [
            f"{identity}: {reason}"
            for identity, r in reports.items()
            for reason in r.get("reasons", ())
        ]
        return {
            # same legacy contract as the solo report: old SDK pollers
            # check status == "ok"
            "status": "ok" if ready else "not-ready",
            "live": live,
            "ready": ready,
            "reasons": reasons,
            "replicas": reports,
        }

    return provider


class LeaderElector:
    """Lease-based leader election (ref: leaderelection.RunOrDie,
    server.go:159-184).  Losing a held lease is fatal, matching the
    reference's restart-the-process recovery model."""

    def __init__(self, cluster: ClusterInterface, identity: str,
                 on_started_leading, on_lost_lease) -> None:
        self.cluster = cluster
        self.identity = identity
        self.on_started_leading = on_started_leading
        self.on_lost_lease = on_lost_lease
        self._stop = threading.Event()

    def run(self) -> None:
        leading = False
        while not self._stop.is_set():
            acquired = self.cluster.try_acquire_lease(
                LEASE_NAME, self.identity, LEASE_DURATION
            )
            if acquired and not leading:
                leading = True
                metrics.is_leader.labels().set(1)
                self.on_started_leading()
            elif not acquired and leading:
                metrics.is_leader.labels().set(0)
                self.on_lost_lease()
                return
            elif not acquired:
                metrics.is_leader.labels().set(0)
            self._stop.wait(RENEW_PERIOD if leading else RETRY_PERIOD)

    def stop(self) -> None:
        self._stop.set()


def startup_crd_check(cluster, log) -> None:
    """Fail fast before any controller machinery starts when the CRD isn't
    installed (ref: checkCRDExists, server.go:215-227).  Injected test
    clusters without the check (in-memory/local) skip it.  Only a
    confirmed-absent CRD is fatal: a transient apiserver hiccup or an RBAC
    403 here must not crash-loop the operator when the reference would
    start anyway (its checkCRDExists only treats IsNotFound as fatal) —
    the watch/relist machinery retries once running."""
    if not hasattr(cluster, "check_crd_exists"):
        return
    from ..runtime.k8s import CRDNotInstalledError

    try:
        cluster.check_crd_exists()
    except CRDNotInstalledError as e:
        log.error("CRD check failed: %s", e)
        raise SystemExit(str(e))
    except Exception as e:  # noqa: BLE001 — inconclusive, not absent
        log.warning(
            "CRD check inconclusive (%s); continuing startup — the "
            "controller's watch machinery will retry", e)


def run(argv=None, cluster: Optional[ClusterInterface] = None) -> TPUJobController:
    """Build everything and run the controller (blocking).  `cluster` may be
    injected for tests (ref: app.Run, server.go:71-187)."""
    args = build_arg_parser().parse_args(argv)
    tpulog.configure(json_format=args.json_log_format, level=logging.INFO)
    log = tpulog.logger_for_key("server")

    gang_in_process = (
        args.enable_gang_scheduling and args.gang_mechanism == "podgroup"
    )
    if cluster is None:
        if args.runtime == "k8s":
            from ..runtime.k8s import (
                PODGROUP_API,
                TPU_PODGROUP_API,
                KubeConfig,
                KubernetesCluster,
                default_config,
            )

            kube = (
                KubeConfig.from_kubeconfig(args.kubeconfig)
                if args.kubeconfig
                else None  # in-cluster / $KUBECONFIG resolution
            )
            if args.master:
                # --master overrides the kubeconfig/in-cluster host, like
                # clientcmd.BuildConfigFromFlags (ref: server.go:94-99)
                if kube is None:
                    try:
                        kube = default_config()
                    except FileNotFoundError:
                        # no kubeconfig anywhere: a bare-master setup
                        # (unauthenticated endpoint, e.g. a test fixture
                        # or kubectl proxy)
                        kube = KubeConfig(host=args.master)
                    # a PRESENT-but-broken kubeconfig still raises: the
                    # reference surfaces parse errors at startup rather
                    # than silently dropping the credentials it carries
                kube.host = args.master.rstrip("/")
            cluster = KubernetesCluster(
                kube, namespace=args.namespace or None,
                # In-process gang admission uses the operator's own PodGroup
                # CRD (manifests/podgroup.yaml); volcano/pdb modes keep the
                # Volcano group so a cluster-installed Volcano sees them.
                podgroup_api=(TPU_PODGROUP_API if gang_in_process
                              else PODGROUP_API),
                qps=args.qps, burst=args.burst,
            )
        elif args.runtime == "local":
            cluster = LocalProcessCluster(workdir=args.workdir)
        else:
            cluster = InMemoryCluster()

    startup_crd_check(cluster, log)

    config = ReconcilerConfig(
        reconciler_sync_loop_period=args.resync_period,
        enable_gang_scheduling=args.enable_gang_scheduling,
        gang_scheduler_name=args.gang_scheduler_name,
        gang_mechanism=args.gang_mechanism,
    )
    resolver_owner = cluster if hasattr(cluster, "resolver") else None
    healing = SelfHealingConfig(
        quarantine_threshold=args.quarantine_threshold,
        quarantine_probation=args.quarantine_probation,
        stuck_sync_deadline=args.stuck_sync_deadline,
        watch_stale_deadline=args.watch_stale_deadline,
        full_resync_every=args.full_resync_every,
    )

    # Federation (docs/federation.md): shard leases split the key space
    # across replicas — in this process (--replicas N) or across pods
    # (--enable-shard-leases, one replica per pod sharing the cluster's
    # lease store).
    replicas = max(1, args.replicas)
    shard_leases_on = replicas > 1 or args.enable_shard_leases
    if shard_leases_on and args.enable_leader_election:
        raise SystemExit(
            "--enable-leader-election (1-owns-all) and shard leases "
            "(--replicas > 1 / --enable-shard-leases) are mutually "
            "exclusive: shard leases ARE the generalized election — every "
            "replica leads its own shards"
        )
    if shard_leases_on and gang_in_process:
        raise SystemExit(
            "shard leases (--replicas > 1 / --enable-shard-leases) with "
            "--gang-mechanism podgroup would run one in-process gang "
            "scheduler per ACTIVE replica against shared slice capacity "
            "(every shard-lease replica is active, unlike leader-election "
            "standbys); run gang admission in one solo process or "
            "delegate it (--gang-mechanism volcano/pdb)"
        )

    def shard_lease_config():
        return (ShardLeaseConfig(
                    num_shards=args.reconcile_shards,
                    lease_duration=args.shard_lease_duration,
                    renew_period=args.shard_lease_renew)
                if shard_leases_on else None)

    import os as os_mod
    import socket as socket_mod

    base_identity = f"{socket_mod.gethostname()}-{os_mod.getpid()}"

    def build_controller(index: int) -> TPUJobController:
        return TPUJobController(
            cluster,
            config=config,
            threadiness=args.threadiness,
            healing=healing,
            shards=args.reconcile_shards,
            use_informer=args.use_informer,
            informer_relist_period=args.informer_relist_period,
            shard_lease=shard_lease_config(),
            identity=(base_identity if replicas == 1
                      else f"{base_identity}-r{index}"),
            **({"resolver": resolver_owner.resolver} if resolver_owner else {}),
        )

    controller = build_controller(0)
    # Peer replicas of the in-process fleet: started with the primary,
    # stopped with it.  Each owns its lease-assigned share of the shards.
    peers = [build_controller(i) for i in range(1, replicas)]
    if getattr(args, "slice_inventory", None) and not gang_in_process:
        raise SystemExit(
            "--slice-inventory requires --enable-gang-scheduling with "
            "--gang-mechanism podgroup (slice-shaped admission is enforced "
            "by the gang scheduler); the inventory would otherwise be ignored"
        )
    if args.slice_chips is not None and not gang_in_process:
        raise SystemExit(
            "--slice-chips requires --enable-gang-scheduling with "
            "--gang-mechanism podgroup (the chip-capacity cap is enforced "
            "by the in-process gang scheduler); with --gang-mechanism "
            "volcano or pdb the cap would be silently unenforced"
        )
    if gang_in_process:
        from ..runtime.scheduler import GangScheduler

        slice_provider = None
        if getattr(args, "slice_inventory", None):
            from ..runtime.slices import FakeSliceProvider, parse_topology

            inventory = {}
            for entry in args.slice_inventory.split(","):
                try:
                    accelerator, topology, count = entry.strip().split(":")
                    parse_topology(topology)
                    inventory[(accelerator, topology)] = int(count)
                except ValueError as exc:
                    raise SystemExit(
                        f"--slice-inventory: bad entry {entry.strip()!r} ({exc}); "
                        "expected accelerator:topology:count, e.g. v5litepod-32:4x8:2"
                    )
            slice_provider = FakeSliceProvider(inventory)
        controller.gang_scheduler = GangScheduler(
            cluster, total_chips=args.slice_chips,
            scheduler_name=args.gang_scheduler_name,
            slice_provider=slice_provider,
        )

    # SIGTERM/SIGINT: first one stops gracefully, second exits 1
    # (ref: vendor/.../util/signals/signal.go:25-42).
    if threading.current_thread() is threading.main_thread():
        import os
        import signal as signal_mod

        signal_count = {"n": 0}

        def _handle_signal(signum, frame):
            signal_count["n"] += 1
            if signal_count["n"] >= 2:
                os._exit(1)
            controller.stop()

        signal_mod.signal(signal_mod.SIGTERM, _handle_signal)
        signal_mod.signal(signal_mod.SIGINT, _handle_signal)

    # With leader election a replica may sit not-started waiting for the
    # lease; that standby is healthy by design and must report ready, or a
    # readinessProbe keeps the Deployment's rollout NotReady forever.
    if args.enable_leader_election:
        def health_provider() -> dict:
            return controller.health_report(standby_ok=True)
    elif peers:
        # In-process fleet: a probe must see EVERY replica, not just the
        # primary — a wedged peer's shards would otherwise go unsynced
        # behind a green readiness gate (docs/federation.md).
        health_provider = fleet_health_provider([controller, *peers])
    else:
        health_provider = controller.health_report
    monitoring = start_monitoring(args.monitoring_port,
                                  health_provider=health_provider)
    log.info("monitoring on 0.0.0.0:%d (/metrics /healthz /debug/threads)",
             args.monitoring_port)
    api = None
    if args.api_port:
        from .api_server import start_api_server

        api = start_api_server(cluster, args.api_port,
                               health_provider=health_provider)
        log.info("REST API on 127.0.0.1:%d", args.api_port)

    if args.enable_leader_election:
        import os
        import socket

        identity = f"{socket.gethostname()}-{os.getpid()}"
        fatal = {"lost": False}

        def on_lost():
            # (ref: server.go:179-182 — lease loss is fatal)
            log.error("leader election lost; exiting")
            fatal["lost"] = True
            controller.stop()

        elector = LeaderElector(cluster, identity, controller.start, on_lost)
        try:
            elector.run()
        except KeyboardInterrupt:
            pass
        finally:
            elector.stop()
            controller.stop()
            monitoring.shutdown()
            if api:
                api.shutdown()
        if fatal["lost"]:
            raise SystemExit(1)
    else:
        metrics.is_leader.labels().set(1)
        try:
            for peer in peers:
                peer.start()
            controller.run()
        except KeyboardInterrupt:
            pass
        finally:
            controller.stop()
            for peer in peers:
                peer.stop()
            monitoring.shutdown()
            if api:
                api.shutdown()
    return controller
