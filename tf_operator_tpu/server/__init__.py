"""Subpackage."""
