"""The k8s probe contract, shared by both HTTP surfaces.

The monitoring port (server.MonitoringHandler) and the REST API port
(api_server.make_handler) both expose /healthz, /livez, and /readyz; the
three paths serve the same aggregated health report
(TPUJobController.health_report, docs/self-healing.md) and differ only in
which verdict picks the status code.  One implementation here keeps the
two ports from diverging in probe behavior.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

from ..utils import logging as tpulog

log = tpulog.logger_for_key("health-probe")


def probe_response(path: str,
                   health_provider: Optional[Callable[[], dict]],
                   ) -> Tuple[int, dict]:
    """(status_code, report) for a probe request.

    /livez answers 503 only when not live — liveness probes belong here; a
    live-but-not-ready controller (leader-election standby, hung sync) must
    fail readiness, not get restarted.  /readyz and /healthz answer 503
    while not ready.  A provider-less server (no controller wired) is
    trivially ok, and a provider that *raises* is reported as a failed
    probe rather than killing the handler thread mid-response.
    """
    if health_provider is None:
        report: dict = {"status": "ok", "live": True, "ready": True}
    else:
        try:
            report = health_provider()
        except Exception as err:  # noqa: BLE001 — probe must answer, not die
            log.warning("health provider failed: %s", err)
            report = {"live": False, "ready": False,
                      "error": f"health provider failed: {err}"}
    verdict = (report.get("live") if path == "/livez"
               else report.get("ready"))
    return (200 if verdict else 503), report
