"""Build/version metadata (ref: pkg/version/version.go:21-43).

The reference stamps Version + GitSHA at link time via -ldflags; a pure-Python
package has no link step, so GitSHA is resolved lazily from the installed
tree's git metadata when available and falls back to "unknown" — the printed
shape (version, git sha, runtime) matches PrintVersionAndExit's output.
"""
from __future__ import annotations

import logging
import os
import platform
import subprocess
import sys

from tf_operator_tpu import __version__

VERSION = __version__


def git_sha() -> str:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5,
        )
        if out.returncode == 0:
            return out.stdout.strip()
    except Exception as err:
        logging.getLogger("tpu_operator").debug("git sha unavailable: %s", err)
    return "unknown"


def version_info() -> dict:
    return {
        "version": VERSION,
        "git_sha": git_sha(),
        "python": sys.version.split()[0],
        "platform": f"{platform.system().lower()}/{platform.machine()}",
    }


def version_string() -> str:
    info = version_info()
    return (
        f"tpu-operator {info['version']} (git {info['git_sha']}, "
        f"python {info['python']}, {info['platform']})"
    )
