"""Registered explorer scenarios for the standalone race soak.

`python -m tf_operator_tpu.analysis --race <name|all>` runs these under the
race-checked interleaving explorer (analysis/explore.py); CI's lint tier
sweeps them with a bounded schedule budget and records `race-findings.json`
(build/run_tests.py).  The deep scenario library lives in
`tests/test_schedule_explorer.py` — this registry holds the lean,
real-code, in-package scenarios the soak and CI can reach without
importing the test tree.

The elastic-resize scenario drives the PR 16 control-plane surfaces that
carry `@shared_state` / `track_access` instrumentation: two jobs resize
concurrently through the shared `CoalescingStatusWriter` and the
module-global virtual-replica gauge state, each cycling the declared
Resizing→RunningResized condition arc.  Every schedule is race-checked;
the post-schedule invariant pins wire-vs-memory consistency per key.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from ..api.core import ObjectMeta
from ..api.types import JobConditionType, JobStatus
from ..runtime import conditions, reconciler, statuswriter
from ..utils import locks
from . import explore


class _Job:
    """Minimal TPUJob stand-in: metadata + status + key(), nothing more —
    the writer and condition helpers only touch these."""

    def __init__(self, namespace: str, name: str) -> None:
        self.metadata = ObjectMeta(name=name, namespace=namespace)
        self.status = JobStatus()

    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"


class _SinkCluster:
    """Records every status PUT the writer sends, newest last per key."""

    def __init__(self) -> None:
        self._lock = locks.new_lock("race-sink")
        # key -> snapshots of every PUT status, in wire order
        self.puts: Dict[str, List[Tuple]] = {}  # guarded-by: _lock

    def update_job_status(self, namespace: str, name: str, status) -> None:
        snapshot = statuswriter.snapshot_status(status)
        with self._lock:
            self.puts.setdefault(f"{namespace}/{name}", []).append(snapshot)

    def last_put(self, key: str):
        with self._lock:
            entries = self.puts.get(key)
            return entries[-1] if entries else None

    def total_puts(self) -> int:
        with self._lock:
            return sum(len(v) for v in self.puts.values())


class _ElasticResizeState:
    def __init__(self) -> None:
        self.sink = _SinkCluster()
        self.writer = statuswriter.CoalescingStatusWriter(self.sink)
        self.jobs = [_Job("race", "elastic-a"), _Job("race", "elastic-b")]
        # The gauge lock is module-level, built at import time — OUTSIDE
        # the schedule's `locks.instrumented()` block — so it is a raw
        # lock the detector cannot see happens-before edges through.
        # Swap in an instrumented twin for the schedule (restored in
        # cleanup): the detector then verifies the real locking
        # discipline — drop the `with _virtual_replica_lock:` from
        # _publish_virtual_replicas and this scenario reports the race.
        self.original_gauge_lock = reconciler._virtual_replica_lock
        reconciler._virtual_replica_lock = locks.new_lock(
            "virtual-replica-gauge")


class ElasticResizeRaceScenario(explore.Scenario):
    """Two jobs resize concurrently through the shared writer + gauge
    state.  DIFFERENT keys per thread: the writer's per-key-exclusivity
    assumption (shard ownership keeps replicas off each other's keys,
    runtime/statuswriter.py) is part of the design being checked, not a
    restriction to dodge."""

    name = "elastic-resize"
    cycles = 2

    def build(self) -> _ElasticResizeState:
        return _ElasticResizeState()

    def _resize_cycles(self, state: _ElasticResizeState, job: _Job) -> None:
        key = job.key()
        for generation in range(self.cycles):
            old = statuswriter.snapshot_status(job.status)
            conditions.update_job_conditions(
                job.status, JobConditionType.RESIZING, "JobResizing",
                f"resize generation {generation}")
            reconciler._publish_virtual_replicas(key, 1, 1)
            explore.yield_point()
            state.writer.write_if_changed(job, old)
            explore.yield_point()
            old = statuswriter.snapshot_status(job.status)
            conditions.clear_condition(
                job.status, JobConditionType.RESIZING, "RunningResized",
                "resized gang running")
            reconciler._publish_virtual_replicas(key, 2, 0)
            explore.yield_point()
            state.writer.write_if_changed(job, old)

    def threads(self, state: _ElasticResizeState):
        return [
            (f"resize-{job.metadata.name}",
             lambda job=job: self._resize_cycles(state, job))
            for job in state.jobs
        ]

    def check(self, state: _ElasticResizeState) -> None:
        total = 0
        for job in state.jobs:
            key = job.key()
            wire = state.sink.last_put(key)
            if wire is None:
                raise explore.InvariantViolation(f"no PUT reached {key}")
            # The writer's memory of "what the wire holds" must match the
            # last PUT that actually went out — the invariant coalescing
            # rule 3 (stale-read echo suppression) stands on.
            with state.writer._lock:
                remembered = state.writer._last.get(key)
            if remembered != wire:
                raise explore.InvariantViolation(
                    f"writer memory for {key} diverged from the wire: "
                    f"remembered {remembered!r}, wire holds {wire!r}")
            if wire != statuswriter.snapshot_status(job.status):
                raise explore.InvariantViolation(
                    f"final status of {key} never reached the wire")
        for job in state.jobs:
            total += len(state.sink.puts.get(job.key(), ()))
        if state.writer.counters()["writes"] != total:
            raise explore.InvariantViolation(
                f"writer counted {state.writer.counters()['writes']} "
                f"writes, the wire saw {total}")

    def cleanup(self, state: _ElasticResizeState) -> None:
        # The gauge dict is module-global: drop this schedule's keys so
        # the next schedule (and the rest of the process) starts clean,
        # then put the original module lock back.
        for job in state.jobs:
            reconciler._publish_virtual_replicas(job.key(), None, 0)
        reconciler._virtual_replica_lock = state.original_gauge_lock


# name -> zero-arg scenario factory, the `--race` registry.
SCENARIOS = {
    ElasticResizeRaceScenario.name: ElasticResizeRaceScenario,
}
