"""AST-based concurrency lint for the control plane.

Zero-dependency static checker, run as:

    python -m tf_operator_tpu.analysis tf_operator_tpu

The control plane is a heavily threaded system (worker pools, resync loops,
watch supervisors, gang-retry sweeps, leader election); these rules
machine-check the concurrency discipline the code relies on:

  bare-lock       no `threading.Lock()` / `RLock()` / `Condition()` outside
                  the `utils/locks.py` factories — locks must be named (and
                  instrumentable) via `new_lock` / `new_rlock` /
                  `new_condition`.
  wall-clock      no `time.time` inside `runtime/`, `controller/` or
                  `server/` — timestamps go through `utils/clock.py`'s
                  `clock.now()` (fakeable in tests), durations through
                  `time.monotonic()`.
  swallow         every `except Exception` (or bare `except`) handler must
                  log or re-raise; silent swallows hide real failures.
  thread-hygiene  `threading.Thread(...)` must pass an explicit `name=`
                  (convention: `tpujob-<role>`) and `daemon=True`.
  guarded-by      an attribute declared with a trailing
                  `# guarded-by: <lockattr>` comment may only be mutated
                  while `with self.<lockattr>:` is held (the declaring
                  `__init__` is exempt).  Helpers annotated
                  `# requires-lock: <lockattr>` on (or directly above)
                  their `def` line count as holding the lock in their body,
                  and their `self.<helper>()` call sites are checked.
                  Module-level globals work the same with bare names.
  sleep-poll      (tests scope only) `time.sleep` inside a `while` loop
                  with no wall/monotonic-clock deadline comparison anywhere
                  in the loop — the unbounded-poll flaky-test smell
                  `tests/testutil.py:sync_until` exists to prevent.

Three architectural conformance rules check invariants of THIS control
plane rather than generic concurrency hygiene:

  statuswriter-bypass  every TPUJob status PUT must flow through
                       `CoalescingStatusWriter` (runtime/statuswriter.py) —
                       a direct `cluster.update_job_status(...)` anywhere
                       else silently breaks the coalescer's last-written
                       memory and the echo-suppression invariant.
  ownership-fence      in federated modules (anything referencing the
                       shard-lease manager), a work-queue enqueue or
                       worker pop must sit in a function that checks
                       `owns()` / `owns_key()` — an unfenced path processes
                       keys another replica owns.
  state-machine        condition transitions named in
                       `CONDITION_STATE_MACHINES` (one machine per
                       JobConditionType member — all seven are declared)
                       must use a declared reason (literal, module
                       constant, or a local assigned only literals); an
                       undeclared or unresolvable reason is an edge the
                       machine does not have.  The contract extractor
                       additionally reports a declared condition type that
                       is never set at any write site.

Three contract-drift rules are fed by the interface-manifest extractor
(`analysis/contract.py`, docs/static-analysis.md#contract-drift-rules),
which walks the package once and reconstructs the operator's contract
surface — wire dataclasses, TPUJOB_* env knobs, tpujob_* metrics,
condition write sites — into `interface-manifest.json` (CI diff-gates it
against the committed docs/interface-manifest.json):

  wire-roundtrip  a wire dataclass field serialized by `*_to_dict` but
                  never restored by `*_from_dict` (or vice versa, or
                  neither) — the round-trip drift class behind the old
                  `spec_entries` leak.
  knob-chain      a TPUJOB_* env knob produced (gen_tpu_env) with no
                  consumer, consumed but never produced, or declared dead.
  metric-doc      an emitted tpujob_* metric missing from
                  docs/monitoring.md, or a documented one never emitted.

Contract sites are exempted with `# contract: exempt(<rule>)` next to a
why-comment (intentionally one-directional fields, user-set env
overrides); `# lint: allow(<rule>)` also works at the reporting site.

Three further rules are interprocedural and package-wide, built from a
whole-program call graph + lock-acquisition graph (`analysis/lockgraph.py`):

  lock-order            cycle in the may-hold-while-acquiring graph (the
                        static deadlock precondition), reported with the
                        full witness path;
  guarded-by-interproc  a `# guarded-by:` field READ via a call chain on
                        which no caller holds the declared lock (writes
                        stay the per-file rule's job);
  atomicity             check-then-act: a guarded field read under one
                        `with <lock>:` and written under a different
                        acquisition of the same lock in the same function.

Suppression: `# lint: allow(<rule>)` on the statement's header line (the
line the statement starts on; for an `except` clause, the `except` line).
A `lock-order` cycle is suppressed when any of its edges' acquisition
sites carries the allow.

The checker is pure stdlib `ast` + source-line comment scanning, so it runs
in milliseconds with no pytest machinery — see `build/run_tests.py --tier
lint` and `tests/test_static_analysis.py` (which pins the package at zero
findings and pins each rule's firing behavior on known-bad fixtures).
"""
from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import contract, lockgraph
from .hlo import (
    HLO_RULES,
    RULE_HLO_MEMORY_INFEASIBLE,
    RULE_HLO_PLAN_DRIFT,
    RULE_HLO_REPLICATED_OPTSTATE,
    RULE_HLO_SYNC_COLLECTIVE,
)
from .lockgraph import (
    RULE_ATOMICITY,
    RULE_GUARDED_INTERPROC,
    RULE_LOCK_ORDER,
)

RULE_BARE_LOCK = "bare-lock"
RULE_WALL_CLOCK = "wall-clock"
RULE_SWALLOW = "swallow"
RULE_THREAD_HYGIENE = "thread-hygiene"
RULE_GUARDED_BY = "guarded-by"
RULE_SLEEP_POLL = "sleep-poll"
RULE_STATUSWRITER_BYPASS = "statuswriter-bypass"
RULE_OWNERSHIP_FENCE = "ownership-fence"
RULE_STATE_MACHINE = "state-machine"
RULE_WIRE_ROUNDTRIP = contract.RULE_WIRE
RULE_KNOB_CHAIN = contract.RULE_KNOB
RULE_METRIC_DOC = contract.RULE_METRIC
# not a style rule: an unparseable file cannot be checked, which must
# surface as a finding (exit 1), never as a traceback
RULE_PARSE_ERROR = "parse-error"
# Not in ALL_RULES: race findings come from the dynamic detector
# (analysis/racedetect.py via `--race`), never from the static pass, but
# they share the Finding/severity/rule_doc machinery.
RULE_RACE = "race"

ALL_RULES = (
    RULE_BARE_LOCK,
    RULE_WALL_CLOCK,
    RULE_SWALLOW,
    RULE_THREAD_HYGIENE,
    RULE_GUARDED_BY,
    RULE_SLEEP_POLL,
    RULE_STATUSWRITER_BYPASS,
    RULE_OWNERSHIP_FENCE,
    RULE_STATE_MACHINE,
    RULE_LOCK_ORDER,
    RULE_GUARDED_INTERPROC,
    RULE_ATOMICITY,
    RULE_WIRE_ROUNDTRIP,
    RULE_KNOB_CHAIN,
    RULE_METRIC_DOC,
    # compiled-program rules (analysis/hlo.py): fired by `--hlo`, never by
    # the per-file static pass — they need a lowered+compiled train step
    RULE_HLO_PLAN_DRIFT,
    RULE_HLO_REPLICATED_OPTSTATE,
    RULE_HLO_SYNC_COLLECTIVE,
    RULE_HLO_MEMORY_INFEASIBLE,
    RULE_PARSE_ERROR,
)

# Rules whose findings come out of the contract extractor's whole-tree
# pass (analysis/contract.py) rather than a per-file visitor.  The
# state-machine rule is both: per-file for write-site edges, contract-fed
# for never-set condition types.
CONTRACT_RULES = (
    RULE_WIRE_ROUNDTRIP,
    RULE_KNOB_CHAIN,
    RULE_METRIC_DOC,
    RULE_STATE_MACHINE,
)

# Schema version of the --json findings document (docs/static-analysis.md).
# v2 adds the top-level `schema` marker and per-finding severity/rule_doc;
# every v1 key is preserved unchanged, so v1 readers keep working.
FINDINGS_JSON_VERSION = 2
FINDINGS_JSON_SCHEMA = "tf-operator-tpu/lint-findings"

# Warnings are smells a human should triage; everything else (and any rule
# not listed) is an error — a correctness invariant the build gates on.
RULE_SEVERITY = {
    RULE_WALL_CLOCK: "warning",
    RULE_SWALLOW: "warning",
    RULE_THREAD_HYGIENE: "warning",
    RULE_SLEEP_POLL: "warning",
}


def rule_doc(rule: str) -> str:
    """URL-ish anchor into docs/static-analysis.md for a rule id.  The
    dynamic explorer kinds (`race`, `explore-*`) share one section, as do
    the compiled-program rules (`hlo-*`)."""
    if rule == RULE_RACE or rule.startswith("explore-"):
        return "docs/static-analysis.md#the-race-detector"
    if rule in HLO_RULES:
        return "docs/static-analysis.md#hlo-rules"
    return f"docs/static-analysis.md#{rule}"


# Declared condition state machines for the `state-machine` rule: condition
# type name -> the reasons allowed to set it true / flip it false.  Every
# JobConditionType member carries a machine (tests pin the coverage);
# SUCCEEDED and FAILED are terminal — an empty clear set means any
# clear-transition out of them is an undeclared edge.  Transitions on
# condition types outside this table (e.g. fixture-local enums) stay
# unconstrained.
CONDITION_STATE_MACHINES = {
    "CREATED": {
        "set": {"TPUJobCreated"},
        "clear": set(),
    },
    "RUNNING": {
        "set": {"TPUJobRunning"},
        "clear": set(),
    },
    "RESTARTING": {
        "set": {"JobRestarting"},
        "clear": set(),
    },
    "SUCCEEDED": {  # terminal
        "set": {"TPUJobSucceeded"},
        "clear": set(),
    },
    "FAILED": {  # terminal
        "set": {"TPUJobFailed", "FailedValidation",
                "BackoffLimitExceeded", "DeadlineExceeded",
                "MemoryInfeasible"},
        "clear": set(),
    },
    "STUCK": {
        "set": {"JobStuck"},
        "clear": {"SyncRecovered"},
    },
    "RESIZING": {
        "set": {"JobResizing"},
        "clear": {"RunningResized"},
    },
    "PREEMPTED": {
        "set": {"GangPreempted"},
        "clear": {"RunningAfterPreemption"},
    },
}

# Calls the state-machine rule inspects, mapped to the transition verb.
_CONDITION_CALLS = {
    "update_job_conditions": "set",
    "set_operational_condition": "set",
    "clear_condition": "clear",
}

# Subpackages (relative to the package root) where wall-clock reads are
# banned.  train/ and ops/ are workload-side (they run inside pods, where
# wall time is the point); utils/ hosts the clock seam itself.
WALL_CLOCK_SCOPES = ("runtime", "controller", "server")

# Primitive constructors the bare-lock rule owns.
_LOCK_CTORS = {"Lock": "new_lock", "RLock": "new_rlock",
               "Condition": "new_condition"}

# Methods on a guarded attribute's value that mutate it in place.
_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "reverse", "setdefault", "sort",
    "update",
}

# Handler calls that count as "logged it" for the swallow rule.
_LOG_METHODS = {
    "critical", "debug", "error", "exception", "info", "log", "log_message",
    "print_exc", "warn", "warning",
}

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z><A-Z_-]+)\)")
_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_REQUIRES_RE = re.compile(r"#\s*requires-lock:\s*([A-Za-z_][A-Za-z0-9_]*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # package-relative, forward slashes
    line: int
    message: str

    def render(self, prefix: str = "") -> str:
        where = f"{prefix}{self.path}" if prefix else self.path
        return f"{where}:{self.line}: [{self.rule}] {self.message}"


class _Comments:
    """Per-line comment annotations: suppressions + lock declarations."""

    def __init__(self, source: str) -> None:
        self.allow: Dict[int, Set[str]] = {}
        self.guarded: Dict[int, str] = {}
        self.requires: Dict[int, str] = {}
        for lineno, text in enumerate(source.splitlines(), start=1):
            if "#" not in text:
                continue
            for match in _ALLOW_RE.finditer(text):
                self.allow.setdefault(lineno, set()).add(match.group(1))
            match = _GUARDED_RE.search(text)
            if match:
                self.guarded[lineno] = match.group(1)
            match = _REQUIRES_RE.search(text)
            if match:
                self.requires[lineno] = match.group(1)

    def allows(self, lineno: int, rule: str) -> bool:
        return rule in self.allow.get(lineno, ())


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


class _FileChecker:
    def __init__(self, source: str, rel_path: str,
                 test_scope: Optional[bool] = None) -> None:
        self.rel_path = rel_path.replace(os.sep, "/")
        self.comments = _Comments(source)
        self.tree = ast.parse(source, filename=self.rel_path)
        self.findings: List[Finding] = []
        # any directory segment counts, so the rule stays armed when the
        # lint root is a parent of the package (vendored/src layouts:
        # "tf_operator_tpu/runtime/x.py" as well as "runtime/x.py")
        self.in_wall_clock_scope = any(
            part in WALL_CLOCK_SCOPES
            for part in self.rel_path.split("/")[:-1]
        )
        # sleep-poll scope: test code only (a `tests` dir segment or a
        # test_*.py file); the caller can force it when the lint root IS
        # the tests directory, where rel paths carry no `tests` segment
        if test_scope is None:
            parts = self.rel_path.split("/")
            test_scope = ("tests" in parts[:-1]
                          or parts[-1].startswith("test_"))
        self.in_test_scope = test_scope
        # line -> header line of the innermost statement covering it, so a
        # suppression on a multi-line statement's first line covers a
        # violating expression that starts on a continuation line
        self.stmt_header: Dict[int, int] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.stmt) or node.end_lineno is None:
                continue
            for line in range(node.lineno, node.end_lineno + 1):
                prev = self.stmt_header.get(line)
                if prev is None or node.lineno > prev:  # innermost wins
                    self.stmt_header[line] = node.lineno
        # line -> name of the innermost class whose body covers it
        # (statuswriter-bypass exempts CoalescingStatusWriter's own body).
        # ast.walk visits parents before nested classes, so the last
        # writer for a line is the innermost class.
        self.class_at_line: Dict[int, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef) and node.end_lineno is not None:
                for line in range(node.lineno, node.end_lineno + 1):
                    self.class_at_line[line] = node.name
        # state-machine reason resolution: module-level string constants
        # (JOB_STUCK_REASON et al.) plus the innermost function covering a
        # line, so contract.reason_candidates can resolve Name reasons
        # assigned only literals (same parents-before-children walk order
        # as class_at_line: the last writer is the innermost function).
        self.module_consts: Dict[str, str] = contract.module_string_consts(
            self.tree)
        self.func_at_line: Dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.end_lineno is not None):
                for line in range(node.lineno, node.end_lineno + 1):
                    self.func_at_line[line] = node
        # ownership-fence arms only in federated modules: anything that
        # talks about the shard-lease manager is expected to fence its
        # queue traffic; modules that predate federation stay untouched.
        self.in_federated_scope = any(
            (isinstance(node, ast.Attribute)
             and node.attr == "shard_manager")
            or (isinstance(node, ast.Name)
                and node.id in ("shard_manager", "ShardLeaseManager"))
            for node in ast.walk(self.tree)
        )
        # Alias tracking so `import threading as th` / `from time import
        # time` cannot evade the rules the literal spellings would trip.
        # names bound by `from threading import Lock, Thread, ...` -> the
        # original threading attr they denote
        self.threading_names: Dict[str, str] = {}
        # module aliases: names that denote the threading / time modules
        self.threading_modules: Set[str] = {"threading"}
        self.time_modules: Set[str] = {"time"}
        # names bound to the time.time function itself
        self.time_funcs: Set[str] = set()
        # names bound to time.sleep / time.monotonic-family functions
        # (sleep-poll rule raw material)
        self.sleep_funcs: Set[str] = set()
        self.clock_read_funcs: Set[str] = set()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "threading":
                        self.threading_modules.add(alias.asname or alias.name)
                    elif alias.name == "time":
                        self.time_modules.add(alias.asname or alias.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "threading":
                    for alias in node.names:
                        self.threading_names[alias.asname or alias.name] = (
                            alias.name
                        )
                elif node.module == "time":
                    for alias in node.names:
                        if alias.name == "time":
                            self.time_funcs.add(alias.asname or alias.name)
                        elif alias.name == "sleep":
                            self.sleep_funcs.add(alias.asname or alias.name)
                        elif alias.name in ("monotonic", "perf_counter"):
                            self.clock_read_funcs.add(
                                alias.asname or alias.name)

    # -- entry point ---------------------------------------------------

    def run(self) -> List[Finding]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                self._check_call(node)
            elif isinstance(node, (ast.Attribute, ast.Name)):
                self._check_wall_clock(node)
            elif isinstance(node, ast.ExceptHandler):
                self._check_swallow(node)
        self._check_timers()
        self._check_sleep_poll()
        self._check_ownership_fence()
        self._check_guarded_module(self.tree)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._check_guarded_class(node)
        self.findings.sort(key=lambda f: (f.line, f.rule))
        return self.findings

    def _report(self, rule: str, node: ast.AST, message: str) -> None:
        lineno = getattr(node, "lineno", 0)
        header = self.stmt_header.get(lineno, lineno)
        if (self.comments.allows(lineno, rule)
                or self.comments.allows(header, rule)):
            return
        self.findings.append(Finding(rule, self.rel_path, lineno, message))

    # -- bare-lock + thread-hygiene ------------------------------------

    def _threading_ctor(self, func: ast.AST) -> Optional[str]:
        """'Lock'/'RLock'/'Condition'/'Thread' when `func` names one from
        the threading module (by any import spelling), else None."""
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in self.threading_modules):
            return func.attr
        if isinstance(func, ast.Name) and func.id in self.threading_names:
            return self.threading_names[func.id]
        return None

    def _check_call(self, node: ast.Call) -> None:
        self._check_statuswriter_bypass(node)
        self._check_state_machine(node)
        ctor = self._threading_ctor(node.func)
        if ctor in _LOCK_CTORS:
            self._report(
                RULE_BARE_LOCK, node,
                f"bare threading.{ctor}(); use "
                f"utils.locks.{_LOCK_CTORS[ctor]}(name) so the lock is "
                "named and instrumentable",
            )
        elif ctor == "Thread":
            kwargs = {kw.arg for kw in node.keywords if kw.arg}
            missing = []
            if "name" not in kwargs:
                missing.append("an explicit name= (convention: "
                               "\"tpujob-<role>\")")
            daemon = next(
                (kw.value for kw in node.keywords if kw.arg == "daemon"), None
            )
            if not (isinstance(daemon, ast.Constant) and daemon.value is True):
                missing.append("daemon=True")
            if missing:
                self._report(
                    RULE_THREAD_HYGIENE, node,
                    "threading.Thread(...) missing " + " and ".join(missing),
                )

    @staticmethod
    def _scope_walk(scope: ast.AST):
        """All nodes of `scope` excluding nested function/class scopes."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_timers(self) -> None:
        """threading.Timer is a Thread subclass whose constructor takes no
        name=/daemon=; require the post-construction assignments instead
        (`t.name = "tpujob-<role>"; t.daemon = True` in the same scope)."""
        scopes = [self.tree] + [
            n for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            timers: Dict[str, ast.Call] = {}   # var -> constructing call
            assigned_calls: Set[int] = set()
            named: Set[str] = set()
            daemoned: Set[str] = set()
            calls: List[ast.Call] = []
            for node in self._scope_walk(scope):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    value = node.value
                    if (isinstance(value, ast.Call)
                            and self._threading_ctor(value.func) == "Timer"):
                        for target in targets:
                            if isinstance(target, ast.Name):
                                timers[target.id] = value
                                assigned_calls.add(id(value))
                    for target in targets:
                        if (isinstance(target, ast.Attribute)
                                and isinstance(target.value, ast.Name)):
                            if target.attr == "name":
                                named.add(target.value.id)
                            elif (target.attr == "daemon"
                                  and isinstance(value, ast.Constant)
                                  and value.value is True):
                                daemoned.add(target.value.id)
                elif isinstance(node, ast.Call):
                    calls.append(node)
            for var, call in timers.items():
                missing = []
                if var not in named:
                    missing.append(f'{var}.name = "tpujob-<role>"')
                if var not in daemoned:
                    missing.append(f"{var}.daemon = True")
                if missing:
                    self._report(
                        RULE_THREAD_HYGIENE, call,
                        "threading.Timer(...) without " + " and ".join(missing)
                        + " in the same scope",
                    )
            for call in calls:
                if (self._threading_ctor(call.func) == "Timer"
                        and id(call) not in assigned_calls):
                    self._report(
                        RULE_THREAD_HYGIENE, call,
                        "threading.Timer(...) not bound to a variable; it "
                        "cannot be named (t.name = \"tpujob-<role>\") or "
                        "made a daemon",
                    )

    # -- architectural conformance -------------------------------------

    @staticmethod
    def _call_arg(node: ast.Call, index: int,
                  kwname: str) -> Optional[ast.AST]:
        """Positional arg `index` or keyword `kwname`, whichever the call
        spelled; None when absent."""
        for kw in node.keywords:
            if kw.arg == kwname:
                return kw.value
        if len(node.args) > index:
            return node.args[index]
        return None

    def _check_statuswriter_bypass(self, node: ast.Call) -> None:
        """A status PUT (`<cluster>.update_job_status(...)`) anywhere but
        inside CoalescingStatusWriter bypasses the coalescer: the writer's
        last-written memory goes stale and echo suppression starts eating
        real transitions.  Route through `status_writer.write(...)` /
        `write_if_changed(...)` instead."""
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr == "update_job_status"):
            return
        receiver = func.value
        is_cluster = (
            (isinstance(receiver, ast.Name) and receiver.id == "cluster")
            or (isinstance(receiver, ast.Attribute)
                and receiver.attr == "cluster")
        )
        if not is_cluster:
            # plugin/backends named otherwise (status_engine, the cluster
            # implementations themselves) are different layers, not PUTs
            # sneaking around the writer
            return
        if self.class_at_line.get(node.lineno) == "CoalescingStatusWriter":
            return  # the sanctioned path's own body
        self._report(
            RULE_STATUSWRITER_BYPASS, node,
            "status PUT bypasses CoalescingStatusWriter; route it through "
            "status_writer.write()/write_if_changed() so coalescing and "
            "echo suppression stay correct (runtime/statuswriter.py)",
        )

    def _mentions_work_queue(self, expr: ast.AST) -> bool:
        return any(
            (isinstance(n, ast.Attribute) and n.attr == "work_queue")
            or (isinstance(n, ast.Name) and n.id == "work_queue")
            for n in ast.walk(expr)
        )

    def _check_ownership_fence(self) -> None:
        """In federated modules, every function that enqueues to or pops
        from the work queue must check shard ownership (`owns()` /
        `owns_key()`) somewhere in its body — an unfenced enqueue admits
        keys another replica owns, an unfenced pop processes them."""
        if not self.in_federated_scope:
            return
        funcs = [n for n in ast.walk(self.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            body = list(self._scope_walk(fn))
            fenced = any(
                isinstance(n, ast.Call) and (
                    (isinstance(n.func, ast.Attribute)
                     and n.func.attr in ("owns", "owns_key"))
                    or (isinstance(n.func, ast.Name)
                        and n.func.id in ("owns", "owns_key"))
                )
                for n in body
            )
            if fenced:
                continue
            # vars bound from a work-queue call (`shard_queue =
            # self.work_queue.shard(i)`) carry the taint: popping THEM is
            # popping the queue
            queue_vars: Set[str] = set()
            for n in body:
                if (isinstance(n, ast.Assign)
                        and isinstance(n.value, ast.Call)
                        and self._mentions_work_queue(n.value.func)):
                    queue_vars.update(
                        t.id for t in n.targets if isinstance(t, ast.Name))
            for n in body:
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in ("add", "get")):
                    continue
                receiver = n.func.value
                if (self._mentions_work_queue(receiver)
                        or (isinstance(receiver, ast.Name)
                            and receiver.id in queue_vars)):
                    self._report(
                        RULE_OWNERSHIP_FENCE, n,
                        f"work-queue .{n.func.attr}() in federated code "
                        f"with no owns()/owns_key() check in "
                        f"{fn.name}(); an unfenced path touches keys "
                        "another replica owns — gate it (e.g. via "
                        "_enqueue) or fence the function",
                    )

    def _check_state_machine(self, node: ast.Call) -> None:
        """Condition transitions on a declared machine must use a declared
        reason: the edge set in CONDITION_STATE_MACHINES is the spec, and
        a novel (or unresolvable) reason is an edge the machine does not
        have.  Reasons resolve through contract.reason_candidates —
        literals, module string constants, and locals assigned only
        literals all check; anything else is uncheckable and reports."""
        func = node.func
        name = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None)
        verb = _CONDITION_CALLS.get(name or "")
        if verb is None:
            return
        ctype = self._call_arg(node, 1, "ctype")
        key = (ctype.attr if isinstance(ctype, ast.Attribute)
               else ctype.id if isinstance(ctype, ast.Name) else None)
        machine = CONDITION_STATE_MACHINES.get(key or "")
        if machine is None:
            return
        allowed = machine[verb]
        reason = self._call_arg(node, 2, "reason")
        candidates = contract.reason_candidates(
            reason, self.module_consts, self.func_at_line.get(node.lineno))
        if candidates is None:
            detail = "a non-literal reason (the edge set is uncheckable)"
        else:
            bad = sorted(set(candidates) - allowed)
            if not bad:
                return
            detail = f"undeclared reason {bad[0]!r}"
        self._report(
            RULE_STATE_MACHINE, node,
            f"{key} {verb} transition with {detail}; declared edges for "
            f"{verb} are {sorted(allowed)} (CONDITION_STATE_MACHINES in "
            "tf_operator_tpu/analysis/__init__.py)",
        )

    # -- sleep-poll ----------------------------------------------------

    def _is_sleep_call(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "sleep"
                and isinstance(func.value, ast.Name)
                and func.value.id in self.time_modules):
            return True
        return isinstance(func, ast.Name) and func.id in self.sleep_funcs

    def _is_clock_read(self, node: ast.AST) -> bool:
        """A wall/monotonic clock read: time.time()/monotonic()/
        perf_counter(), clock.now(), or a from-imported alias of one."""
        if not isinstance(node, ast.Call):
            return False
        func = node.func
        if isinstance(func, ast.Attribute):
            if (func.attr in ("time", "monotonic", "perf_counter")
                    and isinstance(func.value, ast.Name)
                    and func.value.id in self.time_modules):
                return True
            if func.attr == "now":  # clock.now() / <fake>.now()
                return True
        return (isinstance(func, ast.Name)
                and (func.id in self.time_funcs
                     or func.id in self.clock_read_funcs))

    def _check_sleep_poll(self) -> None:
        """`time.sleep` in a `while` loop whose subtree never compares a
        clock read — an unbounded poll that hangs forever instead of
        failing with a diagnosable timeout.  Test scope only (the control
        plane has no business sleeping in loops at all; its loops block on
        Events/Conditions, and the thread rules keep them visible)."""
        if not self.in_test_scope:
            return
        reported: Set[int] = set()  # sleep-call node ids (nested loops
        # both match the same sleep; one finding per sleep, not per loop)
        for loop in ast.walk(self.tree):
            if not isinstance(loop, ast.While):
                continue
            # _scope_walk, not ast.walk: a sleep inside a function/lambda
            # DEFINED in the loop body does not run in the loop, and a
            # clock compare hidden in one bounds nothing — both would
            # mislead the full-subtree scan
            sleeps = [n for n in self._scope_walk(loop)
                      if self._is_sleep_call(n) and id(n) not in reported]
            if not sleeps:
                continue
            deadline_checked = any(
                isinstance(n, ast.Compare)
                and any(self._is_clock_read(sub)
                        for sub in ast.walk(n))
                for n in self._scope_walk(loop)
            )
            if not deadline_checked:
                reported.update(id(n) for n in sleeps)
                self._report(
                    RULE_SLEEP_POLL, sleeps[0],
                    "time.sleep in a while loop with no deadline check; "
                    "poll against a clock deadline (or use "
                    "tests/testutil.py sync_until) so a hang fails fast "
                    "with a diagnosable timeout",
                )

    # -- wall-clock ----------------------------------------------------

    def _check_wall_clock(self, node: ast.AST) -> None:
        if not self.in_wall_clock_scope:
            return
        hit = (
            isinstance(node, ast.Attribute)
            and node.attr == "time"
            and isinstance(node.value, ast.Name)
            and node.value.id in self.time_modules
        ) or (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in self.time_funcs
        )
        if hit:
            self._report(
                RULE_WALL_CLOCK, node,
                "time.time in control-plane code; use utils.clock.now() "
                "for timestamps or time.monotonic() for durations",
            )

    # -- swallow -------------------------------------------------------

    @staticmethod
    def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
        if handler.type is None:  # bare `except:` — broader still
            return True
        types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
                 else [handler.type])
        return any(
            isinstance(t, ast.Name) and t.id in ("Exception", "BaseException")
            for t in types
        )

    def _check_swallow(self, handler: ast.ExceptHandler) -> None:
        if not self._is_broad_handler(handler):
            return
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _LOG_METHODS):
                return
        self._report(
            RULE_SWALLOW, handler,
            "broad except handler neither logs nor re-raises; silent "
            "swallows hide real failures (log at debug or add "
            "`# lint: allow(swallow)` with a justification)",
        )

    # -- guarded-by ----------------------------------------------------

    def _check_guarded_class(self, cls: ast.ClassDef) -> None:
        guarded: Dict[str, str] = {}   # attr -> lock attr
        requires: Dict[str, str] = {}  # method name -> lock attr
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for method in methods:
            lock = (self.comments.requires.get(method.lineno)
                    or self.comments.requires.get(method.lineno - 1))
            if lock:
                requires[method.name] = lock
            for node in ast.walk(method):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    lock = self.comments.guarded.get(node.lineno)
                    if not lock:
                        continue
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for target in targets:
                        if _is_self_attr(target):
                            guarded[target.attr] = lock
        if not guarded and not requires:
            return
        for method in methods:
            held: Set[str] = set()
            if method.name in requires:
                held = {requires[method.name]}
            self._walk_guarded(
                method, held, guarded, requires,
                exempt=(method.name == "__init__"),
                owner=f"{cls.name}.{method.name}",
            )

    def _check_guarded_module(self, tree: ast.Module) -> None:
        """Module-level globals declared `name = ...  # guarded-by: lock`."""
        guarded: Dict[str, str] = {}
        declared_at: Dict[str, int] = {}
        for node in tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                lock = self.comments.guarded.get(node.lineno)
                if not lock:
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for target in targets:
                    if isinstance(target, ast.Name):
                        guarded[target.id] = lock
                        declared_at[target.id] = node.lineno
        if guarded:
            self._walk_module_guarded(tree, set(), guarded, declared_at)

    def _with_locks(self, node: ast.With) -> Set[str]:
        """Lock names taken by a `with` statement: `self.<attr>` and bare
        `Name` context expressions."""
        held = set()
        for item in node.items:
            expr = item.context_expr
            if _is_self_attr(expr):
                held.add(expr.attr)
            elif isinstance(expr, ast.Name):
                held.add(expr.id)
        return held

    def _walk_guarded(self, node: ast.AST, held: Set[str],
                      guarded: Dict[str, str], requires: Dict[str, str],
                      exempt: bool, owner: str) -> None:
        for child in ast.iter_child_nodes(node):
            child_held = held
            if isinstance(child, ast.With):
                child_held = held | self._with_locks(child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda)):
                # A nested function body runs at some later time — locks
                # held at definition prove nothing, and a closure defined
                # in __init__ outlives __init__'s single-threaded grace
                # period.  Checked with an empty held set and NO __init__
                # exemption (suppress intentional cases).
                self._walk_guarded(child, set(), guarded, requires,
                                   exempt=False, owner=owner)
                continue
            if not exempt:
                self._check_guarded_stmt(child, child_held, guarded, requires)
            self._walk_guarded(child, child_held, guarded, requires,
                               exempt, owner)

    def _check_guarded_stmt(self, node: ast.AST, held: Set[str],
                            guarded: Dict[str, str],
                            requires: Dict[str, str]) -> None:
        def flag(attr: str, via: ast.AST) -> None:
            lock = guarded[attr]
            if lock in held:
                return
            self._report(
                RULE_GUARDED_BY, via,
                f"self.{attr} (guarded-by {lock}) mutated outside "
                f"`with self.{lock}:`",
            )

        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                attr = self._guarded_target_attr(target, guarded)
                if attr is not None:
                    flag(attr, node)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                attr = self._guarded_target_attr(target, guarded)
                if attr is not None:
                    flag(attr, node)
        elif isinstance(node, ast.Call):
            func = node.func
            if not isinstance(func, ast.Attribute):
                return
            # self.<attr>.<mutator>(...) on a guarded attribute
            if (func.attr in _MUTATORS and _is_self_attr(func.value)
                    and func.value.attr in guarded):
                flag(func.value.attr, node)
            # self.<helper>() where helper is `# requires-lock:` annotated
            elif (func.attr in requires
                  and isinstance(func.value, ast.Name)
                  and func.value.id == "self"
                  and requires[func.attr] not in held):
                lock = requires[func.attr]
                self._report(
                    RULE_GUARDED_BY, node,
                    f"call to self.{func.attr}() (requires-lock {lock}) "
                    f"outside `with self.{lock}:`",
                )

    @staticmethod
    def _guarded_target_attr(target: ast.AST,
                             guarded: Dict[str, str]) -> Optional[str]:
        """Guarded attr name when `target` writes self.<attr> or
        self.<attr>[...]; else None."""
        if isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute) and _is_self_attr(target):
            if target.attr in guarded:
                return target.attr
        return None

    def _walk_module_guarded(self, node: ast.AST, held: Set[str],
                             guarded: Dict[str, str],
                             declared_at: Dict[str, int]) -> None:
        def flag(name: str, via: ast.AST) -> None:
            self._report(
                RULE_GUARDED_BY, via,
                f"module global {name} (guarded-by {guarded[name]}) "
                f"mutated outside `with {guarded[name]}:`",
            )

        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                # class bodies bind class attributes, and methods use the
                # self-attr rule — bare names there are not module globals
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # Inside a function: a bare-name ASSIGNMENT mutates the
                # global only under `global`; without it the name becomes a
                # local for the whole function, so in-place mutator calls
                # on such a name target the local too.  Names never bound
                # locally stay checkable for in-place mutation (no `global`
                # needed for `_pending.append(v)`).  Locks held at the
                # definition site prove nothing at call time.
                declared_global = {
                    name
                    for g in ast.walk(child) if isinstance(g, ast.Global)
                    for name in g.names
                }
                locally_bound = {
                    target.id
                    for n in ast.walk(child)
                    if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign))
                    for target in (n.targets if isinstance(n, ast.Assign)
                                   else [n.target])
                    if isinstance(target, ast.Name)
                } - declared_global
                scoped = {k: v for k, v in guarded.items()
                          if k in declared_global or k not in locally_bound}
                if scoped:
                    self._walk_module_guarded(child, set(), scoped,
                                              declared_at)
                continue
            child_held = held
            if isinstance(child, ast.With):
                child_held = held | self._with_locks(child)
            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (child.targets if isinstance(child, ast.Assign)
                           else [child.target])
                for target in targets:
                    name = None
                    if isinstance(target, ast.Name):
                        if declared_at.get(target.id) == child.lineno:
                            continue  # the declaring assignment itself
                        name = target.id
                    elif (isinstance(target, ast.Subscript)
                          and isinstance(target.value, ast.Name)):
                        name = target.value.id
                    if (name in guarded
                            and guarded[name] not in child_held):
                        flag(name, child)
            elif isinstance(child, ast.Call):
                func = child.func
                if (isinstance(func, ast.Attribute)
                        and func.attr in _MUTATORS
                        and isinstance(func.value, ast.Name)
                        and func.value.id in guarded
                        and guarded[func.value.id] not in child_held):
                    flag(func.value.id, child)
            self._walk_module_guarded(child, child_held, guarded, declared_at)


def _suppressed(checker: _FileChecker, line: int, rule: str) -> bool:
    header = checker.stmt_header.get(line, line)
    return (checker.comments.allows(line, rule)
            or checker.comments.allows(header, rule))


def _project_findings(checkers: List[_FileChecker]) -> List[Finding]:
    """The interprocedural pass (lock-order / guarded-by-interproc /
    atomicity) over every successfully parsed file, with the same
    header-line suppression semantics as the per-file rules."""
    by_path = {c.rel_path: c for c in checkers}
    project = lockgraph.build_project(
        [(c.rel_path, c.tree, c.comments) for c in checkers])
    findings: List[Finding] = []

    def lock_order_edge_allowed(path: str, line: int) -> bool:
        checker = by_path.get(path)
        return (checker is not None
                and _suppressed(checker, line, RULE_LOCK_ORDER))

    # suppressed edges are removed BEFORE cycle detection: an allow breaks
    # exactly the cycles through that edge, and any OTHER cycle in the
    # same component still reports
    for cycle in project.lock_order_cycles(lock_order_edge_allowed):
        hops = " -> ".join(
            f"{a} ({path}:{line} {detail})"
            for a, _b, path, line, detail in cycle)
        first = cycle[0]
        findings.append(Finding(
            RULE_LOCK_ORDER, first[2], first[3],
            f"potential deadlock: lock acquisition cycle {hops} -> "
            f"{first[0]}; impose one global order (or break an edge and "
            "suppress it with a justification)",
        ))

    for cls, fn, access, lock, chain in project.unguarded_reads():
        checker = by_path.get(cls.path)
        if checker is not None and _suppressed(checker, access.line,
                                               RULE_GUARDED_INTERPROC):
            continue
        via = " -> ".join(f"{cls.name}.{m}" for m in chain)
        findings.append(Finding(
            RULE_GUARDED_INTERPROC, cls.path, access.line,
            f"self.{access.attr} (guarded-by {lock}) read without the lock"
            f" — reachable lock-free via {via}; hold `with self.{lock}:` "
            "for the read or annotate the chain with `# requires-lock: "
            f"{lock}`",
        ))

    for cls, fn, read, write, lock in project.check_then_act():
        checker = by_path.get(cls.path)
        if checker is not None and (
                _suppressed(checker, write.line, RULE_ATOMICITY)
                or _suppressed(checker, read.line, RULE_ATOMICITY)):
            continue
        findings.append(Finding(
            RULE_ATOMICITY, cls.path, write.line,
            f"check-then-act on self.{write.attr} (guarded-by {lock}): "
            f"read under `with self.{lock}:` at line {read.line}, lock "
            f"released, then written under a new acquisition in "
            f"{cls.name}.{fn.name}; merge into one critical section or "
            "re-validate the read",
        ))
    return findings


def _check_many(files: Sequence[Tuple[str, str]],
                test_scope: Optional[bool] = None,
                rules: Optional[Iterable[str]] = None,
                contract_doc: Optional[Tuple[str, str]] = None) -> List[Finding]:
    """Per-file rules + the interprocedural pass over `(rel_path, source)`
    pairs; unparseable files surface as parse-error findings and drop out
    of the project model.  When a `rules` subset is given that names no
    interprocedural rule, the whole-program pass is skipped entirely —
    the CI tests-tree sleep-poll pass must not pay for a call-graph
    fixpoint whose findings it would discard.  The contract-drift pass
    (CONTRACT_RULES, analysis/contract.py) is gated the same way;
    `contract_doc` is the optional (display_path, text) of
    docs/monitoring.md for the metric-doc rule."""
    findings: List[Finding] = []
    checkers: List[_FileChecker] = []
    contract_files: List[Tuple[str, str, ast.AST]] = []
    for rel_path, source in files:
        try:
            checker = _FileChecker(source, rel_path, test_scope=test_scope)
        except SyntaxError as err:
            findings.append(Finding(
                RULE_PARSE_ERROR, rel_path.replace(os.sep, "/"),
                err.lineno or 0, f"cannot parse module: {err.msg}",
            ))
            continue
        findings.extend(checker.run())
        checkers.append(checker)
        contract_files.append((checker.rel_path, source, checker.tree))
    wanted = None if rules is None else set(rules)
    if wanted is None or wanted & set(lockgraph.LOCKGRAPH_RULES):
        findings.extend(_project_findings(checkers))
    if wanted is None or wanted & set(CONTRACT_RULES):
        by_path = {c.rel_path: c for c in checkers}
        built = contract.build_contract(contract_files, doc=contract_doc)
        for rule, path, line, message in contract.contract_findings(built):
            checker = by_path.get(path)
            # `# lint: allow(...)` works on contract findings too; the
            # extractor's own `# contract: exempt(...)` was applied inside
            # contract_findings.  Doc-side findings have no checker.
            if checker is not None and _suppressed(checker, line, rule):
                continue
            findings.append(Finding(rule, path, line, message))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def check_source(source: str, rel_path: str,
                 test_scope: Optional[bool] = None) -> List[Finding]:
    """Lint one module's source.  `rel_path` is the path relative to the
    package root (it decides wall-clock scoping, e.g. "runtime/x.py", and
    sleep-poll's tests scope).  The interprocedural rules run over the
    single-file project.  An unparseable module yields a single
    `parse-error` finding."""
    return _check_many([(rel_path, source)], test_scope=test_scope)


def check_file(path: str, rel_path: Optional[str] = None,
               test_scope: Optional[bool] = None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    return check_source(source, rel_path or os.path.basename(path),
                        test_scope=test_scope)


def _package_files(root: str,
                   exclude_dirs: Iterable[str] = ()) -> List[Tuple[str, str]]:
    """Sorted (rel_path, source) pairs for every .py under `root`, with
    `exclude_dirs` (and __pycache__) pruned."""
    skip = {"__pycache__", *exclude_dirs}
    files: List[Tuple[str, str]] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in skip)
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            with open(path, encoding="utf-8") as f:
                files.append((os.path.relpath(path, root)
                              .replace(os.sep, "/"), f.read()))
    return files


def _monitoring_doc(root: str) -> Optional[Tuple[str, str]]:
    """(display_path, text) of docs/monitoring.md next to the package
    root, or None — the metric-doc rule's reference surface."""
    doc_path = os.path.join(os.path.dirname(os.path.abspath(root)),
                            "docs", "monitoring.md")
    if not os.path.exists(doc_path):
        return None
    with open(doc_path, encoding="utf-8") as f:
        return "../docs/monitoring.md", f.read()


def check_package(root: str,
                  exclude_dirs: Iterable[str] = (),
                  rules: Optional[Iterable[str]] = None) -> List[Finding]:
    """Lint every .py under the package directory `root` (per-file rules
    file by file, interprocedural + contract rules over the whole tree).
    Directory names in `exclude_dirs` are pruned (e.g. known-bad fixture
    dirs); `rules` (when given) lets _check_many skip the whole-program
    passes if no interprocedural/contract rule is requested — the caller
    still post-filters the per-file findings."""
    files = _package_files(root, exclude_dirs)
    # when the lint root IS a tests tree, rel paths carry no `tests`
    # segment — force the scope so sleep-poll still arms; the monitoring
    # doc belongs to the package surface only, never to a tests tree
    root_is_tests = os.path.basename(os.path.abspath(root)) == "tests"
    return _check_many(files, test_scope=True if root_is_tests else None,
                       rules=rules,
                       contract_doc=None if root_is_tests
                       else _monitoring_doc(root))


def package_contract(root: str,
                     exclude_dirs: Iterable[str] = ()) -> contract.Contract:
    """The extracted contract surface of a package directory — what
    `--manifest` serializes and tests introspect (analysis/contract.py)."""
    return contract.build_contract(_package_files(root, exclude_dirs),
                                   doc=_monitoring_doc(root))


def write_findings_json(path: str, findings: List[Finding],
                        target: str) -> None:
    """Machine-readable findings document, schema v2: top-level {version,
    schema, target, count, findings[]}, per-finding {rule, path, line,
    message, severity, rule_doc} — docs/static-analysis.md.  Strictly
    additive over v1 (same keys, new ones alongside), so v1 readers that
    index version/target/count/findings keep working unchanged."""
    doc = {
        "version": FINDINGS_JSON_VERSION,
        "schema": FINDINGS_JSON_SCHEMA,
        "target": target,
        "count": len(findings),
        "findings": [
            {"rule": f.rule, "path": f.path, "line": f.line,
             "message": f.message,
             "severity": RULE_SEVERITY.get(f.rule, "error"),
             "rule_doc": rule_doc(f.rule)}
            for f in findings
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def resolve_package_dir(spec: str) -> Tuple[str, str]:
    """(directory, display-prefix) for a path or an importable package."""
    if os.path.isdir(spec):
        return spec, spec.rstrip("/\\") + "/"
    import importlib.util

    found = importlib.util.find_spec(spec)
    if found is None or not found.submodule_search_locations:
        raise SystemExit(f"cannot resolve package or directory: {spec!r}")
    root = list(found.submodule_search_locations)[0]
    return root, spec.replace(".", "/") + "/"


def race_findings(names: Sequence[str], schedules: int,
                  seed: int = 0) -> List[Finding]:
    """Run the registered scenarios race-checked for `schedules` seeded
    schedules each; every failing schedule (race or otherwise) becomes a
    Finding whose message carries the full seed/decision-trace artifact."""
    from . import explore, scenarios

    findings: List[Finding] = []
    for name in names:
        scenario = scenarios.SCENARIOS[name]()
        result = explore.explore(scenario, schedules=schedules, seed=seed)
        failure = result.failure
        if failure is None:
            continue
        rule = (RULE_RACE if failure.kind == explore.FAIL_RACE
                else f"explore-{failure.kind}")
        findings.append(Finding(
            rule=rule, path=f"scenario:{name}",
            line=max(failure.schedule_index, 0),
            message=failure.render(),
        ))
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m tf_operator_tpu.analysis",
        description="concurrency lint (see docs/static-analysis.md)",
    )
    parser.add_argument("package", nargs="?", default="tf_operator_tpu",
                        help="package name or directory to lint "
                             "(default: tf_operator_tpu)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to report (default: "
                             "all; parse-error always reports)")
    parser.add_argument("--exclude", default=None,
                        help="comma-separated directory names to skip "
                             "(e.g. lint_fixtures)")
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="also write machine-readable findings to PATH "
                             "(schema in docs/static-analysis.md); with "
                             "--manifest, write the manifest there instead")
    parser.add_argument("--manifest", action="store_true",
                        help="emit the interface manifest (contract "
                             "surface, docs/static-analysis.md"
                             "#interface-manifest) instead of lint "
                             "findings: print it (or --json PATH it)")
    parser.add_argument("--diff", default=None, metavar="PATH",
                        help="with --manifest: compare the regenerated "
                             "manifest against the committed snapshot at "
                             "PATH and exit 1 on drift")
    parser.add_argument("--hlo", default=None, metavar="TARGET",
                        help="compiled-program lint: capture+check the "
                             "train-step HLO for a workload name, 'all', "
                             "or a capture-fixture .py path (docs/"
                             "static-analysis.md#hlo-rules). --json writes "
                             "findings; --manifest --json PATH writes the "
                             "collective-signature manifest; --diff PATH "
                             "gates against the committed "
                             "docs/hlo-manifest.json")
    parser.add_argument("--devices", type=int, default=None,
                        help="CPU virtual devices for --hlo capture "
                             "(default: $ANALYSIS_HLO_DEVICES, else 4)")
    parser.add_argument("--race", default=None, metavar="SCENARIO",
                        help="instead of the static lint, run the "
                             "race-checked interleaving soak over one "
                             "registered scenario, or 'all' "
                             "(analysis/scenarios.py)")
    parser.add_argument("--schedules", type=int, default=None,
                        help="schedules per scenario for --race (default: "
                             "$ANALYSIS_EXPLORE_BUDGET, else 150)")
    parser.add_argument("--seed", type=int, default=0,
                        help="base seed for --race schedules (default: 0)")
    args = parser.parse_args(argv)
    if args.diff is not None and not args.manifest and args.hlo is None:
        parser.error("--diff requires --manifest or --hlo")

    if args.hlo is not None:
        from . import hlo as hlo_mod

        wanted_hlo: Optional[Set[str]] = None
        if args.rules is not None:
            wanted_hlo = {r for r in args.rules.split(",") if r}
            unknown = wanted_hlo - set(ALL_RULES)
            if unknown:
                raise SystemExit(
                    f"unknown rule(s): {', '.join(sorted(unknown))}")
        if args.manifest and args.json is None:
            parser.error("--hlo --manifest requires --json PATH (the "
                         "manifest output file)")
        return hlo_mod.run_hlo(
            args.hlo,
            num_devices=args.devices,
            json_path=None if args.manifest else args.json,
            manifest_path=args.json if args.manifest else None,
            diff_path=args.diff,
            rules=wanted_hlo,
        )

    if args.manifest:
        root, _prefix = resolve_package_dir(args.package)
        exclude = [d for d in (args.exclude or "").split(",") if d]
        doc = contract.manifest_dict(package_contract(root,
                                                      exclude_dirs=exclude))
        text = json.dumps(doc, indent=2, sort_keys=True) + "\n"
        if args.json is not None:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(text)
        else:
            print(text, end="")
        if args.diff is not None:
            try:
                with open(args.diff, encoding="utf-8") as f:
                    committed = json.load(f)
            except (OSError, ValueError) as err:
                print(f"cannot read committed manifest {args.diff}: {err}")
                return 1
            drift = contract.diff_summary(committed, doc)
            if drift:
                for line in drift[:40]:
                    print(f"manifest drift: {line}")
                if len(drift) > 40:
                    print(f"... and {len(drift) - 40} more difference(s)")
                print(f"interface manifest drifted from {args.diff}; if "
                      f"the contract change is intentional, regenerate "
                      f"with `python -m tf_operator_tpu.analysis "
                      f"--manifest --json {args.diff}` and commit it")
                return 1
            print(f"interface manifest matches {args.diff}")
        return 0

    if args.race is not None:
        from . import scenarios

        if args.race == "all":
            names = sorted(scenarios.SCENARIOS)
        elif args.race in scenarios.SCENARIOS:
            names = [args.race]
        else:
            known = ", ".join(sorted(scenarios.SCENARIOS))
            raise SystemExit(
                f"unknown scenario: {args.race!r} (known: {known}, or 'all')")
        schedules = args.schedules
        if schedules is None:
            schedules = int(os.environ.get("ANALYSIS_EXPLORE_BUDGET", "150"))
        findings = race_findings(names, schedules=schedules, seed=args.seed)
        for finding in findings:
            print(finding.render())
        print(f"{len(findings)} race finding(s) over {len(names)} "
              f"scenario(s) x {schedules} schedules")
        if args.json is not None:
            write_findings_json(args.json, findings,
                                target=f"race:{args.race}")
        return 1 if findings else 0

    root, prefix = resolve_package_dir(args.package)
    exclude = [d for d in (args.exclude or "").split(",") if d]
    wanted: Optional[Set[str]] = None
    if args.rules is not None:
        wanted = {r for r in args.rules.split(",") if r}
        unknown = wanted - set(ALL_RULES)
        if unknown:
            raise SystemExit(f"unknown rule(s): {', '.join(sorted(unknown))}")
        # an unparseable file can never be claimed clean under any filter
        wanted.add(RULE_PARSE_ERROR)
    findings = check_package(root, exclude_dirs=exclude, rules=wanted)
    if wanted is not None:
        findings = [f for f in findings if f.rule in wanted]
    for finding in findings:
        print(finding.render(prefix))
    print(f"{len(findings)} finding(s) in {prefix.rstrip('/')}")
    if args.json is not None:
        write_findings_json(args.json, findings, prefix.rstrip("/"))
    return 1 if findings else 0
