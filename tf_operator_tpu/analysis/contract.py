"""Contract-surface extraction: the interface manifest behind the four
contract-drift rules (docs/static-analysis.md#interface-manifest).

The operator's real API is not a function signature — it is *contract
wiring*: dataclass fields that must survive a dict round-trip
(api/serialization.py), TPUJOB_* env knobs that must flow producer →
consumer (controller/topology.py → workloads/runner.py), tpujob_* metrics
that must match docs/monitoring.md, and JobConditionType members that must
be reachable with declared reasons.  This module extracts that surface from
the AST alone (stdlib only, no imports of the checked code) into a
canonical, schema-versioned manifest dict, and derives conformance findings
from it:

    wire-roundtrip   field serialized in only one direction (or neither)
    knob-chain       knob produced with no consumer / consumed but never
                     produced / declared but dead
    metric-doc       emitted metric undocumented, or documented metric
                     never emitted
    state-machine    declared condition type never set at any write site
                     (the per-write-site edge check lives in __init__)

Sites are exempted with a `# contract: exempt(<rule>)` annotation on the
flagged line (or the first line of its statement), always next to a comment
saying *why* — the analogue of `# lint: allow(...)` for contract surface
that is intentionally one-directional or externally owned.

`__init__` imports this module (never the reverse); rule-name strings are
therefore duplicated here rather than imported.
"""
from __future__ import annotations

import ast
import posixpath
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

MANIFEST_VERSION = 1
MANIFEST_SCHEMA = "tf-operator-tpu/interface-manifest"

KNOB_PREFIX = "TPUJOB_"
METRIC_PREFIX = "tpujob_"
CONDITION_ENUM = "JobConditionType"

RULE_WIRE = "wire-roundtrip"
RULE_KNOB = "knob-chain"
RULE_METRIC = "metric-doc"
RULE_STATE = "state-machine"

# condition-write entry points (runtime/conditions.py) and their verb
CONDITION_CALLS = {
    "update_job_conditions": "set",
    "set_operational_condition": "set",
    "clear_condition": "clear",
}

# a knob is the *full* TPUJOB_<NAME> string — the bare prefix (e.g.
# `key.startswith("TPUJOB_")`) and prose strings embedding a knob name
# ("TPUJOB_X entries may be stale ...") must not register
_KNOB_NAME_RE = re.compile(r"^TPUJOB_[A-Z0-9_]+$")
_EXEMPT_RE = re.compile(r"#\s*contract:\s*exempt\(([a-z-]+)\)")
_METRIC_DOC_RE = re.compile(r"\btpujob_[a-z0-9_]+")

Site = Tuple[str, int]  # (rel_path, line)


# ---------------------------------------------------------------------------
# per-file parse state


class _FileInfo:
    """One parsed source file: tree + exemption annotations.

    Mirrors the statement-header logic of the lint suppressions: an
    annotation on the first line of a multi-line statement covers every
    line of that statement.
    """

    def __init__(self, rel_path: str, source: str, tree=None):
        self.rel_path = rel_path
        self.source = source
        self.error: Optional[SyntaxError] = None
        if tree is None:
            try:
                tree = ast.parse(source)
            except SyntaxError as err:
                self.error = err
                tree = None
        self.tree = tree
        self.exempt: Dict[int, set] = {}
        for lineno, line in enumerate(source.splitlines(), 1):
            for m in _EXEMPT_RE.finditer(line):
                self.exempt.setdefault(lineno, set()).add(m.group(1))
        self.stmt_header: Dict[int, int] = {}
        if self.tree is not None:
            for node in ast.walk(self.tree):
                if isinstance(node, ast.stmt) and getattr(node, "end_lineno", None):
                    for line_no in range(node.lineno, node.end_lineno + 1):
                        prev = self.stmt_header.get(line_no)
                        if prev is None or node.lineno > prev:
                            self.stmt_header[line_no] = node.lineno

    def is_exempt(self, line: int, rule: str) -> bool:
        if rule in self.exempt.get(line, ()):
            return True
        header = self.stmt_header.get(line)
        return header is not None and rule in self.exempt.get(header, ())


# ---------------------------------------------------------------------------
# data model


@dataclass
class WireField:
    name: str
    line: int
    to: bool = False
    frm: bool = False
    exempt: bool = False


@dataclass
class WireType:
    name: str
    path: str
    line: int
    fields: Dict[str, WireField] = field(default_factory=dict)


@dataclass
class Knob:
    name: str
    constant: Optional[str] = None
    const_site: Optional[Site] = None
    producers: List[Site] = field(default_factory=list)
    consumers: List[Site] = field(default_factory=list)
    exempt: bool = False


@dataclass
class Metric:
    name: str
    kind: str
    labels: List[str]
    path: str
    line: int
    exempt: bool = False


@dataclass
class Condition:
    name: str
    path: str
    line: int
    set_reasons: set = field(default_factory=set)
    clear_reasons: set = field(default_factory=set)
    set_sites: int = 0
    exempt: bool = False


@dataclass
class Contract:
    serializer_modules: List[str]
    wire_types: Dict[str, WireType]
    knobs: Dict[str, Knob]
    metrics: Dict[str, Metric]
    conditions: Dict[str, Condition]
    doc_path: Optional[str] = None
    documented: Dict[str, int] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# shared AST helpers


def _type_name(node) -> Optional[str]:
    """The bare type name a Name/Attribute/str-Constant node refers to."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value  # forward reference
    return None


def _ann_info(node) -> Tuple[Optional[str], Optional[str], Optional[str]]:
    """(direct, element, mapping-value) type names of an annotation.

    Optional[X] is transparent; List/Sequence/Set/Tuple yield their element
    type; Dict/Mapping yield their value type (the key side of the wire
    dicts is always a plain enum/str).
    """
    name = _type_name(node)
    if name is not None:
        return name, None, None
    if isinstance(node, ast.Subscript):
        base = _type_name(node.value)
        if base == "Optional":
            return _ann_info(node.slice)
        if base in ("List", "Sequence", "Set", "FrozenSet", "Tuple",
                    "list", "tuple", "set", "frozenset"):
            elts = (node.slice.elts
                    if isinstance(node.slice, ast.Tuple) else [node.slice])
            return None, _type_name(elts[0]) if elts else None, None
        if base in ("Dict", "Mapping", "MutableMapping", "dict"):
            if isinstance(node.slice, ast.Tuple) and len(node.slice.elts) == 2:
                return None, None, _type_name(node.slice.elts[1])
    return None, None, None


def _ann_names(node) -> List[str]:
    return [n for n in _ann_info(node) if n is not None]


def _param_names(fn) -> set:
    args = fn.args
    names = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    return names


def reason_candidates(node, module_consts: Dict[str, str],
                      enclosing_fn=None) -> Optional[List[str]]:
    """The reason strings a condition-write argument can evaluate to, or
    None when the edge set is uncheckable (parameter, attribute, call, ...).

    Resolves: string literals; module-level string constants; local
    variables whose every assignment in the enclosing function is a string
    literal (empty-string assignments are dropped — the ``reason = ""``
    then ``if reason:`` idiom means empty never reaches the write)."""
    if isinstance(node, ast.Constant):
        return [node.value] if isinstance(node.value, str) else None
    if isinstance(node, ast.Name):
        if node.id in module_consts:
            return [module_consts[node.id]]
        if enclosing_fn is not None and node.id not in _param_names(enclosing_fn):
            values: List[str] = []
            for sub in ast.walk(enclosing_fn):
                if isinstance(sub, ast.Assign):
                    targets = sub.targets
                elif isinstance(sub, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr)):
                    targets = [sub.target]
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    targets = [sub.target]
                else:
                    continue
                plain_hit = any(isinstance(t, ast.Name) and t.id == node.id
                                for t in targets)
                nested_hit = any(
                    isinstance(n, ast.Name) and n.id == node.id
                    for t in targets for n in ast.walk(t))
                if not nested_hit:
                    continue
                if not plain_hit:  # tuple unpacking etc. hides the value
                    return None
                if isinstance(sub, ast.AnnAssign) and sub.value is None:
                    continue  # bare annotation binds nothing
                value = getattr(sub, "value", None)
                if (isinstance(sub, (ast.Assign, ast.AnnAssign, ast.NamedExpr))
                        and isinstance(value, ast.Constant)
                        and isinstance(value.value, str)):
                    values.append(value.value)
                else:
                    return None  # reassigned from something non-literal
            values = sorted({v for v in values if v})
            if values:
                return values
    return None


def module_string_consts(tree) -> Dict[str, str]:
    """Module-level NAME = "literal" assignments (reason/knob constants)."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            target, value = stmt.targets[0], stmt.value
        elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            target, value = stmt.target, stmt.value
        else:
            continue
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            out[target.id] = value.value
    return out


def _walk_with_fn(tree):
    """Yield (node, innermost enclosing FunctionDef or None) pairs."""

    def visit(node, fn):
        for child in ast.iter_child_nodes(node):
            child_fn = (child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn)
            yield child, child_fn
            yield from visit(child, child_fn)

    yield from visit(tree, None)


def _call_arg(node: ast.Call, index: int, keyword: str):
    for kw in node.keywords:
        if kw.arg == keyword:
            return kw.value
    if len(node.args) > index:
        return node.args[index]
    return None


# ---------------------------------------------------------------------------
# (a) wire types: declared fields vs to_dict/from_dict coverage


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for deco in cls.decorator_list:
        target = deco.func if isinstance(deco, ast.Call) else deco
        if _type_name(target) == "dataclass":
            return True
    return False


@dataclass
class _FieldDecl:
    name: str
    line: int
    ann: object


def _class_fields(cls: ast.ClassDef) -> List[_FieldDecl]:
    out = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
            ann = stmt.annotation
            base = ann.value if isinstance(ann, ast.Subscript) else ann
            if _type_name(base) == "ClassVar":
                continue
            out.append(_FieldDecl(stmt.target.id, stmt.lineno, ann))
    return out


def _extract_wire(infos: Sequence[_FileInfo]):
    # every @dataclass in the scanned set, preferring definitions that live
    # next to a serializer module when a name is defined more than once
    defs: Dict[str, List[Tuple[_FileInfo, ast.ClassDef]]] = {}
    for info in infos:
        for node in ast.walk(info.tree):
            if isinstance(node, ast.ClassDef) and _is_dataclass(node):
                defs.setdefault(node.name, []).append((info, node))

    serializers = []
    for info in infos:
        to_funcs = [n for n in info.tree.body
                    if isinstance(n, ast.FunctionDef)
                    and n.name.endswith("_to_dict")]
        from_funcs = [n for n in info.tree.body
                      if isinstance(n, ast.FunctionDef)
                      and n.name.endswith("_from_dict")]
        if to_funcs and from_funcs:
            serializers.append((info, to_funcs, from_funcs))

    ser_dirs = {posixpath.dirname(info.rel_path.replace("\\", "/"))
                for info, _t, _f in serializers}

    def pick(candidates):
        def key(item):
            info, _cls = item
            d = posixpath.dirname(info.rel_path.replace("\\", "/"))
            return (0 if d in ser_dirs else 1, info.rel_path)
        return min(candidates, key=key)

    table: Dict[str, Tuple[_FileInfo, ast.ClassDef, List[_FieldDecl]]] = {}
    for name, candidates in defs.items():
        info, cls = pick(candidates)
        table[name] = (info, cls, _class_fields(cls))

    def fields_of(cls_name: str, attr: str):
        entry = table.get(cls_name)
        if entry is None:
            return None
        for f in entry[2]:
            if f.name == attr:
                return _ann_info(f.ann)
        return None

    # seed the closure from serializer signatures and constructor calls
    seeds: set = set()
    for info, to_funcs, from_funcs in serializers:
        for fn in to_funcs:
            for arg in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
                if arg.annotation is not None:
                    for nm in _ann_names(arg.annotation):
                        if nm in table:
                            seeds.add(nm)
        for fn in from_funcs:
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    nm = _type_name(node.func)
                    if nm in table:
                        seeds.add(nm)

    closure: set = set()
    queue = sorted(seeds)
    while queue:
        nm = queue.pop()
        if nm in closure:
            continue
        closure.add(nm)
        for f in table[nm][2]:
            for ref in _ann_names(f.ann):
                if ref in table and ref not in closure:
                    queue.append(ref)

    wire_types: Dict[str, WireType] = {}
    for nm in sorted(closure):
        info, cls, fields = table[nm]
        wt = WireType(nm, info.rel_path, cls.lineno)
        for f in fields:
            wf = WireField(f.name, f.line)
            wf.exempt = info.is_exempt(f.line, RULE_WIRE)
            wt.fields[f.name] = wf
        wire_types[nm] = wt

    def infer_expr(node, env):
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            base = infer_expr(node.value, env)
            if base is not None:
                got = fields_of(base, node.attr)
                if got is not None:
                    return got[0]
            return None
        if isinstance(node, ast.Call):
            nm = _type_name(node.func)
            if nm in closure:
                return nm
        return None

    def bind_iter(target, iter_node, env):
        # for x in obj.field / for k, v in obj.field.items()
        if (isinstance(iter_node, ast.Call)
                and isinstance(iter_node.func, ast.Attribute)
                and iter_node.func.attr == "items" and not iter_node.args):
            inner = iter_node.func.value
            if isinstance(inner, ast.Attribute):
                base = infer_expr(inner.value, env)
                if base is not None:
                    got = fields_of(base, inner.attr)
                    if (got is not None and got[2] is not None
                            and isinstance(target, ast.Tuple)
                            and len(target.elts) == 2
                            and isinstance(target.elts[1], ast.Name)):
                        env[target.elts[1].id] = got[2]
            return
        if isinstance(iter_node, ast.Attribute):
            base = infer_expr(iter_node.value, env)
            if base is not None:
                got = fields_of(base, iter_node.attr)
                if (got is not None and got[1] is not None
                        and isinstance(target, ast.Name)):
                    env[target.id] = got[1]

    def typed_env(fn):
        env: Dict[str, str] = {}
        for arg in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            if arg.annotation is not None:
                for nm in _ann_names(arg.annotation):
                    if nm in closure:
                        env[arg.arg] = nm
                        break
        for _ in range(3):  # fixpoint over local aliases / nested loops
            for node in ast.walk(fn):
                if (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    t = infer_expr(node.value, env)
                    if t in closure:
                        env[node.targets[0].id] = t
                elif (isinstance(node, ast.AnnAssign)
                        and isinstance(node.target, ast.Name)):
                    for nm in _ann_names(node.annotation):
                        if nm in closure:
                            env[node.target.id] = nm
                            break
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    bind_iter(node.target, node.iter, env)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        bind_iter(gen.target, gen.iter, env)
        return env

    for info, to_funcs, from_funcs in serializers:
        for fn in to_funcs:
            env = typed_env(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute):
                    base = infer_expr(node.value, env)
                    if base in wire_types and node.attr in wire_types[base].fields:
                        wire_types[base].fields[node.attr].to = True
        for fn in from_funcs:
            env = typed_env(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    nm = _type_name(node.func)
                    if nm not in wire_types:
                        continue
                    order = [f.name for f in table[nm][2]]
                    for i, _arg in enumerate(node.args):
                        if i < len(order):
                            wire_types[nm].fields[order[i]].frm = True
                    for kw in node.keywords:
                        if kw.arg in wire_types[nm].fields:
                            wire_types[nm].fields[kw.arg].frm = True
                elif (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Store)):
                    base = infer_expr(node.value, env)
                    if base in wire_types and node.attr in wire_types[base].fields:
                        wire_types[base].fields[node.attr].frm = True

    modules = sorted(info.rel_path for info, _t, _f in serializers)
    return modules, wire_types


# ---------------------------------------------------------------------------
# (b) TPUJOB_* env knobs: producers vs consumers


def _extract_knobs(infos: Sequence[_FileInfo]) -> Dict[str, Knob]:
    knobs: Dict[str, Knob] = {}
    by_path = {info.rel_path: info for info in infos}
    const_table: Dict[str, str] = {}

    def knob(name: str) -> Knob:
        return knobs.setdefault(name, Knob(name))

    for info in infos:
        for const_name, value in module_string_consts(info.tree).items():
            if _KNOB_NAME_RE.match(value):
                const_table[const_name] = value

    # record declaration sites (first per knob, in path order)
    for info in sorted(infos, key=lambda i: i.rel_path):
        for stmt in info.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                target, value = stmt.target, stmt.value
            else:
                continue
            if (isinstance(value, ast.Constant) and isinstance(value.value, str)
                    and _KNOB_NAME_RE.match(value.value)):
                k = knob(value.value)
                if k.const_site is None:
                    k.constant = target.id
                    k.const_site = (info.rel_path, stmt.lineno)

    def knob_of(node) -> Optional[str]:
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and _KNOB_NAME_RE.match(node.value)):
            return node.value
        if isinstance(node, ast.Name):
            return const_table.get(node.id)
        if isinstance(node, ast.Attribute):
            return const_table.get(node.attr)
        return None

    for info in infos:
        path = info.rel_path
        for node in ast.walk(info.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        name = knob_of(t.slice)
                        if name:
                            knob(name).producers.append((path, t.lineno))
            elif isinstance(node, ast.Dict):
                for key in node.keys:
                    if key is not None:
                        name = knob_of(key)
                        if name:
                            knob(name).producers.append((path, key.lineno))
            elif isinstance(node, ast.Call):
                start = 0
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("set_env", "setdefault")
                        and node.args):
                    name = knob_of(node.args[0])
                    if name:
                        knob(name).producers.append((path, node.lineno))
                        start = 1
                for arg in node.args[start:]:
                    name = knob_of(arg)
                    if name:
                        knob(name).consumers.append((path, arg.lineno))
                for kw in node.keywords:
                    name = knob_of(kw.value)
                    if name:
                        knob(name).consumers.append((path, kw.value.lineno))
            elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
                name = knob_of(node.slice)
                if name:
                    knob(name).consumers.append((path, node.lineno))
            elif isinstance(node, ast.Compare) and any(
                    isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                name = knob_of(node.left)
                if name:
                    knob(name).consumers.append((path, node.lineno))

    for k in knobs.values():
        k.producers.sort()
        k.consumers.sort()
        sites = list(k.producers) + list(k.consumers)
        if k.const_site is not None:
            sites.append(k.const_site)
        k.exempt = any(
            by_path[p].is_exempt(line, RULE_KNOB)
            for p, line in sites if p in by_path)
    return knobs


# ---------------------------------------------------------------------------
# (c) tpujob_* metrics


def _extract_metrics(infos: Sequence[_FileInfo]) -> Dict[str, Metric]:
    metrics: Dict[str, Metric] = {}
    for info in sorted(infos, key=lambda i: i.rel_path):
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge")):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith(METRIC_PREFIX)):
                continue
            name = node.args[0].value
            if name in metrics:  # first registration wins
                continue
            label_node = None
            if len(node.args) > 2:
                label_node = node.args[2]
            else:
                for kw in node.keywords:
                    if kw.arg == "label_names":
                        label_node = kw.value
            labels = []
            if isinstance(label_node, (ast.Tuple, ast.List)):
                labels = [e.value for e in label_node.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
            metric = Metric(name, node.func.attr, labels,
                            info.rel_path, node.lineno)
            metric.exempt = info.is_exempt(node.lineno, RULE_METRIC)
            metrics[name] = metric
    return metrics


def _scan_doc(text: str) -> Dict[str, int]:
    documented: Dict[str, int] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        for m in _METRIC_DOC_RE.finditer(line):
            documented.setdefault(m.group(0), lineno)
    return documented


# ---------------------------------------------------------------------------
# (d) JobConditionType members and their write sites


def _extract_conditions(infos: Sequence[_FileInfo]) -> Dict[str, Condition]:
    conditions: Dict[str, Condition] = {}
    for info in sorted(infos, key=lambda i: i.rel_path):
        for node in ast.walk(info.tree):
            if not (isinstance(node, ast.ClassDef)
                    and node.name == CONDITION_ENUM):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Constant)):
                    member = stmt.targets[0].id
                    if member not in conditions:
                        cond = Condition(member, info.rel_path, stmt.lineno)
                        cond.exempt = info.is_exempt(stmt.lineno, RULE_STATE)
                        conditions[member] = cond

    for info in infos:
        consts = module_string_consts(info.tree)
        for node, fn in _walk_with_fn(info.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _type_name(node.func)
            verb = CONDITION_CALLS.get(callee or "")
            if verb is None:
                continue
            member = _type_name(_call_arg(node, 1, "ctype"))
            if member is None or member not in conditions:
                continue
            cond = conditions[member]
            reasons = reason_candidates(_call_arg(node, 2, "reason"),
                                        consts, fn)
            target = (cond.set_reasons if verb == "set"
                      else cond.clear_reasons)
            for reason in reasons or ():
                if reason:
                    target.add(reason)
            if verb == "set":
                cond.set_sites += 1
    return conditions


# ---------------------------------------------------------------------------
# public API


def build_contract(files, doc=None) -> Contract:
    """Extract the contract surface.

    `files` is a sequence of (rel_path, source) or (rel_path, source, tree)
    tuples; unparseable files are skipped (the lint reports them as
    parse-error findings separately).  `doc` is an optional
    (display_path, text) pair for docs/monitoring.md.
    """
    infos = []
    for item in files:
        rel_path, source = item[0], item[1]
        tree = item[2] if len(item) > 2 else None
        fi = _FileInfo(rel_path, source, tree)
        if fi.tree is not None:
            infos.append(fi)
    modules, wire_types = _extract_wire(infos)
    contract = Contract(
        serializer_modules=modules,
        wire_types=wire_types,
        knobs=_extract_knobs(infos),
        metrics=_extract_metrics(infos),
        conditions=_extract_conditions(infos),
    )
    if doc is not None:
        contract.doc_path = doc[0]
        contract.documented = _scan_doc(doc[1])
    return contract


def contract_findings(contract: Contract):
    """[(rule, path, line, message), ...] derived from the contract."""
    out = []
    for name in sorted(contract.wire_types):
        wt = contract.wire_types[name]
        for f in wt.fields.values():
            if f.exempt or (f.to and f.frm):
                continue
            if f.to:
                what = "serialized by *_to_dict but never restored by *_from_dict"
            elif f.frm:
                what = "restored by *_from_dict but never serialized by *_to_dict"
            else:
                what = "declared but serialized in neither direction"
            out.append((RULE_WIRE, wt.path, f.line,
                        f"wire field '{name}.{f.name}' is {what} "
                        f"(fix the serializer or annotate "
                        f"`# contract: exempt({RULE_WIRE})` with why)"))
    for name in sorted(contract.knobs):
        k = contract.knobs[name]
        if k.exempt:
            continue
        if k.producers and not k.consumers:
            path, line = k.producers[0]
            out.append((RULE_KNOB, path, line,
                        f"env knob '{name}' is produced but never consumed "
                        f"(no reader in the scanned tree)"))
        elif k.consumers and not k.producers:
            path, line = k.consumers[0]
            out.append((RULE_KNOB, path, line,
                        f"env knob '{name}' is consumed but never produced "
                        f"(annotate `# contract: exempt({RULE_KNOB})` for "
                        f"user-set overrides)"))
        elif not k.producers and not k.consumers and k.const_site is not None:
            path, line = k.const_site
            out.append((RULE_KNOB, path, line,
                        f"env knob '{name}' is declared but never produced "
                        f"or consumed"))
    for name in sorted(contract.metrics):
        m = contract.metrics[name]
        if m.exempt:
            continue
        if name not in contract.documented:
            out.append((RULE_METRIC, m.path, m.line,
                        f"metric '{name}' is emitted but not documented in "
                        f"docs/monitoring.md"))
    if contract.doc_path is not None:
        for name in sorted(contract.documented):
            if name not in contract.metrics:
                out.append((RULE_METRIC, contract.doc_path,
                            contract.documented[name],
                            f"metric '{name}' is documented but never "
                            f"emitted by the package"))
    for name in sorted(contract.conditions):
        cond = contract.conditions[name]
        if cond.exempt or cond.set_sites:
            continue
        out.append((RULE_STATE, cond.path, cond.line,
                    f"condition '{name}' is declared but never set at any "
                    f"condition-write site"))
    out.sort(key=lambda f: (f[1], f[2], f[0], f[3]))
    return out


def manifest_dict(contract: Contract) -> dict:
    """The canonical manifest document (stable: no line numbers, sorted
    keys, deduplicated module paths) — what gets committed to
    docs/interface-manifest.json and diff-gated in CI."""
    wire = {}
    for name, wt in sorted(contract.wire_types.items()):
        wire[name] = {
            "module": wt.path,
            "fields": {
                f.name: {"to": f.to, "from": f.frm, "exempt": f.exempt}
                for f in wt.fields.values()
            },
        }
    knobs = {}
    for name, k in sorted(contract.knobs.items()):
        knobs[name] = {
            "constant": k.constant,
            "producers": sorted({p for p, _line in k.producers}),
            "consumers": sorted({p for p, _line in k.consumers}),
            "exempt": k.exempt,
        }
    metrics = {}
    for name, m in sorted(contract.metrics.items()):
        metrics[name] = {
            "kind": m.kind,
            "labels": list(m.labels),
            "module": m.path,
            "documented": name in contract.documented,
        }
    conditions = {}
    for name, cond in sorted(contract.conditions.items()):
        conditions[name] = {
            "set_reasons": sorted(cond.set_reasons),
            "clear_reasons": sorted(cond.clear_reasons),
            "set": cond.set_sites,
        }
    return {
        "version": MANIFEST_VERSION,
        "schema": MANIFEST_SCHEMA,
        "serializers": list(contract.serializer_modules),
        "wire": wire,
        "knobs": knobs,
        "metrics": metrics,
        "conditions": conditions,
        "doc": contract.doc_path,
    }


def diff_summary(committed, regenerated, prefix: str = "") -> List[str]:
    """Human-readable recursive diff of two manifest documents."""
    lines: List[str] = []
    if isinstance(committed, dict) and isinstance(regenerated, dict):
        for key in sorted(set(committed) | set(regenerated), key=str):
            sub = f"{prefix}.{key}" if prefix else str(key)
            if key not in committed:
                lines.append(f"{sub}: only in regenerated manifest")
            elif key not in regenerated:
                lines.append(f"{sub}: only in committed manifest")
            else:
                lines.extend(diff_summary(committed[key], regenerated[key], sub))
    elif committed != regenerated:
        lines.append(f"{prefix}: committed {committed!r} != "
                     f"regenerated {regenerated!r}")
    return lines
