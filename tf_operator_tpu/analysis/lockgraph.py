"""Whole-program lock analysis: the interprocedural layer of the lint.

The per-file rules in `tf_operator_tpu.analysis` are deliberately
intraprocedural — they check each statement against the lock it can see.
This module builds a package-wide model and checks the properties that only
exist *between* functions and files:

  lock-order            a cycle in the may-hold-while-acquiring graph — the
                        static deadlock precondition.  Nodes are lock
                        *declarations* (`self.X = locks.new_lock("name")`
                        sites, including `new_rlock`/`new_condition` and
                        module-level locks); edges mean "some code path
                        acquires B while holding A", from `with`-block
                        nesting plus call chains.  Reported once per cycle
                        with the full witness path and the file:line of
                        every edge.
  guarded-by-interproc  a `# guarded-by:` field READ on a call chain along
                        which no caller holds the declared lock.  The
                        intraprocedural `guarded-by` rule owns writes; this
                        rule closes the read side: a public method (or a
                        helper only reachable from one) that snapshots a
                        guarded map without the lock sees torn state.
  atomicity             check-then-act on a guarded field: the field is
                        read under one `with <lock>:` block and written
                        under a *different* acquisition of the same lock in
                        the same function — the lock was released between
                        the check and the act, so the read may be stale by
                        the time the write lands.

Model (kept deliberately simple, like the per-file rules):

  - A "lock" is an attribute or module global assigned from
    `locks.new_lock/new_rlock/new_condition(...)`.  The node id is the
    declaring `Class.attr` (or `module:name`), displayed with the runtime
    name hint; f-string names keep their literal prefix (`informer-*`).
  - Calls resolve to: `self.m()` (own class + bases), module functions,
    `self.attr.m()` where `self.attr = SomeClass(...)` in `__init__`, and
    `var.m()` where `var = SomeClass(...)` earlier in the same function.
    Anything else (duck-typed callbacks, externals) is out of the graph —
    the dynamic layer (`analysis/explore.py`) covers what this misses.
  - Held-lock tracking is syntactic `with` nesting plus `# requires-lock:`
    entry assumptions; `Condition.wait()`'s release-while-waiting is not
    modeled.  Nested function bodies are not analyzed here (the per-file
    rules already check their writes with an empty held set).

Suppressions work like every other rule (`# lint: allow(<rule>)` on the
statement's header line); a `lock-order` cycle is suppressed when ANY of
its edges' acquisition sites carries the allow — a justified edge breaks
the cycle.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import (Callable, Dict, FrozenSet, List, Optional, Sequence,
                    Set, Tuple)

from ..utils import graph as graphlib

RULE_LOCK_ORDER = "lock-order"
RULE_GUARDED_INTERPROC = "guarded-by-interproc"
RULE_ATOMICITY = "atomicity"

LOCKGRAPH_RULES = (RULE_LOCK_ORDER, RULE_GUARDED_INTERPROC, RULE_ATOMICITY)

_LOCK_FACTORIES = {"new_lock", "new_rlock", "new_condition"}

# In-place mutator methods — kept in sync with the per-file checker's list.
_MUTATORS = {
    "add", "append", "appendleft", "clear", "discard", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "reverse", "setdefault", "sort",
    "update",
}

_ENTRY_SESSION = -1  # "held at entry" (requires-lock) — not a with block


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and (attr is None or node.attr == attr)
    )


def _lock_name_hint(call: ast.Call) -> str:
    """The runtime lock name passed to the factory: a literal, or the
    literal parts of an f-string with `*` for the formatted holes."""
    if not call.args:
        return "?"
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for value in arg.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            else:
                parts.append("*")
        return "".join(parts)
    return "?"


def _iter_mro(cls: "_ClassModel", resolve_base):
    """`cls` followed by its base chain: single inheritance, first
    resolvable base per class, cycle-guarded.  `resolve_base` maps a base
    name to a `_ClassModel` or None — the ONE place base resolution lives;
    every lock/guarded/method/attr-type lookup walks through here."""
    seen: Set[str] = set()
    current: Optional["_ClassModel"] = cls
    while current is not None and current.name not in seen:
        seen.add(current.name)
        yield current
        nxt = None
        for base in current.bases:
            candidate = resolve_base(base)
            if candidate is not None:
                nxt = candidate
                break
        current = nxt


def _is_lock_factory_call(node: ast.AST) -> Optional[ast.Call]:
    """The Call node when `node` is `locks.new_*(...)` / `new_*(...)`."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _LOCK_FACTORIES:
        return node
    if isinstance(func, ast.Name) and func.id in _LOCK_FACTORIES:
        return node
    return None


@dataclass
class LockDecl:
    lock_id: str    # "Class.attr" or "module.py:name"
    hint: str       # runtime name hint ("sync-health", "informer-*", ...)
    path: str
    line: int

    @property
    def display(self) -> str:
        return f"{self.lock_id}[{self.hint}]"


@dataclass
class _Access:
    attr: str
    write: bool
    line: int
    held: FrozenSet[str]                 # lock attrs held at this point
    sessions: Tuple[Tuple[str, int], ...]  # (lock attr, with-session id)


@dataclass
class _CallSite:
    # ("self", method) | ("func", name) | ("attr", self_attr, method)
    # | ("var", class_name, method)
    target: Tuple[str, ...]
    line: int
    held: FrozenSet[str]


@dataclass
class _Acquire:
    lock_attr: str
    line: int
    held_before: FrozenSet[str]


@dataclass
class _FuncModel:
    name: str
    line: int
    requires: Optional[str] = None
    accesses: List[_Access] = field(default_factory=list)
    calls: List[_CallSite] = field(default_factory=list)
    acquires: List[_Acquire] = field(default_factory=list)


@dataclass
class _ClassModel:
    name: str
    path: str
    bases: List[str] = field(default_factory=list)
    locks: Dict[str, LockDecl] = field(default_factory=dict)      # attr ->
    guarded: Dict[str, str] = field(default_factory=dict)         # attr -> lock attr
    attr_types: Dict[str, str] = field(default_factory=dict)      # attr -> class name
    methods: Dict[str, _FuncModel] = field(default_factory=dict)


@dataclass
class _ModuleModel:
    path: str
    locks: Dict[str, LockDecl] = field(default_factory=dict)      # global -> decl
    classes: Dict[str, _ClassModel] = field(default_factory=dict)
    functions: Dict[str, _FuncModel] = field(default_factory=dict)


class _FuncWalker:
    """Extract one function's lock behavior: acquisitions, guarded-field
    accesses, resolvable call sites — with `with`-nesting held tracking."""

    def __init__(self, func: ast.AST, cls: Optional[_ClassModel],
                 module: _ModuleModel, requires: Optional[str]) -> None:
        self.cls = cls
        self.module = module
        self.model = _FuncModel(name=func.name, line=func.lineno,
                                requires=requires)
        self.local_types: Dict[str, str] = {}  # var -> class name
        # write-ish Attribute node ids: assign/del targets (incl. subscript
        # bases) and mutator receivers — excluded from the read scan
        self._write_nodes: Set[int] = set()
        # guarded-attr map incl. inherited, computed ONCE (the class model
        # is fully built before any method is walked)
        self._guarded = self._all_guarded()
        held: Dict[str, int] = {}
        if requires:
            held[requires] = _ENTRY_SESSION
        self._walk_body(list(ast.iter_child_nodes(func)), held)

    # -- helpers -------------------------------------------------------

    def _known_lock(self, attr: str) -> bool:
        if self.cls is not None and self._resolve_lock_attr(attr) is not None:
            return True
        return attr in self.module.locks

    def _resolve_lock_attr(self, attr: str) -> Optional[LockDecl]:
        """Lock decl for `self.<attr>`, searching base classes too (the
        subclass's `with self._lock:` refers to the parent's decl)."""
        if self.cls is None:
            return None
        for cls in _iter_mro(self.cls, self.module.classes.get):
            if attr in cls.locks:
                return cls.locks[attr]
        return None

    def _with_lock_attrs(self, node: ast.With) -> List[str]:
        out = []
        for item in node.items:
            expr = item.context_expr
            if _is_self_attr(expr) and self.cls is not None:
                out.append(expr.attr)
            elif isinstance(expr, ast.Name) and expr.id in self.module.locks:
                out.append(expr.id)
        return out

    def _held_set(self, held: Dict[str, int]) -> FrozenSet[str]:
        return frozenset(held)

    def _sessions(self, held: Dict[str, int]) -> Tuple[Tuple[str, int], ...]:
        return tuple(sorted(held.items()))

    # -- the walk ------------------------------------------------------

    def _walk_body(self, nodes: List[ast.AST], held: Dict[str, int]) -> None:
        for node in nodes:
            self._walk_stmt(node, held)

    def _walk_stmt(self, node: ast.AST, held: Dict[str, int]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return  # nested scopes: out of the interprocedural model
        if isinstance(node, ast.With):
            taken = [a for a in self._with_lock_attrs(node)
                     if a not in held]
            child_held = dict(held)
            for attr in taken:
                # held_before accumulates the EARLIER items of this same
                # statement: `with self._a, self._b:` acquires b while
                # holding a, exactly like the nested form
                self.model.acquires.append(_Acquire(
                    lock_attr=attr, line=node.lineno,
                    held_before=frozenset(child_held)))
                child_held[attr] = node.lineno  # session id = with line
            for item in node.items:
                self._scan_expr(item.context_expr, held)
            self._walk_body(list(node.body), child_held)
            return
        # local type bindings: var = ClassName(...)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            func = node.value.func
            if isinstance(func, ast.Name):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.local_types[target.id] = func.id
        # guarded writes: mark target attribute nodes as write-ish
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                self._mark_write_target(target, node.lineno, held)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                self._mark_write_target(target, node.lineno, held)
        # everything else: expressions are scanned for calls/reads;
        # statement-ish children (incl. ExceptHandler and other
        # stmt containers, which are NOT ast.stmt) recurse with held
        # tracking intact — an `except` body's `with self._lock:` must
        # count like any other
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._scan_expr(child, held)
            else:
                self._walk_stmt(child, held)

    def _mark_write_target(self, target: ast.AST, line: int,
                           held: Dict[str, int]) -> None:
        base = target
        if isinstance(base, ast.Subscript):
            # the slice is scanned by the generic child loop (exactly once)
            base = base.value
        if (_is_self_attr(base) and self.cls is not None
                and base.attr in self._guarded):
            self._write_nodes.add(id(base))
            self.model.accesses.append(_Access(
                attr=base.attr, write=True, line=line,
                held=self._held_set(held), sessions=self._sessions(held)))

    def _all_guarded(self) -> Dict[str, str]:
        out: Dict[str, str] = {}
        if self.cls is None:
            return out
        for cls in _iter_mro(self.cls, self.module.classes.get):
            for k, v in cls.guarded.items():
                out.setdefault(k, v)
        return out

    @staticmethod
    def _expr_walk(node: ast.AST):
        """ast.walk minus nested-scope subtrees: a lambda's body runs at
        some later time on some other thread — locks held here prove
        nothing there (mirrors the per-file rules' treatment)."""
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, (ast.Lambda, ast.FunctionDef,
                                ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            yield sub
            stack.extend(ast.iter_child_nodes(sub))

    def _scan_expr(self, node: ast.AST, held: Dict[str, int]) -> None:
        for sub in self._expr_walk(node):
            if isinstance(sub, ast.Call):
                self._record_call(sub, held)
            elif (isinstance(sub, ast.Attribute)
                  and isinstance(sub.ctx, ast.Load)
                  and _is_self_attr(sub)
                  and id(sub) not in self._write_nodes
                  and self.cls is not None
                  and sub.attr in self._guarded):
                self.model.accesses.append(_Access(
                    attr=sub.attr, write=False, line=sub.lineno,
                    held=self._held_set(held),
                    sessions=self._sessions(held)))

    def _record_call(self, node: ast.Call, held: Dict[str, int]) -> None:
        func = node.func
        held_set = self._held_set(held)
        # mutator on a guarded attr: a write access, and its receiver load
        # must not double as a read
        if (isinstance(func, ast.Attribute) and func.attr in _MUTATORS
                and _is_self_attr(func.value) and self.cls is not None
                and func.value.attr in self._guarded):
            self._write_nodes.add(id(func.value))
            self.model.accesses.append(_Access(
                attr=func.value.attr, write=True, line=node.lineno,
                held=held_set, sessions=self._sessions(held)))
            return
        if isinstance(func, ast.Name):
            self.model.calls.append(_CallSite(
                target=("func", func.id), line=node.lineno, held=held_set))
            return
        if not isinstance(func, ast.Attribute):
            return
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self":
                self.model.calls.append(_CallSite(
                    target=("self", func.attr), line=node.lineno,
                    held=held_set))
            elif base.id in self.local_types:
                self.model.calls.append(_CallSite(
                    target=("var", self.local_types[base.id], func.attr),
                    line=node.lineno, held=held_set))
        elif _is_self_attr(base):
            # self.attr.method(); receiver load of a guarded attr counts as
            # a read (handled by the generic scan), the call may resolve via
            # the attr's constructor-assigned type
            self.model.calls.append(_CallSite(
                target=("attr", base.attr, func.attr), line=node.lineno,
                held=held_set))


def _build_module(tree: ast.Module, path: str, comments) -> _ModuleModel:
    """`comments` is the per-file annotation index (allow/guarded/requires
    line maps) built by the per-file checker."""
    module = _ModuleModel(path=path)

    # module-level locks and (future) globals
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            call = _is_lock_factory_call(
                node.value if node.value is not None else ast.Constant(None))
            if call is None:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                if isinstance(target, ast.Name):
                    module.locks[target.id] = LockDecl(
                        lock_id=f"{path}:{target.id}",
                        hint=_lock_name_hint(call), path=path,
                        line=node.lineno)

    def requires_for(fn: ast.AST) -> Optional[str]:
        return (comments.requires.get(fn.lineno)
                or comments.requires.get(fn.lineno - 1))

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            cls = _ClassModel(
                name=node.name, path=path,
                bases=[b.id for b in node.bases if isinstance(b, ast.Name)])
            module.classes[node.name] = cls
            methods = [n for n in node.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            # declarations: self.X = locks.new_*(...), self.X = Class(...),
            # and `# guarded-by:` annotations — from any method (__init__
            # usually, but lazily-created locks exist too)
            for method in methods:
                for sub in ast.walk(method):
                    if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                        continue
                    value = sub.value
                    targets = (sub.targets if isinstance(sub, ast.Assign)
                               else [sub.target])
                    self_attrs = [t.attr for t in targets
                                  if _is_self_attr(t)]
                    if not self_attrs or value is None:
                        continue
                    call = _is_lock_factory_call(value)
                    for attr in self_attrs:
                        if call is not None:
                            cls.locks.setdefault(attr, LockDecl(
                                lock_id=f"{node.name}.{attr}",
                                hint=_lock_name_hint(call), path=path,
                                line=sub.lineno))
                        elif (isinstance(value, ast.Call)
                              and isinstance(value.func, ast.Name)):
                            cls.attr_types.setdefault(attr, value.func.id)
                        lock = comments.guarded.get(sub.lineno)
                        if lock:
                            cls.guarded[attr] = lock
            for method in methods:
                walker = _FuncWalker(method, cls, module,
                                     requires_for(method))
                cls.methods[method.name] = walker.model
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walker = _FuncWalker(node, None, module, requires_for(node))
            module.functions[node.name] = walker.model

    return module


class _Project:
    """Cross-module resolution + the three interprocedural rules."""

    def __init__(self, modules: List[_ModuleModel]) -> None:
        self.modules = modules
        # class name -> models (usually one; duplicates resolve per-module
        # first, then by unique package-wide name)
        self.classes: Dict[str, List[_ClassModel]] = {}
        for module in modules:
            for cls in module.classes.values():
                self.classes.setdefault(cls.name, []).append(cls)

    def _class_named(self, name: str,
                     prefer_module: _ModuleModel) -> Optional[_ClassModel]:
        if name in prefer_module.classes:
            return prefer_module.classes[name]
        candidates = self.classes.get(name, [])
        if len(candidates) == 1:
            return candidates[0]
        return None

    def _module_of(self, cls: _ClassModel) -> _ModuleModel:
        for module in self.modules:
            if module.path == cls.path and cls.name in module.classes:
                return module
        raise KeyError(cls.name)  # pragma: no cover - construction invariant

    def _base_resolver(self, module: _ModuleModel):
        return lambda name: self._class_named(name, module)

    def _resolve_method(self, cls: _ClassModel,
                        name: str) -> Optional[Tuple[_ClassModel, _FuncModel]]:
        module = self._module_of(cls)
        for current in _iter_mro(cls, self._base_resolver(module)):
            if name in current.methods:
                return current, current.methods[name]
        return None

    def _resolve_lock(self, cls: Optional[_ClassModel],
                      module: _ModuleModel,
                      attr: str) -> Optional[LockDecl]:
        if cls is not None:
            for current in _iter_mro(cls, self._base_resolver(module)):
                if attr in current.locks:
                    return current.locks[attr]
        return module.locks.get(attr)

    def _resolve_call(self, cls: Optional[_ClassModel],
                      module: _ModuleModel, call: _CallSite
                      ) -> Optional[Tuple[Optional[_ClassModel], _FuncModel]]:
        kind = call.target[0]
        if kind == "self" and cls is not None:
            resolved = self._resolve_method(cls, call.target[1])
            if resolved is not None:
                return resolved
        elif kind == "func":
            fn = module.functions.get(call.target[1])
            if fn is not None:
                return None, fn
        elif kind == "attr" and cls is not None:
            attr, method = call.target[1], call.target[2]
            type_name = None
            for current in _iter_mro(cls, self._base_resolver(module)):
                if attr in current.attr_types:
                    type_name = current.attr_types[attr]
                    break
            if type_name is not None:
                target_cls = self._class_named(type_name, module)
                if target_cls is not None:
                    return self._resolve_method(target_cls, method)
        elif kind == "var":
            target_cls = self._class_named(call.target[1], module)
            if target_cls is not None:
                return self._resolve_method(target_cls, call.target[2])
        return None

    # -- lock-order ----------------------------------------------------

    def lock_order_edges(self) -> Dict[Tuple[str, str],
                                       List[Tuple[str, int, str]]]:
        """(outer lock id, inner lock id) -> every (path, line, detail)
        acquisition site witnessing the edge.  ALL sites are kept: an edge
        is only suppressible when every one of its sites carries the
        allow — one justified nesting must not silence an unjustified
        nesting of the same pair elsewhere."""
        # Step 1: per function, the set of lock decls it may acquire
        # transitively (fixpoint over the resolved call graph).
        func_key = id  # _FuncModel identity
        direct: Dict[int, Set[str]] = {}
        callees: Dict[int, Set[int]] = {}
        owners: Dict[int, Tuple[Optional[_ClassModel], _ModuleModel,
                                _FuncModel]] = {}
        for module in self.modules:
            scopes = [(None, fn) for fn in module.functions.values()]
            scopes += [(cls, fn) for cls in module.classes.values()
                       for fn in cls.methods.values()]
            for cls, fn in scopes:
                key = func_key(fn)
                owners[key] = (cls, module, fn)
                direct[key] = set()
                callees[key] = set()
                for acq in fn.acquires:
                    decl = self._resolve_lock(cls, module, acq.lock_attr)
                    if decl is not None:
                        direct[key].add(decl.lock_id)
                for call in fn.calls:
                    resolved = self._resolve_call(cls, module, call)
                    if resolved is not None:
                        callees[key].add(func_key(resolved[1]))
        acq_star: Dict[int, Set[str]] = {k: set(v) for k, v in direct.items()}
        changed = True
        while changed:
            changed = False
            for key, callee_keys in callees.items():
                for ck in callee_keys:
                    extra = acq_star.get(ck, set()) - acq_star[key]
                    if extra:
                        acq_star[key].update(extra)
                        changed = True

        # Step 2: edges.  Intraprocedural nesting + held-across-call.
        edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}

        def add_edge(outer: str, inner: str, path: str, line: int,
                     detail: str) -> None:
            if outer == inner:
                return  # re-entrant same-lock nesting is not an ordering
            edges.setdefault((outer, inner), []).append((path, line, detail))

        for key, (cls, module, fn) in owners.items():
            where = f"{cls.name + '.' if cls else ''}{fn.name}"
            for acq in fn.acquires:
                inner = self._resolve_lock(cls, module, acq.lock_attr)
                if inner is None:
                    continue
                for held_attr in acq.held_before:
                    outer = self._resolve_lock(cls, module, held_attr)
                    if outer is not None:
                        add_edge(outer.lock_id, inner.lock_id, module.path,
                                 acq.line, f"in {where}")
            # a requires-lock entry is already seeded into every call
            # site's held set by _FuncWalker, so held covers it
            for call in fn.calls:
                if not call.held:
                    continue
                resolved = self._resolve_call(cls, module, call)
                if resolved is None:
                    continue
                inner_ids = acq_star.get(func_key(resolved[1]), set())
                for held_attr in call.held:
                    outer = self._resolve_lock(cls, module, held_attr)
                    if outer is None:
                        continue
                    for inner_id in inner_ids:
                        add_edge(outer.lock_id, inner_id, module.path,
                                 call.line,
                                 f"in {where} via call to "
                                 f"{'.'.join(call.target[1:])}")
        return edges

    def lock_order_cycles(
        self,
        edge_allowed: Optional[Callable[[str, int], bool]] = None,
    ) -> List[List[Tuple[str, str, str, int, str]]]:
        """Cycles in the edge graph; each as a list of
        (outer, inner, path, line, detail) edges, deterministic order —
        one witness per strongly-connected component (fix one, rerun).

        `edge_allowed(path, line)` names suppressed acquisition sites; an
        edge drops out BEFORE cycle detection only when EVERY site
        witnessing it is suppressed (one justified nesting cannot silence
        an unjustified nesting of the same pair elsewhere), so an allow
        breaks exactly the cycles through fully-justified edges and every
        other cycle in the component still reports."""
        edges = self.lock_order_edges()
        if edge_allowed is not None:
            filtered = {}
            for pair, sites in edges.items():
                live = [s for s in sites if not edge_allowed(s[0], s[1])]
                if live:
                    filtered[pair] = live
            edges = filtered
        out = []
        for cycle in graphlib.witness_cycles(edges.keys()):
            detail = []
            for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                path, line, where = edges[(a, b)][0]
                detail.append((a, b, path, line, where))
            out.append(detail)
        return out

    # -- guarded-by-interproc ------------------------------------------

    def unguarded_reads(self) -> List[Tuple[_ClassModel, _FuncModel,
                                            _Access, str, List[str]]]:
        """(class, method, read access, lock attr, witness chain) for every
        guarded-field READ reachable on a chain where the lock is unheld."""
        findings = []
        for name in sorted(self.classes):
            for cls in self.classes[name]:
                findings.extend(self._class_unguarded_reads(cls))
        return findings

    def _merged_guarded(self, cls: _ClassModel) -> Dict[str, str]:
        """attr -> lock attr, base-class declarations included — a field
        declared `# guarded-by:` in the base is just as guarded in the
        subclass's methods."""
        module = self._module_of(cls)
        guarded: Dict[str, str] = {}
        for current in _iter_mro(cls, self._base_resolver(module)):
            for k, v in current.guarded.items():
                guarded.setdefault(k, v)
        return guarded

    def _class_unguarded_reads(self, cls: _ClassModel):
        guarded = self._merged_guarded(cls)
        if not guarded:
            return []
        locks_used = sorted(set(guarded.values()))

        # intraclass callers: method -> [(caller, held at call site)]
        callers: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for mname, fn in cls.methods.items():
            for call in fn.calls:
                if call.target[0] == "self":
                    callers.setdefault(call.target[1], []).append(
                        (mname, call.held))

        findings = []
        for lock in locks_used:
            # fixpoint: methods enterable with `lock` NOT held, with one
            # witness predecessor for the report
            unlocked: Dict[str, Optional[str]] = {}
            pending = []
            for mname, fn in cls.methods.items():
                if mname == "__init__" or fn.requires == lock:
                    continue
                is_entry = (not mname.startswith("_")
                            or mname not in callers)
                if is_entry:
                    unlocked[mname] = None
                    pending.append(mname)
            while pending:
                mname = pending.pop()
                fn = cls.methods.get(mname)
                if fn is None:
                    continue
                for call in fn.calls:
                    if call.target[0] != "self":
                        continue
                    callee = call.target[1]
                    target = cls.methods.get(callee)
                    if (target is None or callee in unlocked
                            or callee == "__init__"
                            or target.requires == lock
                            or lock in call.held):
                        continue
                    unlocked[callee] = mname
                    pending.append(callee)

            for mname, fn in cls.methods.items():
                if mname not in unlocked:
                    continue
                for access in fn.accesses:
                    if access.write:
                        continue  # writes are the per-file rule's job
                    if guarded.get(access.attr) != lock:
                        continue
                    if lock in access.held:
                        continue
                    chain = [mname]
                    node = unlocked[mname]
                    while node is not None:
                        chain.append(node)
                        node = unlocked.get(node)
                    chain.reverse()
                    findings.append((cls, fn, access, lock, chain))
        return findings

    # -- atomicity -----------------------------------------------------

    def check_then_act(self) -> List[Tuple[_ClassModel, _FuncModel,
                                           _Access, _Access, str]]:
        """(class, method, read, write, lock attr): the read and the write
        of one guarded field sit under *different* acquisitions of its lock
        in the same function (the lock was released in between)."""
        findings = []
        for name in sorted(self.classes):
            for cls in self.classes[name]:
                guarded = self._merged_guarded(cls)
                if not guarded:
                    continue
                for mname, fn in sorted(cls.methods.items()):
                    if mname == "__init__":
                        continue
                    for attr, lock in sorted(guarded.items()):
                        reads = [a for a in fn.accesses
                                 if a.attr == attr and not a.write
                                 and dict(a.sessions).get(lock) is not None]
                        writes = [a for a in fn.accesses
                                  if a.attr == attr and a.write
                                  and dict(a.sessions).get(lock) is not None]
                        for write in writes:
                            w_sess = dict(write.sessions)[lock]
                            if w_sess == _ENTRY_SESSION:
                                continue
                            prior = [r for r in reads
                                     if r.line < write.line
                                     and dict(r.sessions)[lock]
                                     not in (w_sess, _ENTRY_SESSION)]
                            # Double-checked pattern: a read of the same
                            # field inside the write's own critical section
                            # re-validates the stale check — that IS the
                            # documented fix, so it must not fire.
                            revalidated = any(
                                r.line <= write.line
                                and dict(r.sessions)[lock] == w_sess
                                for r in reads)
                            if prior and not revalidated:
                                findings.append(
                                    (cls, fn, prior[0], write, lock))
                                break  # one finding per (method, attr)
        return findings


def build_project(files: Sequence[Tuple[str, ast.Module, object]]
                  ) -> _Project:
    """`files` is (rel_path, parsed tree, per-file comments index)."""
    return _Project([
        _build_module(tree, path, comments)
        for path, tree, comments in files
    ])
