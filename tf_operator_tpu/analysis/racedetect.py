"""FastTrack-style happens-before data-race detector: the sanitizer layer.

The static rules reason about annotated lock discipline and the explorer
fails on invariants it is told to check; neither can catch a shared field
that is simply never locked consistently.  This module closes that gap the
way FastTrack (Flanagan & Freund, PLDI 2009) and Go's `-race` do: build the
happens-before relation from the synchronization the program actually
performed, and flag any pair of accesses to the same (object, field) — at
least one a write — that the relation does not order.

The model, fed entirely by the `utils.locks` seams:

  - every thread `t` carries a vector clock `C_t`;
  - every `InstrumentedLock` `m` carries a clock `L_m`: a release copies
    `C_t` into `L_m` and ticks `C_t[t]`; an acquire joins `L_m` into `C_t`
    — the release→acquire synchronization edge.  The events arrive via the
    `locks.add_lock_watcher` chain, which fires on every acquire/release
    regardless of which thread the explorer hook manages;
  - `locks.track_access(obj, field, is_write)` (and the `@shared_state`
    decorator that calls it) records read/write epochs per (object,
    field).  A write must happen-after the previous write and every
    recorded read; a read must happen-after the previous write;
  - the explorer contributes fork/join edges (`fork_barrier` before it
    starts scenario threads, `join_barrier` after it joins them) so
    single-threaded setup in `Scenario.build()` and the post-schedule
    `Scenario.check()` never read as racing with the scenario threads.

One detector instance per explored schedule (`analysis/explore.py` wires
it into every schedule; a detected race is a first-class `FAIL_RACE`
failure artifact with the same seed/decision-trace replay as a deadlock).
A variable reports at most one race and is then retired — FastTrack's
first-race-per-variable policy keeps reports readable.

Thread identities are `threading.get_ident()` values, labeled with the
thread's name at its first event so reports read "tpujob-explore-writer-b"
rather than an integer.
"""
from __future__ import annotations

import sys
import threading
from dataclasses import dataclass, field as dataclass_field
from typing import Dict, List, Optional, Tuple

from ..utils import locks

# Frames from these files are skipped when attributing an access to a
# source location: the seam and the detector are plumbing, not the access.
_PLUMBING_SUFFIXES = ("utils/locks.py", "analysis/racedetect.py")


def _join(into: Dict[int, int], other: Dict[int, int]) -> None:
    """Pointwise max: `into` ⊔= `other`."""
    for ident, clk in other.items():
        if clk > into.get(ident, 0):
            into[ident] = clk


@dataclass
class _VarState:
    """Per-(object, field) access history."""
    label: str                      # "ClassName.field" for reports
    write: Optional[Tuple[int, int, str, str]] = None  # (ident, clk, thread, site)
    reads: Dict[int, Tuple[int, str, str]] = dataclass_field(
        default_factory=dict)   # ident -> (clk, thread name, site)
    retired: bool = False           # one race per variable, then silence


@dataclass(frozen=True)
class RaceReport:
    var: str        # "ClassName.field"
    kind: str       # "write-write" | "read-write" | "write-read"
    current_op: str
    current_thread: str
    current_site: str
    prior_op: str
    prior_thread: str
    prior_site: str

    def render(self) -> str:
        return (
            f"data race on {self.var} ({self.kind}): "
            f"{self.current_op} by {self.current_thread} at "
            f"{self.current_site} is unordered with {self.prior_op} by "
            f"{self.prior_thread} at {self.prior_site} — no lock or "
            "fork/join edge orders the two accesses"
        )


class RaceDetector(locks.LockWatcher):
    """One schedule's happens-before state.  Install with
    `locks.add_lock_watcher(det)` + `locks.set_access_tracker(det.on_access)`;
    inspect `det.races` after the run."""

    def __init__(self) -> None:
        # Raw lock: the detector is called from inside InstrumentedLock
        # operations, so taking an instrumented lock here would recurse
        # into the watcher chain.
        self._meta = threading.Lock()  # lint: allow(bare-lock) — detector internals, see comment
        self._clocks: Dict[int, Dict[int, int]] = {}   # guarded-by: _meta
        self._lock_clocks: Dict[int, Dict[int, int]] = {}  # guarded-by: _meta
        self._vars: Dict[Tuple[int, str], _VarState] = {}  # guarded-by: _meta
        # Strong refs to every tracked object: id() keys must stay unique
        # for the schedule's lifetime, so no tracked object may be
        # collected (and its id reused) mid-schedule.
        self._pins: List[object] = []  # guarded-by: _meta
        self._names: Dict[int, str] = {}  # guarded-by: _meta
        # Vector clock new threads are born with (the fork edge): set by
        # fork_barrier to the forking thread's clock at that instant.
        self._origin: Dict[int, int] = {}  # guarded-by: _meta
        self.races: List[RaceReport] = []  # guarded-by: _meta

    # -- clock plumbing (all under _meta) ------------------------------

    # requires-lock: _meta
    def _clock(self, ident: int) -> Dict[int, int]:
        clock = self._clocks.get(ident)
        if clock is None:
            clock = dict(self._origin)
            clock[ident] = clock.get(ident, 0) + 1
            self._clocks[ident] = clock
            self._names[ident] = threading.current_thread().name
        return clock

    def fork_barrier(self) -> None:
        """Record the calling thread's clock as the birth clock of every
        thread first seen afterwards: writes the caller performed so far
        happen-before everything those threads do."""
        ident = threading.get_ident()
        with self._meta:
            clock = self._clock(ident)
            self._origin = dict(clock)
            clock[ident] += 1

    def join_barrier(self) -> None:
        """Join every known thread's clock into the calling thread's:
        everything the joined threads did happens-before what the caller
        does next (the explorer calls this after join_all, so
        `Scenario.check` reads are ordered after scenario-thread writes)."""
        ident = threading.get_ident()
        with self._meta:
            clock = self._clock(ident)
            for other_ident, other in self._clocks.items():
                if other_ident != ident:
                    _join(clock, other)

    # -- locks.LockWatcher surface -------------------------------------

    def on_acquired(self, lock) -> None:
        ident = threading.get_ident()
        with self._meta:
            _join(self._clock(ident), self._lock_clocks.get(id(lock), {}))

    def on_released(self, lock) -> None:
        ident = threading.get_ident()
        with self._meta:
            clock = self._clock(ident)
            self._lock_clocks[id(lock)] = dict(clock)
            clock[ident] += 1

    # -- the access seam (locks.set_access_tracker target) -------------

    def on_access(self, obj: object, field: str, is_write: bool) -> None:
        ident = threading.get_ident()
        site = _access_site()
        with self._meta:
            clock = self._clock(ident)
            key = (id(obj), field)
            var = self._vars.get(key)
            if var is None:
                var = _VarState(label=f"{type(obj).__name__}.{field}")
                self._vars[key] = var
                self._pins.append(obj)
            if var.retired:
                return
            name = self._names[ident]
            if is_write:
                race = self._check_write(var, ident, clock, name, site)
            else:
                race = self._check_read(var, ident, clock, name, site)
            if race is not None:
                var.retired = True
                self.races.append(race)

    # requires-lock: _meta
    def _check_write(self, var: _VarState, ident: int,
                     clock: Dict[int, int], name: str,
                     site: str) -> Optional[RaceReport]:
        if var.write is not None:
            w_ident, w_clk, w_name, w_site = var.write
            if w_clk > clock.get(w_ident, 0):
                return RaceReport(var.label, "write-write", "write", name,
                                  site, "write", w_name, w_site)
        for r_ident, (r_clk, r_name, r_site) in var.reads.items():
            if r_ident != ident and r_clk > clock.get(r_ident, 0):
                return RaceReport(var.label, "read-write", "write", name,
                                  site, "read", r_name, r_site)
        var.write = (ident, clock[ident], name, site)
        var.reads.clear()
        return None

    # requires-lock: _meta
    def _check_read(self, var: _VarState, ident: int,
                    clock: Dict[int, int], name: str,
                    site: str) -> Optional[RaceReport]:
        if var.write is not None:
            w_ident, w_clk, w_name, w_site = var.write
            if w_clk > clock.get(w_ident, 0):
                return RaceReport(var.label, "write-read", "read", name,
                                  site, "write", w_name, w_site)
        var.reads[ident] = (clock[ident], name, site)
        return None


def _access_site() -> str:
    """file:line of the access being tracked — the first frame below the
    locks/racedetect plumbing."""
    frame = sys._getframe(1)
    while frame is not None:
        filename = frame.f_code.co_filename.replace("\\", "/")
        if not filename.endswith(_PLUMBING_SUFFIXES):
            return f"{filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>"
