"""`python -m tf_operator_tpu.analysis <package>` entry point."""
import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
