"""Deterministic interleaving explorer: the lint's dynamic layer.

The static rules (`analysis/lockgraph.py`) prove properties of the lock
*structure*; they cannot prove that the informer's delete-tombstone
invariant holds under an adversarial watch-vs-relist interleaving, or that
the sharded queue never loses a key when add/add_after/done race a drain.
This module runs those small multi-threaded scenarios under a cooperative
scheduler that OWNS the interleaving: exactly one scenario thread runs at a
time, every `InstrumentedLock` acquire/release (via the
`utils.locks.set_explore_hook` seam) and every explicit
`explore.yield_point()` is a scheduling decision, and the decisions are
drawn from a seeded RNG — so a run is a *schedule*, a failing schedule is a
reproducible artifact (seed + decision trace), and `replay(scenario,
trace)` re-executes it exactly.

What a schedule can catch:

  - invariant violations (`Scenario.check` raises, or a scenario thread
    asserts) — e.g. a tombstoned object resurrected by a stale LIST;
  - deadlocks: every unfinished thread blocked on a lock a peer holds —
    reported with the who-waits-on-whom detail;
  - lock-order inversions: each schedule runs inside
    `locks.instrumented()`, and a non-empty
    `registry.inversion_cycles()` fails the schedule even when the timing
    dodged the actual deadlock;
  - livelock/budget overrun (a schedule exceeding `max_steps` decisions).

Granularity: code under an instrumented lock is atomic *between* its lock
operations (one running thread + the GIL), so lock-free scenario steps
should be separated with explicit `yield_point()` calls at the boundaries
the scenario wants permuted.  Structures serialized by a raw Condition
(e.g. the workqueue — conditions are never instrumented) interleave at
method granularity via those explicit points, which is exactly the
granularity their one-lock design makes meaningful.

Scenario threads may spawn real helper threads (a queue's requeue
dispatcher, say); those run unmanaged on the raw lock path — the explorer
only schedules its own threads, and treats a lock held by a foreign thread
as "retry later", never as a deadlock participant.

Each schedule runs under a fresh `FakeClock` (installed via `clock.use`) so
`clock.now()`-driven logic is schedule-controlled, not wall-time-controlled;
`time.monotonic()` still advances for real, which only matters for
scenarios that encode duration thresholds — keep those thresholds at 0 or
huge, as the scenarios in `tests/test_schedule_explorer.py` do.

Budget: `explore(scenario, schedules=N, seed=S)` runs N independent
schedules.  Tier-1 uses a few hundred per scenario (sub-second each); the
slow tier's `ANALYSIS_EXPLORE_BUDGET` env var scales N up for deep sweeps
(see docs/static-analysis.md).
"""
from __future__ import annotations

import random
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from ..utils import clock, locks
from . import racedetect

# A single scheduling step should be microseconds; a scenario thread that
# fails to reach its next yield point within this many seconds is stuck in
# a genuinely blocking call the explorer cannot control (a raw
# Condition.wait, say) — surfaced as a hard error, not a hang.
STEP_TIMEOUT = 60.0

# Decision budget per schedule: generous for small scenarios, small enough
# that a livelocked schedule fails in milliseconds, not minutes.
DEFAULT_MAX_STEPS = 5000

FAIL_INVARIANT = "invariant"
FAIL_DEADLOCK = "deadlock"
FAIL_EXCEPTION = "exception"
FAIL_INVERSION = "lock-inversion"
FAIL_BUDGET = "budget"
FAIL_RACE = "race"


class InvariantViolation(AssertionError):
    """Raised by `Scenario.check` (or scenario thread asserts) when a
    schedule produced an illegal state."""


class Scenario:
    """One explorable concurrency scenario.  Subclass and override:

      name       identifier used in reports
      build()    fresh state for ONE schedule (never shared across runs)
      threads(state)
                 [(thread name, zero-arg callable)] — the racing bodies
      check(state)
                 post-schedule invariant; raise InvariantViolation
      cleanup(state)
                 optional teardown (stop helper threads etc.)
    """

    name = "scenario"

    def build(self):  # pragma: no cover - interface
        raise NotImplementedError

    def threads(self, state) -> Sequence[Tuple[str, Callable[[], None]]]:  # pragma: no cover
        raise NotImplementedError

    def check(self, state) -> None:
        pass

    def cleanup(self, state) -> None:
        pass


@dataclass
class ScheduleFailure:
    scenario: str
    schedule_index: int   # which schedule (seed offset) failed
    seed: int             # the explore() seed that produced it
    kind: str             # FAIL_* above
    detail: str
    trace: List[str] = field(default_factory=list)  # decision sequence

    def render(self) -> str:
        return (
            f"scenario {self.scenario!r}: {self.kind} at schedule "
            f"#{self.schedule_index} (seed={self.seed}, "
            f"{len(self.trace)} decisions)\n  {self.detail}\n"
            f"  replay trace: {self.trace}"
        )


@dataclass
class ExploreResult:
    scenario: str
    schedules: int  # schedules actually executed
    failure: Optional[ScheduleFailure]

    @property
    def ok(self) -> bool:
        return self.failure is None


# The run currently driving managed threads (exactly one at a time; the
# explorer is not itself reentrant).  Written only from the driving thread
# while every managed thread is parked, so plain writes are safe.
_active_run: Optional["_Run"] = None


def yield_point() -> None:
    """Explicit scheduling point for scenario code: a no-op outside the
    explorer, a yield-to-scheduler inside it.  Put one between the scenario
    steps whose interleavings matter."""
    run = _active_run
    if run is None:
        return
    task = run.current_task()
    if task is not None:
        run.pause(task)


class _AbortSchedule(BaseException):
    """Raised inside parked scenario threads to unwind them (releasing
    their `with` blocks on the way out) once the schedule's verdict is in —
    a deadlocked schedule would otherwise leave threads parked forever.
    BaseException so scenario code's `except Exception` cannot absorb it."""


class _Task:
    def __init__(self, name: str, fn: Callable[[], None]) -> None:
        self.name = name
        self.fn = fn
        self.go = threading.Event()
        self.started = threading.Event()
        self.done = False
        self.error: Optional[BaseException] = None
        self.error_tb = ""
        self.blocked_on = None  # the InstrumentedLock we failed to acquire
        self.thread: Optional[threading.Thread] = None


class _Run(locks.ExploreHook):
    """One schedule's cooperative scheduler + the locks.py hook."""

    def __init__(self, specs: Sequence[Tuple[str, Callable[[], None]]]) -> None:
        self.tasks = [_Task(name, fn) for name, fn in specs]
        self._by_ident: Dict[int, _Task] = {}
        self._ctrl = threading.Event()
        # id(lock) -> (task, hold depth) for locks managed tasks hold
        self._holders: Dict[int, Tuple[_Task, int]] = {}
        self.trace: List[str] = []
        self._aborting = False  # set once the schedule's verdict is in

    # -- hook surface (called from managed scenario threads) -----------

    def manages_current_thread(self) -> bool:
        return threading.get_ident() in self._by_ident

    def current_task(self) -> Optional[_Task]:
        return self._by_ident.get(threading.get_ident())

    def pause(self, task: _Task) -> None:
        """Hand control to the scheduler; resumes when scheduled again.
        During an abort it raises instead, unwinding the thread (with-block
        releases run on the way out, so held locks are returned)."""
        if self._aborting:
            raise _AbortSchedule()
        self._ctrl.set()
        task.go.wait()
        task.go.clear()
        if self._aborting:
            raise _AbortSchedule()

    def cooperative_acquire(self, lock) -> bool:
        task = self._by_ident[threading.get_ident()]
        self.pause(task)  # the acquire itself is a scheduling point
        while True:
            if lock._inner.acquire(blocking=False):
                held = self._holders.get(id(lock))
                depth = held[1] + 1 if held is not None else 1
                self._holders[id(lock)] = (task, depth)
                return True
            task.blocked_on = lock
            self.pause(task)
            task.blocked_on = None

    def on_release(self, lock) -> None:
        task = self._by_ident.get(threading.get_ident())
        if task is None:
            return
        held = self._holders.get(id(lock))
        if held is not None and held[0] is task:
            if held[1] > 1:
                self._holders[id(lock)] = (task, held[1] - 1)
            else:
                del self._holders[id(lock)]
        self.pause(task)  # post-release: let a waiter grab it first

    # -- thread bodies -------------------------------------------------

    def _task_main(self, task: _Task) -> None:
        self._by_ident[threading.get_ident()] = task
        task.started.set()
        task.go.wait()
        task.go.clear()
        try:
            task.fn()
        except _AbortSchedule:
            pass  # deliberate unwind, not a scenario error
        except BaseException as err:  # lint: allow(swallow) — re-raised by the driver as a schedule failure
            task.error = err
            task.error_tb = traceback.format_exc()
        finally:
            self._by_ident.pop(threading.get_ident(), None)
            task.done = True
            self._ctrl.set()

    # -- the drive loop (runs on the exploring thread) -----------------

    def _runnable(self, task: _Task) -> bool:
        if task.done:
            return False
        lock = task.blocked_on
        if lock is None:
            return True
        held = self._holders.get(id(lock))
        # Held by a managed peer: not runnable until that peer releases.
        # Held by a foreign (unmanaged) thread or free: runnable — the task
        # retries its try-acquire when scheduled.
        return held is None or held[0] is task

    def drive(self, choose: Callable[[List[_Task]], _Task],
              max_steps: int) -> Optional[Tuple[str, str]]:
        """Run the schedule; returns (failure kind, detail) or None.
        `choose` picks the next task from the (name-sorted) runnable list;
        every choice is appended to self.trace."""
        for task in self.tasks:
            thread = threading.Thread(
                target=self._task_main, args=(task,),
                name=f"tpujob-explore-{task.name}", daemon=True)
            task.thread = thread
            thread.start()
        for task in self.tasks:
            if not task.started.wait(timeout=STEP_TIMEOUT):
                return ("error", f"thread {task.name} never started")

        steps = 0
        while any(not t.done for t in self.tasks):
            runnable = sorted(
                (t for t in self.tasks if self._runnable(t)),
                key=lambda t: t.name)
            if not runnable:
                detail = "; ".join(
                    f"{t.name} waits on lock {t.blocked_on.name!r} "
                    f"held by "
                    f"{self._holders[id(t.blocked_on)][0].name}"
                    for t in self.tasks
                    if not t.done and t.blocked_on is not None
                )
                return (FAIL_DEADLOCK,
                        f"all live threads blocked: {detail}")
            task = choose(runnable)
            self.trace.append(task.name)
            self._ctrl.clear()
            task.go.set()
            if not self._ctrl.wait(timeout=STEP_TIMEOUT):
                raise RuntimeError(
                    f"scenario thread {task.name} did not reach a yield "
                    f"point within {STEP_TIMEOUT}s — it is stuck in a "
                    "blocking call the explorer cannot schedule (raw "
                    "Condition.wait?); restructure the scenario to poll")
            steps += 1
            if steps > max_steps:
                return (FAIL_BUDGET,
                        f"schedule exceeded {max_steps} decisions "
                        "(livelock, or raise max_steps)")
        return None

    def abort(self) -> None:
        """Unwind every still-parked thread (deadlocked schedules leave
        them blocked forever otherwise).  Idempotent; a no-op when all
        tasks already finished."""
        if all(task.done for task in self.tasks):
            return
        self._aborting = True
        deadline = time.monotonic() + 10.0
        while (any(not task.done for task in self.tasks)
               and time.monotonic() < deadline):
            for task in self.tasks:
                if not task.done:
                    task.go.set()
            time.sleep(0.0005)  # let the unwinding daemon threads run

    def join_all(self) -> None:
        for task in self.tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)


def _run_one_schedule(scenario: Scenario,
                      choose: Callable[[List[_Task]], _Task],
                      max_steps: int,
                      schedule_index: int,
                      seed: int) -> Optional[ScheduleFailure]:
    global _active_run

    def failure(kind: str, detail: str,
                trace: List[str]) -> ScheduleFailure:
        return ScheduleFailure(
            scenario=scenario.name, schedule_index=schedule_index,
            seed=seed, kind=kind, detail=detail, trace=trace)

    # FakeClock built OUTSIDE instrumented(): its internal lock must stay
    # raw, or every clock.now() would add noise decisions to the schedule.
    fake = clock.FakeClock()
    with clock.use(fake):
        with locks.instrumented() as registry:
            # The race detector observes THIS schedule only: installed
            # before build() so constructor writes are recorded on the
            # main thread's clock, removed before the next schedule.
            detector = racedetect.RaceDetector()
            locks.add_lock_watcher(detector)
            previous_tracker = locks.set_access_tracker(detector.on_access)
            try:
                state = scenario.build()
                try:
                    run = _Run(scenario.threads(state))
                    previous_hook = locks.set_explore_hook(run)
                    _active_run = run
                    detector.fork_barrier()  # build() writes HB thread bodies
                    try:
                        outcome = run.drive(choose, max_steps)
                    finally:
                        _active_run = None
                        locks.set_explore_hook(previous_hook)
                        run.abort()  # unparks what a failed schedule left blocked
                        run.join_all()
                        detector.join_barrier()  # thread writes HB check()
                    if outcome is not None:
                        return failure(outcome[0], outcome[1], run.trace)
                    for task in run.tasks:
                        if task.error is not None:
                            kind = (FAIL_INVARIANT
                                    if isinstance(task.error, AssertionError)
                                    else FAIL_EXCEPTION)
                            return failure(
                                kind,
                                f"thread {task.name}: "
                                f"{task.error!r}\n{task.error_tb}",
                                run.trace)
                    if detector.races:
                        # Checked before inversions: an unordered access
                        # pair is the sharper diagnosis when both fire.
                        return failure(
                            FAIL_RACE,
                            "\n".join(r.render() for r in detector.races),
                            run.trace)
                    cycles = registry.inversion_cycles()
                    if cycles:
                        return failure(
                            FAIL_INVERSION,
                            f"lock acquisition-order cycle(s): {cycles}",
                            run.trace)
                    try:
                        scenario.check(state)
                    except AssertionError as err:
                        return failure(FAIL_INVARIANT, str(err) or repr(err),
                                       run.trace)
                    except Exception as err:  # lint: allow(swallow) — converted to a ScheduleFailure the caller raises on
                        # A racy schedule can corrupt state so badly check()
                        # crashes before any assert (KeyError on a dropped
                        # entry, say).  That is still this schedule's verdict
                        # — keep the seed/trace artifact instead of letting a
                        # raw traceback escape without it.
                        return failure(
                            FAIL_EXCEPTION,
                            f"check() raised {err!r}\n{traceback.format_exc()}",
                            run.trace)
                finally:
                    # Unconditional: even when drive() raised (stuck thread),
                    # the scenario's helpers must not leak into the next
                    # schedule — that diagnostic path needs teardown MOST.
                    scenario.cleanup(state)
            finally:
                # The detector must not outlive its schedule: a leaked
                # tracker would charge the NEXT schedule's accesses to
                # this schedule's clocks.
                locks.set_access_tracker(previous_tracker)
                locks.remove_lock_watcher(detector)
    return None


def explore(scenario: Scenario, schedules: int = 200, seed: int = 0,
            max_steps: int = DEFAULT_MAX_STEPS) -> ExploreResult:
    """Run `schedules` independent seeded schedules of `scenario`; stop at
    the first failing one.  Fully deterministic: the same (scenario, seed,
    schedules) triple always explores the same schedules in the same
    order, so a failure's schedule_index and trace are stable artifacts."""
    for index in range(schedules):
        rng = random.Random(seed * 1_000_003 + index)

        def choose(runnable: List[_Task]) -> _Task:
            return runnable[rng.randrange(len(runnable))]

        fail = _run_one_schedule(scenario, choose, max_steps, index, seed)
        if fail is not None:
            return ExploreResult(scenario=scenario.name,
                                 schedules=index + 1, failure=fail)
    return ExploreResult(scenario=scenario.name, schedules=schedules,
                         failure=None)


def replay(scenario: Scenario, trace: Sequence[str],
           max_steps: int = DEFAULT_MAX_STEPS) -> Optional[ScheduleFailure]:
    """Re-execute one recorded decision trace.  Returns the reproduced
    failure, or None if the trace no longer fails (the bug moved)."""
    decisions: Iterator[str] = iter(trace)

    def choose(runnable: List[_Task]) -> _Task:
        try:
            wanted = next(decisions)
        except StopIteration:
            # Past the recorded prefix (the original failed mid-run):
            # deterministic fallback keeps the run finishable.
            return runnable[0]
        for task in runnable:
            if task.name == wanted:
                return task
        # Divergence (code changed since the trace was recorded): keep
        # going deterministically rather than crash the replay.
        return runnable[0]

    return _run_one_schedule(scenario, choose, max_steps,
                             schedule_index=-1, seed=-1)
