"""Compiled-program analysis of the train path: the HLO lint layer.

Every other pass in this package lints control-plane *Python*; this one
inspects the artifact that actually runs on the accelerator — the XLA
program the train step compiles to.  "Automatic Cross-Replica Sharding of
Weight Update" (arXiv:2004.13336) only pays off when the compiler emits
the right collectives (per-shard gradient reduction, one weight-update
all-gather per sharded bucket, no replicated optimizer math), and AMP-style
admission (arXiv:2210.07297) needs a per-device memory model it can trust.
Both are properties of the compiled HLO, not of the source.

The pipeline:

  capture   lower+compile the real train step for a workload on CPU
            virtual devices (XLA_FLAGS=--xla_force_host_platform_device_
            count=N) — shapes come from jax.eval_shape exactly like
            workloads/runner.zero_plan_for_workload, so no training, no
            real init, deterministic output;
  parse     the SPMD module text into a structured model: a collective
            inventory (kind, shapes, byte counts, replica groups,
            sync-vs-async start/done pairing) plus the ENTRY parameter
            shapes (the per-device resident layout of the donated train
            state) and XLA's own buffer-assignment memory stats;
  check     four rules against the job's ZeroShardingPlan (train/zero.py)
            — see docs/static-analysis.md#hlo-rules;
  snapshot  a per-workload collective signature, committed as
            docs/hlo-manifest.json and diff-gated in CI exactly like the
            interface manifest (docs/static-analysis.md#hlo-manifest).

Portability note baked into the rules: XLA's CPU backend legalizes
reduce-scatter as all-reduce + slice and runs every collective
synchronously, so `hlo-plan-drift` accepts either reduction form and
`hlo-sync-collective` only fires for plan entries explicitly marked
overlappable (PlanEntry.overlap — ROADMAP item 4a's contract).

This module keeps its import surface stdlib-only; jax is imported lazily
inside the capture functions, after _ensure_virtual_devices has had a
chance to set the platform env (which must precede the first jax import).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

RULE_HLO_PLAN_DRIFT = "hlo-plan-drift"
RULE_HLO_REPLICATED_OPTSTATE = "hlo-replicated-optstate"
RULE_HLO_SYNC_COLLECTIVE = "hlo-sync-collective"
RULE_HLO_MEMORY_INFEASIBLE = "hlo-memory-infeasible"

HLO_RULES = (
    RULE_HLO_PLAN_DRIFT,
    RULE_HLO_REPLICATED_OPTSTATE,
    RULE_HLO_SYNC_COLLECTIVE,
    RULE_HLO_MEMORY_INFEASIBLE,
)

HLO_MANIFEST_VERSION = 1
HLO_MANIFEST_SCHEMA = "tf-operator-tpu/hlo-manifest"

# The four train-path workloads the lint tier captures (--hlo all).
TRAIN_WORKLOADS = ("lm", "resnet", "bert", "vit")

DEFAULT_DEVICES = 4

COLLECTIVE_KINDS = (
    "all-reduce", "reduce-scatter", "all-gather", "collective-permute",
    "all-to-all",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

_NP_TO_HLO = {
    "bool": "pred", "int8": "s8", "uint8": "u8", "int16": "s16",
    "uint16": "u16", "float16": "f16", "bfloat16": "bf16", "int32": "s32",
    "uint32": "u32", "float32": "f32", "int64": "s64", "uint64": "u64",
    "float64": "f64",
}


# ---------------------------------------------------------------------------
# HLO text parsing

# dtype[dims] with an optional layout suffix: f32[256,64]{1,0}, s32[]
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# %name = <result shapes> <kind>[-start|-done](<operands>), attrs...
_COLLECTIVE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s+=\s+(?P<result>.+?)\s+"
    r"(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")"
    r"(?P<flavor>-start|-done)?\(",
)
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[\d+\]")
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,]*\}(?:,\{[\d,]*\})*)\}")
_OP_NAME_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
_ENTRY_RE = re.compile(r"^ENTRY [^(]*\((?P<params>.*)\)\s*->")

Shape = Tuple[str, Tuple[int, ...]]  # (hlo dtype, dims)


def shape_bytes(shape: Shape) -> int:
    n = 1
    for d in shape[1]:
        n *= d
    return n * _DTYPE_BYTES.get(shape[0], 4)


def _parse_shapes(text: str) -> Tuple[Shape, ...]:
    return tuple(
        (m.group(1), tuple(int(d) for d in m.group(2).split(",") if d))
        for m in _SHAPE_RE.finditer(text)
        if m.group(1) in _DTYPE_BYTES
    )


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective instruction of the compiled (per-device SPMD) module."""

    kind: str                 # all-reduce | reduce-scatter | all-gather | ...
    name: str                 # instruction name, e.g. all-gather.36
    result_shapes: Tuple[Shape, ...]
    operand_shapes: Tuple[Shape, ...]
    bytes_moved: int          # result payload bytes (per device)
    num_groups: int           # replica groups participating
    group_size: int           # devices per group
    asynchronous: bool        # emitted as a -start/-done pair
    op_name: str = ""         # XLA metadata op_name (source attribution)


@dataclasses.dataclass(frozen=True)
class MemoryStats:
    """XLA buffer-assignment sizes (jax Compiled.memory_analysis)."""

    argument_bytes: int
    output_bytes: int
    alias_bytes: int   # outputs aliased onto (donated) arguments
    temp_bytes: int

    @property
    def peak_bytes(self) -> int:
        """Per-device resident estimate at the peak of one step: live
        arguments + temporaries + any un-aliased output buffers."""
        return (self.argument_bytes + self.temp_bytes
                + max(0, self.output_bytes - self.alias_bytes))


@dataclasses.dataclass(frozen=True)
class HloProgram:
    collectives: Tuple[CollectiveOp, ...]
    entry_params: Tuple[Shape, ...]  # per-device ENTRY parameter shapes
    unpaired_starts: int             # -start ops without a matching -done

    def by_kind(self, kind: str) -> Tuple[CollectiveOp, ...]:
        return tuple(op for op in self.collectives if op.kind == kind)


def parse_hlo(text: str) -> HloProgram:
    """Parse a compiled module's text dump into the structured model."""
    collectives: List[CollectiveOp] = []
    starts: Dict[str, int] = {}
    dones = 0
    entry_params: Tuple[Shape, ...] = ()
    for line in text.splitlines():
        entry = _ENTRY_RE.match(line)
        if entry:
            entry_params = _parse_shapes(entry.group("params"))
            continue
        match = _COLLECTIVE_RE.match(line)
        if not match:
            continue
        flavor = match.group("flavor") or ""
        kind = match.group("kind")
        if flavor == "-done":
            dones += 1
            starts[kind] = starts.get(kind, 0) - 1
            continue
        if flavor == "-start":
            starts[kind] = starts.get(kind, 0) + 1
        results = _parse_shapes(match.group("result"))
        operand_text = line[match.end():].split(")", 1)[0]
        operands = _parse_shapes(operand_text)
        if flavor == "-start":
            # a start op's result tuple repeats the operands (the in-flight
            # aliased buffers) before the actual results — drop that echo
            if len(results) >= 2 * len(operands):
                results = results[len(operands):]
        num_groups, group_size = 1, 0
        iota = _IOTA_GROUPS_RE.search(line)
        explicit = _EXPLICIT_GROUPS_RE.search(line)
        if iota:
            num_groups, group_size = int(iota.group(1)), int(iota.group(2))
        elif explicit:
            groups = explicit.group(1)[1:-1].split("},{")
            num_groups = len(groups)
            group_size = max(
                len([x for x in g.split(",") if x]) for g in groups)
        op_name_m = _OP_NAME_RE.search(line)
        collectives.append(CollectiveOp(
            kind=kind,
            name=match.group("name"),
            result_shapes=results,
            operand_shapes=operands,
            bytes_moved=sum(shape_bytes(s) for s in results),
            num_groups=num_groups,
            group_size=group_size,
            asynchronous=flavor == "-start",
            op_name=op_name_m.group(1) if op_name_m else "",
        ))
    unpaired = sum(n for n in starts.values() if n > 0)
    return HloProgram(
        collectives=tuple(collectives),
        entry_params=entry_params,
        unpaired_starts=unpaired,
    )


# ---------------------------------------------------------------------------
# Capture: lower + compile the train step on CPU virtual devices


def _ensure_virtual_devices(num_devices: int) -> None:
    """Arrange for `num_devices` CPU devices.  Must win the race with the
    first jax import — the CLI path calls this before any jax-touching
    work; in-process callers that already initialized jax must have
    enough devices or the capture refuses (it can't re-init the backend).
    """
    if "jax" in sys.modules:
        import jax

        if jax.device_count() < num_devices:
            raise RuntimeError(
                f"HLO capture needs {num_devices} devices but jax is "
                f"already initialized with {jax.device_count()}; run via "
                "`python -m tf_operator_tpu.analysis --hlo ...` (which "
                "sets XLA_FLAGS before jax loads) or set "
                "XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{num_devices}")
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={num_devices}"
        ).strip()


def _hlo_dtype(dtype) -> str:
    import numpy as np

    return _NP_TO_HLO.get(np.dtype(dtype).name, str(dtype))


def _shard_dims(sharding, aval) -> Tuple[int, ...]:
    shape = getattr(aval, "shape", ())
    if sharding is None or not shape:
        return tuple(shape)
    return tuple(sharding.shard_shape(tuple(shape)))


def expected_entry_shapes(shape_tree, sharding_tree) -> Tuple[Shape, ...]:
    """The per-device ENTRY parameter shapes jit must produce for this
    (abstract value, sharding) tree: each leaf's global shape cut down by
    its NamedSharding.  The replicated-optstate rule compares this
    expectation against the parsed ENTRY signature."""
    import jax

    leaves = jax.tree_util.tree_leaves(shape_tree)
    shardings = jax.tree_util.tree_leaves(sharding_tree)
    assert len(leaves) == len(shardings), (len(leaves), len(shardings))
    return tuple(
        (_hlo_dtype(leaf.dtype), _shard_dims(sh, leaf))
        for leaf, sh in zip(leaves, shardings)
    )


class _Box:
    """Opaque (non-pytree) wrapper so plan entries survive tree_leaves."""

    def __init__(self, value):
        self.value = value


@dataclasses.dataclass(frozen=True)
class PlanPair:
    """One sharded plan entry's weight-update transfer: the compiled
    program must gather `shard_dims` back to `base_dims` each step."""

    shard_dims: Tuple[int, ...]
    base_dims: Tuple[int, ...]
    overlap: bool


def plan_update_pairs(plan, param_shapes, base_shardings) -> Tuple[PlanPair, ...]:
    """Per dim-sharded plan entry, the (shard shape -> base-local shape)
    all-gather the ZeRO weight update implies (zero.constrain_to_base)."""
    import jax
    from jax.sharding import NamedSharding

    from ..train import zero as zero_lib

    if plan is None:
        return ()
    ent_tree = zero_lib._map_with_plan(
        param_shapes, plan, lambda leaf, e: _Box(e))
    entries = [b.value for b in jax.tree_util.tree_leaves(ent_tree)]
    leaves = jax.tree_util.tree_leaves(param_shapes)
    bases = jax.tree_util.tree_leaves(base_shardings)
    pairs = []
    for leaf, base, entry in zip(leaves, bases, entries):
        if entry is None or entry.dim is None:
            continue
        shard = NamedSharding(plan.mesh, entry.spec)
        pairs.append(PlanPair(
            shard_dims=_shard_dims(shard, leaf),
            base_dims=_shard_dims(base, leaf),
            overlap=bool(entry.overlap),
        ))
    return tuple(pairs)


@dataclasses.dataclass
class HloCapture:
    """Everything the rules and the manifest need about one compiled
    train-step program."""

    workload: str
    num_devices: int
    zero: bool
    plan: Any                                  # ZeroShardingPlan | None
    program: HloProgram
    memory: Optional[MemoryStats]
    moments_per_param: int
    expected_args: Tuple[Shape, ...]           # planned per-device layout
    update_pairs: Tuple[PlanPair, ...]         # sharded-entry gathers due
    opt_bytes_per_device: int                  # train/zero model estimate
    params_bytes_per_device: int
    anchor_file: str                           # abs path, for suppressions
    anchor_path: str                           # display path for findings
    anchor_line: int
    device_memory_budget_bytes: int = 0        # 0 = no declared budget


def capture_program(step_fn, args_shapes, in_shardings,
                    donate_argnums=(0,)):
    """Lower+compile `step_fn` at `args_shapes` under `in_shardings`;
    return (HloProgram, MemoryStats|None).  The shared trunk for workload
    capture, fixtures, and bench's per-arm signature hashing."""
    import jax

    compiled = jax.jit(
        step_fn, donate_argnums=donate_argnums, in_shardings=in_shardings,
    ).lower(*args_shapes).compile()
    program = parse_hlo(compiled.as_text())
    stats = compiled.memory_analysis()
    memory = None
    if stats is not None:
        memory = MemoryStats(
            argument_bytes=int(stats.argument_size_in_bytes),
            output_bytes=int(stats.output_size_in_bytes),
            alias_bytes=int(stats.alias_size_in_bytes),
            temp_bytes=int(stats.temp_size_in_bytes),
        )
    return program, memory


def _tree_bytes(shape_tree, sharding_tree=None) -> int:
    import jax

    leaves = jax.tree_util.tree_leaves(shape_tree)
    shardings = (jax.tree_util.tree_leaves(sharding_tree)
                 if sharding_tree is not None else [None] * len(leaves))
    total = 0
    for leaf, sh in zip(leaves, shardings):
        dims = _shard_dims(sh, leaf)
        n = 1
        for d in dims:
            n *= d
        total += n * leaf.dtype.itemsize
    return total


# -- per-workload tiny-shape builders ---------------------------------------
# Each returns the pieces of the real workload's construction chain
# (workloads/<name>.py main()) at test-scale shapes: the model, the loss,
# the optimizer factory, and the global batch.  Shapes stay tiny — capture
# is about the *structure* of the compiled program, which is shape-
# independent, not about realistic sizes.


def _build_lm(mesh, num_devices):
    import jax.numpy as jnp

    from ..models.transformer import TransformerConfig, TransformerLM
    from ..train.optim import lm_optimizer
    from ..train.step import lm_loss_fn

    cfg = TransformerConfig(
        vocab_size=128, num_layers=2, num_heads=2, d_model=32, d_ff=64,
        max_len=16, mesh=mesh)
    model = TransformerLM(cfg)
    return dict(
        model=model,
        example=jnp.zeros((2, 16), jnp.int32),
        loss_fn=lm_loss_fn(model.apply),
        make_tx=lambda plan: lm_optimizer(
            1e-3, schedule="constant", warmup_steps=0, total_steps=8,
            zero_plan=plan, mesh=mesh if plan is not None else None),
        batch={"tokens": ((2 * num_devices, 17), jnp.int32)},
        moments_per_param=2,
    )


def _build_resnet(mesh, num_devices):
    import jax.numpy as jnp
    import optax

    from ..models import resnet as resnet_lib
    from ..train.step import classification_loss_fn

    model = resnet_lib.ResNet18(num_classes=8)
    return dict(
        model=model,
        example=jnp.zeros((2, 32, 32, 3), jnp.float32),
        loss_fn=classification_loss_fn(
            model.apply, has_batch_stats=True, model_kwargs={"train": True}),
        make_tx=lambda plan: _zero_wrap(
            optax.sgd(0.1, momentum=0.9), plan, mesh),
        batch={"x": ((num_devices, 32, 32, 3), jnp.float32),
               "label": ((num_devices,), jnp.int32)},
        moments_per_param=1,       # SGD momentum keeps one moment
        has_batch_stats=True,
        init_kwargs={"train": True},
    )


def _build_bert(mesh, num_devices):
    import jax.numpy as jnp
    import optax

    from ..models.transformer import BertEncoder, bert_base_config
    from ..train.step import classification_loss_fn

    cfg = bert_base_config(
        num_layers=2, d_model=32, num_heads=2, d_ff=64, max_len=16,
        mesh=mesh)
    model = BertEncoder(cfg, num_labels=2)

    def apply_logits(variables, tokens, **kw):
        return model.apply(variables, tokens, **kw)["logits"]

    return dict(
        model=model,
        example=jnp.zeros((2, 16), jnp.int32),
        loss_fn=classification_loss_fn(apply_logits),
        make_tx=lambda plan: _zero_wrap(optax.adamw(5e-5), plan, mesh),
        batch={"x": ((num_devices, 16), jnp.int32),
               "label": ((num_devices,), jnp.int32)},
        moments_per_param=2,
    )


def _build_vit(mesh, num_devices):
    import jax.numpy as jnp
    import optax

    from ..models.vit import ViT, vit_base_config
    from ..train.step import classification_loss_fn

    cfg = vit_base_config(
        num_layers=2, num_heads=2, d_model=32, d_ff=128,
        max_len=(16 // 8) ** 2 + 1, mesh=mesh)
    model = ViT(cfg, num_classes=8, patch_size=8)
    return dict(
        model=model,
        example=jnp.zeros((2, 16, 16, 3), jnp.float32),
        loss_fn=classification_loss_fn(model.apply),
        make_tx=lambda plan: _zero_wrap(optax.adamw(3e-4), plan, mesh),
        batch={"x": ((num_devices, 16, 16, 3), jnp.float32),
               "label": ((num_devices,), jnp.int32)},
        moments_per_param=2,
    )


def _zero_wrap(tx, plan, mesh):
    from ..train.zero import zero_shard_optimizer

    return tx if plan is None else zero_shard_optimizer(tx, plan, mesh)


_BUILDERS = {
    "lm": _build_lm,
    "resnet": _build_resnet,
    "bert": _build_bert,
    "vit": _build_vit,
}


def _workload_anchor(name: str) -> Tuple[str, str, int]:
    """(abs file, display path, line of `def main`) for a builtin
    workload — the source location findings anchor to, and where a
    `# lint: allow(hlo-*)` suppression would live."""
    from .. import workloads

    path = os.path.join(
        list(workloads.__path__)[0] if hasattr(workloads, "__path__")
        else os.path.dirname(workloads.__file__), f"{name}.py")
    line = 1
    try:
        with open(path, encoding="utf-8") as fh:
            for i, text in enumerate(fh, start=1):
                if text.startswith("def main("):
                    line = i
                    break
    except OSError:
        pass
    return path, f"workloads/{name}.py", line


def capture_workload(name: str, num_devices: int = DEFAULT_DEVICES,
                     zero: bool = True,
                     overlap: bool = False,
                     device_memory_budget_bytes: int = 0) -> HloCapture:
    """Capture the compiled train step of a builtin workload on
    `num_devices` CPU virtual devices over a {dp: N} mesh.

    `zero` defaults ON — the lint tier's contract is "the four workloads
    with the ZeRO knob on run clean"; callers driving the spec knob pass
    WorkloadContext.zero_shard_weight_update through here (the env is
    parsed in exactly one place, workloads/runner.py).  `overlap=True`
    marks every sharded plan entry overlappable first (PlanEntry.overlap),
    arming hlo-sync-collective.
    """
    if name not in _BUILDERS:
        raise ValueError(
            f"unknown workload {name!r} (expected one of {TRAIN_WORKLOADS})")
    _ensure_virtual_devices(num_devices)
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import batch_sharding, build_mesh
    from ..parallel.tp_rules import make_param_shardings
    from ..train import zero as zero_lib
    from ..train.state import TrainState
    from ..train.step import make_train_step

    mesh = build_mesh({"dp": num_devices})
    spec = _BUILDERS[name](mesh, num_devices)
    model = spec["model"]
    has_batch_stats = spec.get("has_batch_stats", False)
    init_kwargs = spec.get("init_kwargs") or {}

    # shapes via eval_shape — the zero_plan_for_workload path, no real init
    import functools

    variables = jax.eval_shape(
        functools.partial(model.init, **init_kwargs),
        jax.random.PRNGKey(0), spec["example"])
    shapes = variables["params"]
    batch_stats_shape = variables.get("batch_stats") if has_batch_stats else None
    base = make_param_shardings(shapes, mesh)
    plan = None
    if zero:
        plan = zero_lib.build_zero_plan(shapes, mesh, base_specs=base)
        if overlap:
            plan = plan.with_overlap()
    tx = spec["make_tx"](plan)
    opt_shape = jax.eval_shape(tx.init, shapes)

    def opt_sharding_of(leaf, entry):
        return NamedSharding(
            mesh, entry.spec if entry is not None else P())

    if plan is not None:
        opt_sh = zero_lib._map_with_plan(opt_shape, plan, opt_sharding_of)
    else:
        opt_sh = jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, P()), opt_shape)

    def init_state(params):
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params,
            opt_state=tx.init(params), batch_stats=batch_stats_shape,
            apply_fn=model.apply, tx=tx, zero_plan=plan)

    state_shape = jax.eval_shape(init_state, shapes)
    replicate = lambda tree: jax.tree_util.tree_map(  # noqa: E731
        lambda _: NamedSharding(mesh, P()), tree)
    state_sh = TrainState(
        step=NamedSharding(mesh, P()), params=base, opt_state=opt_sh,
        batch_stats=replicate(batch_stats_shape)
        if batch_stats_shape is not None else None,
        apply_fn=model.apply, tx=tx, zero_plan=plan)

    batch_shape = {
        key: jax.ShapeDtypeStruct(dims, dtype)
        for key, (dims, dtype) in spec["batch"].items()
    }
    batch_sh = {key: batch_sharding(mesh) for key in batch_shape}

    step = make_train_step(
        spec["loss_fn"], has_batch_stats=has_batch_stats, jit=False)
    program, memory = capture_program(
        step, (state_shape, batch_shape), (state_sh, batch_sh))

    anchor_file, anchor_path, anchor_line = _workload_anchor(name)
    return HloCapture(
        workload=name,
        num_devices=num_devices,
        zero=zero,
        plan=plan,
        program=program,
        memory=memory,
        moments_per_param=spec["moments_per_param"],
        expected_args=(
            expected_entry_shapes(state_shape, state_sh)
            + expected_entry_shapes(batch_shape, batch_sh)),
        update_pairs=plan_update_pairs(plan, shapes, base),
        opt_bytes_per_device=zero_lib.opt_state_bytes_per_device(
            plan, shapes, moments_per_param=spec["moments_per_param"]),
        params_bytes_per_device=_tree_bytes(shapes, base),
        anchor_file=anchor_file,
        anchor_path=anchor_path,
        anchor_line=anchor_line,
        device_memory_budget_bytes=device_memory_budget_bytes,
    )


def capture_from_file(path: str, num_devices: int = DEFAULT_DEVICES):
    """Load a capture-fixture module (tests/lint_fixtures/bad_hlo_*.py)
    and run its `capture(num_devices)` entry point."""
    import importlib.util

    _ensure_virtual_devices(num_devices)
    name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(f"_hlo_fixture_{name}", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    capture = module.capture(num_devices)
    captures = capture if isinstance(capture, (list, tuple)) else [capture]
    for cap in captures:
        cap.anchor_file = os.path.abspath(path)
        cap.anchor_path = os.path.relpath(path, os.getcwd())
    return list(captures)


# ---------------------------------------------------------------------------
# The four rules


def _multiset(items) -> Dict[Any, int]:
    out: Dict[Any, int] = {}
    for item in items:
        out[item] = out.get(item, 0) + 1
    return out


def _gather_transfers(program: HloProgram, sync_only: bool = False):
    """Multiset of (operand dims -> result dims) pairs served by the
    program's all-gathers (tuple-combined gathers contribute pairwise)."""
    pairs = []
    for op in program.by_kind("all-gather"):
        if sync_only and op.asynchronous:
            continue
        for operand, result in zip(op.operand_shapes, op.result_shapes):
            pairs.append((operand[1], result[1]))
    return _multiset(pairs)


def check_capture(capture: HloCapture,
                  rules: Optional[Sequence[str]] = None) -> List:
    """Run the HLO rules against one capture.  Findings anchor at the
    workload/fixture source (`anchor_path:anchor_line`), where the usual
    `# lint: allow(<rule>)` suppression comment applies."""
    from . import Finding, _Comments

    try:
        with open(capture.anchor_file, encoding="utf-8") as fh:
            comments = _Comments(fh.read())
    except OSError:
        comments = _Comments("")
    findings: List[Finding] = []

    def emit(rule: str, message: str) -> None:
        if rules is not None and rule not in rules:
            return
        if comments.allows(capture.anchor_line, rule):
            return
        findings.append(Finding(
            rule=rule, path=capture.anchor_path.replace(os.sep, "/"),
            line=capture.anchor_line, message=message))

    program = capture.program

    # hlo-plan-drift: every dim-sharded plan entry owes the compiled
    # program one weight-update all-gather (shard shape -> base-local
    # shape), and a plan with anything to reduce owes a gradient
    # reduction (all-reduce, or reduce-scatter where the backend keeps
    # it; XLA:CPU legalizes reduce-scatter to all-reduce + slice).
    if capture.plan is not None and capture.update_pairs:
        supply = _gather_transfers(program)
        missing = []
        for pair, count in _multiset(
                (p.shard_dims, p.base_dims) for p in capture.update_pairs
        ).items():
            short = count - supply.get(pair, 0)
            if short > 0:
                missing.append((pair, short))
        reductions = (len(program.by_kind("all-reduce"))
                      + len(program.by_kind("reduce-scatter")))
        problems = []
        if missing:
            total = sum(short for _, short in missing)
            sample = ", ".join(
                f"{list(pair[0])}->{list(pair[1])}x{short}"
                for pair, short in missing[:3])
            problems.append(
                f"{total} of {len(capture.update_pairs)} sharded plan "
                f"entries have no weight-update all-gather in the compiled "
                f"program (missing {sample})")
        if reductions == 0:
            problems.append(
                "no gradient reduction collective (all-reduce/"
                "reduce-scatter) despite a data-parallel sharding plan")
        if problems:
            emit(RULE_HLO_PLAN_DRIFT,
                 f"compiled HLO disagrees with the ZeroShardingPlan "
                 f"(axis={capture.plan.axis!r}, "
                 f"num_shards={capture.plan.num_shards}): "
                 + "; ".join(problems))

    # hlo-replicated-optstate: the donated train state must enter the
    # program at its planned per-device layout — a moment buffer whose
    # shard shape is absent from the ENTRY signature is materialized
    # dense (the exact failure mode ZeRO exists to remove).
    if capture.plan is not None and capture.expected_args:
        measured = _multiset(program.entry_params)
        missing = []
        for shape, count in _multiset(capture.expected_args).items():
            short = count - measured.get(shape, 0)
            if short > 0:
                missing.append((shape, short))
        if missing:
            sample = ", ".join(
                f"{dtype}{list(dims)}x{short}"
                for (dtype, dims), short in missing[:4])
            emit(RULE_HLO_REPLICATED_OPTSTATE,
                 f"{sum(s for _, s in missing)} expected per-device "
                 f"shard buffer(s) missing from the compiled ENTRY "
                 f"layout ({sample}) — optimizer state is materialized "
                 f"at a larger (replicated) shape than the plan's")

    # hlo-sync-collective: a plan entry marked overlappable whose
    # weight-update gather compiled synchronously (no -start/-done pair)
    # serializes the transfer the plan promised to hide.
    overlap_pairs = [p for p in capture.update_pairs if p.overlap]
    if overlap_pairs:
        sync_supply = _gather_transfers(program, sync_only=True)
        stuck = 0
        for pair, count in _multiset(
                (p.shard_dims, p.base_dims) for p in overlap_pairs).items():
            stuck += min(count, sync_supply.get(pair, 0))
        if stuck:
            emit(RULE_HLO_SYNC_COLLECTIVE,
                 f"{stuck} of {len(overlap_pairs)} overlappable plan "
                 f"entries compiled to a synchronous all-gather "
                 f"(no -start/-done pair) — the weight-update transfer "
                 f"cannot overlap compute")

    # hlo-memory-infeasible: the per-device peak estimate exceeds the
    # declared device budget — this layout OOMs before step 2, so the
    # reconciler rejects it at admission (reason MemoryInfeasible).
    if capture.device_memory_budget_bytes > 0 and capture.memory is not None:
        peak = capture.memory.peak_bytes
        budget = capture.device_memory_budget_bytes
        if peak > budget:
            emit(RULE_HLO_MEMORY_INFEASIBLE,
                 f"estimated per-device peak {peak} B exceeds the "
                 f"declared device budget {budget} B "
                 f"(args={capture.memory.argument_bytes} "
                 f"temp={capture.memory.temp_bytes} "
                 f"out={capture.memory.output_bytes} "
                 f"aliased={capture.memory.alias_bytes}); "
                 f"plan-model optimizer bytes/device="
                 f"{capture.opt_bytes_per_device}")
    return findings


# ---------------------------------------------------------------------------
# Collective signature + manifest (docs/hlo-manifest.json)


def collective_signature(program: HloProgram) -> Dict[str, Any]:
    """Aggregate the collective inventory by kind — the shape of the
    program's communication, stable across renumbering."""
    agg: Dict[str, Dict[str, Any]] = {}
    for op in program.collectives:
        entry = agg.setdefault(op.kind, {
            "count": 0, "syncCount": 0, "totalBytes": 0, "groupSizes": set(),
        })
        entry["count"] += 1
        entry["syncCount"] += 0 if op.asynchronous else 1
        entry["totalBytes"] += op.bytes_moved
        if op.group_size:
            entry["groupSizes"].add(op.group_size)
    return {
        kind: {**entry, "groupSizes": sorted(entry["groupSizes"])}
        for kind, entry in sorted(agg.items())
    }


def signature_hash(signature: Dict[str, Any]) -> str:
    blob = json.dumps(signature, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def collective_signature_from_text(text: str) -> Tuple[Dict[str, Any], str]:
    """(signature, hash) straight from a compiled module's text — the
    bench.py per-arm hook."""
    signature = collective_signature(parse_hlo(text))
    return signature, signature_hash(signature)


def workload_signature(capture: HloCapture) -> Dict[str, Any]:
    signature: Dict[str, Any] = {
        "collectives": collective_signature(capture.program),
        "entryParameterBytes": sum(
            shape_bytes(s) for s in capture.program.entry_params),
        "optStateBytesPerDevice": capture.opt_bytes_per_device,
        "paramsBytesPerDevice": capture.params_bytes_per_device,
    }
    if capture.memory is not None:
        signature["peakBytesPerDevice"] = capture.memory.peak_bytes
    if capture.plan is not None:
        signature["plan"] = {
            "axis": capture.plan.axis,
            "numShards": capture.plan.num_shards,
            "entries": len(capture.plan.entries),
            "shardedEntries": len(capture.update_pairs),
        }
    return signature


def build_manifest(captures: Sequence[HloCapture]) -> Dict[str, Any]:
    workloads = {}
    for capture in captures:
        signature = workload_signature(capture)
        workloads[capture.workload] = {
            "hash": signature_hash(signature),
            "signature": signature,
        }
    return {
        "version": HLO_MANIFEST_VERSION,
        "schema": HLO_MANIFEST_SCHEMA,
        "numDevices": captures[0].num_devices if captures else 0,
        "zeroShardWeightUpdate": bool(captures and captures[0].zero),
        "workloads": workloads,
    }


def render_manifest(manifest: Dict[str, Any]) -> str:
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"


# ---------------------------------------------------------------------------
# Admission-time memory feasibility (pure python — no jax, usable from the
# reconciler without touching an accelerator backend)

BYTES_PER_PARAM = 4          # f32 master weights
BYTES_PER_MOMENT = 4         # moments kept in the param dtype


def admission_peak_lower_bound(model_params: int, *, dp_shards: int = 1,
                               model_parallel: int = 1, zero: bool = False,
                               moments_per_param: int = 2) -> int:
    """Analytic lower bound of the per-device training footprint for a
    declared model size: params + grads (+ moments, ZeRO-divided when the
    weight-update sharding knob is on).  Deliberately a LOWER bound — no
    activations, no temps — so exceeding the budget here is a proof of
    infeasibility, never a false positive.  The compiled-HLO measurement
    (HloCapture.memory.peak_bytes) is the tight companion number; see
    docs/roofline.md's training-memory table."""
    model_parallel = max(1, model_parallel)
    dp_shards = max(1, dp_shards)
    params = model_params * BYTES_PER_PARAM // model_parallel
    grads = model_params * BYTES_PER_PARAM // model_parallel
    moments = (model_params * BYTES_PER_MOMENT * moments_per_param
               // model_parallel)
    if zero:
        moments //= dp_shards
    return params + grads + moments


def admission_memory_check(tpu) -> Optional[str]:
    """None when the declared layout can fit (or declares no budget);
    otherwise the human-readable reason the reconciler attaches to its
    MemoryInfeasible FAILED condition.  `tpu` is an api.types.TPUTopology
    carrying device_memory_gb + model_params."""
    if tpu is None or tpu.device_memory_gb <= 0 or tpu.model_params <= 0:
        return None
    mesh = dict(tpu.mesh or {})
    dp_shards = int(mesh.get("dp", 1))
    model_parallel = 1
    for axis, size in mesh.items():
        if axis != "dp":
            model_parallel *= max(1, int(size))
    need = admission_peak_lower_bound(
        int(tpu.model_params), dp_shards=dp_shards,
        model_parallel=model_parallel,
        zero=bool(tpu.zero_shard_weight_update))
    budget = int(tpu.device_memory_gb * (1024 ** 3))
    if need <= budget:
        return None
    gib = need / (1024 ** 3)
    hint = ("" if tpu.zero_shard_weight_update else
            "; enabling tpu.zeroShardWeightUpdate would shard the "
            "optimizer moments over dp")
    return (f"model with {tpu.model_params} params needs >= {gib:.2f} GiB "
            f"per device (params+grads+moments lower bound, mesh {mesh}) "
            f"but tpu.deviceMemoryGB declares {tpu.device_memory_gb}"
            f"{hint}")


# ---------------------------------------------------------------------------
# CLI driver (python -m tf_operator_tpu.analysis --hlo ...)


def run_hlo(target: str, *, num_devices: Optional[int] = None,
            json_path: Optional[str] = None,
            manifest_path: Optional[str] = None,
            diff_path: Optional[str] = None,
            rules: Optional[Sequence[str]] = None) -> int:
    """The `--hlo` mode: capture, lint, optionally snapshot/diff the
    collective-signature manifest.  Returns the process exit code."""
    from . import write_findings_json
    from .contract import diff_summary

    if num_devices is None:
        num_devices = int(os.environ.get("ANALYSIS_HLO_DEVICES")
                          or DEFAULT_DEVICES)
    _ensure_virtual_devices(num_devices)
    if target == "all":
        names = list(TRAIN_WORKLOADS)
    else:
        names = [target]
    captures: List[HloCapture] = []
    for name in names:
        if name.endswith(".py") or os.sep in name:
            captures.extend(capture_from_file(name, num_devices))
        else:
            captures.append(capture_workload(name, num_devices))
    findings = []
    for capture in captures:
        findings.extend(check_capture(capture, rules=rules))
    for finding in findings:
        print(finding.render())
    print(f"{len(findings)} HLO finding(s) over {len(captures)} compiled "
          f"train-step program(s) [{', '.join(c.workload for c in captures)}]")
    if json_path:
        write_findings_json(json_path, findings, f"hlo:{target}")
        print(f"wrote {json_path}")
    exit_code = 1 if findings else 0
    manifest = build_manifest(captures)
    if manifest_path:
        with open(manifest_path, "w", encoding="utf-8") as fh:
            fh.write(render_manifest(manifest))
        print(f"wrote {manifest_path}")
    if diff_path:
        try:
            with open(diff_path, encoding="utf-8") as fh:
                committed = json.load(fh)
        except (OSError, ValueError) as err:
            print(f"cannot read committed HLO manifest {diff_path}: {err}")
            return 1
        drift = diff_summary(committed, manifest)
        if drift:
            print(f"HLO manifest drift vs {diff_path} "
                  f"({len(drift)} difference(s)):")
            for line in drift:
                print(f"  {line}")
            print("the compiled collective signature changed; if intended, "
                  "regenerate with: python -m tf_operator_tpu.analysis "
                  f"--hlo all --manifest --json {diff_path}")
            exit_code = 1
        else:
            print(f"HLO manifest matches {diff_path}")
    return exit_code
