"""Expectations cache: in-flight create/delete accounting.

Behavioral contract of the reference's ControllerExpectations
(/root/reference/vendor/github.com/kubeflow/common/pkg/controller.v1/expectation/expectation.go):
  - per-key (job/replica-type/kind) atomic add/del counters (expectation.go:176-195)
  - SatisfiedExpectations: true when both counters ≤ 0, or the entry has
    expired (5 min TTL — the informer cache is assumed caught-up by then), or
    no expectations were ever recorded (expectation.go:93-118)
  - observations never drive counters negative in effect: fulfilled
    expectations simply stay satisfied

Why it exists: the controller's view of the cluster (informer cache) lags its
own writes; without this gate a sync racing its own pod creations would create
duplicates (SURVEY.md §7 "hard parts").
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..utils import locks

EXPECTATION_TIMEOUT_SECONDS = 5 * 60.0  # ref: expectation.go:24


def expectation_key(job_key: str, replica_type: str, kind: str) -> str:
    """kind is "pods" or "services" (ref: controller.go:339-358 key format)."""
    return f"{job_key}/{replica_type.lower()}/{kind}"


@dataclass
class _Entry:
    adds: int = 0
    dels: int = 0
    # monotonic, not wall-clock: the TTL is a duration measurement and
    # must not jump with NTP steps (and stays out of clock.now()'s remit)
    timestamp: float = field(default_factory=time.monotonic)

    def fulfilled(self) -> bool:
        return self.adds <= 0 and self.dels <= 0

    def expired(self) -> bool:
        return time.monotonic() - self.timestamp > EXPECTATION_TIMEOUT_SECONDS


class Expectations:
    def __init__(self) -> None:
        self._lock = locks.new_lock("expectations")
        self._entries: dict[str, _Entry] = {}  # guarded-by: _lock

    def expect_creations(self, key: str, count: int) -> None:
        self._set(key, adds=count, dels=0)

    def expect_deletions(self, key: str, count: int) -> None:
        self._set(key, adds=0, dels=count)

    def _set(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            self._entries[key] = _Entry(adds=adds, dels=dels)

    def raise_expectations(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            entry = self._entries.setdefault(key, _Entry(adds=0, dels=0))
            entry.adds += adds
            entry.dels += dels

    def creation_observed(self, key: str) -> None:
        self._lower(key, adds=1, dels=0)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, adds=0, dels=1)

    def _lower(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.adds -= adds
                entry.dels -= dels

    def satisfied(self, key: str) -> bool:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return True
            return entry.fulfilled() or entry.expired()

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)
