"""Scheduling-policy decisions for the gang scheduler.

Pure functions and records — no cluster access, no locks, no clock reads —
so the admission policy is unit-testable and replayable independently of the
scheduler's threading (docs/scheduling-policy.md).  The GangScheduler turns
its pod/slice state into `GangRequest`s and capacity maps, asks this module
*what order to try* (`policy_order`), *who may jump the queue*
(`may_backfill`), and *who to evict* (`select_victims`), then executes the
answers under its own lock.

The queue discipline, in decreasing precedence:

  1. strict priority across classes — a gang never waits behind a
     lower-class gang (api/types.py PRIORITY_CLASSES, highest rank first);
  2. weighted fair share across tenants within a class — tenants are
     served in increasing order of weighted dominant share on chips
     (DRF collapsed to the one fungible dimension the pool accounts);
  3. FIFO within a tenant — earliest gang creation first.

Capacity is multi-dimensional for feasibility even though fair share is
chip-only: a request's `dims` map carries the chip count for plain pods
plus one whole-slice count per distinct slice shape, and backfill/victim
arithmetic is done per dimension.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Hashable, List, Mapping, Optional, Sequence, Tuple

# A capacity dimension: the chip pool, or one (accelerator, topology) slice
# shape.  Values are "how many of that dimension" (chips / whole slices).
CHIPS = "chips"
Dim = Hashable
Dims = Dict[Dim, float]


@dataclass(frozen=True)
class GangPolicy:
    """The spec.scheduling knobs as they reach the scheduler (annotations)."""

    priority_class: str
    rank: int
    tenant: str
    preemptible: bool


@dataclass
class GangRequest:
    """One gang, waiting or admitted, as the policy layer sees it."""

    key: str  # "namespace/group-name"
    namespace: str
    policy: GangPolicy
    dims: Dims = field(default_factory=dict)
    # FIFO position: (earliest member pod creation timestamp, key).  The key
    # tiebreak makes the order total, so two sweeps over the same state make
    # the same decisions regardless of pod-list order.
    created: Tuple[float, str] = (0.0, "")

    @property
    def rank(self) -> int:
        return self.policy.rank

    @property
    def tenant(self) -> str:
        return self.policy.tenant

    def chips(self) -> float:
        return float(self.dims.get(CHIPS, 0.0))


def tenant_weight(weights: Optional[Mapping[str, float]], tenant: str) -> float:
    """A tenant's fair-share weight; unknown tenants weigh 1 (never 0 — a
    zero weight would make the tenant's share infinite and starve it)."""
    if not weights:
        return 1.0
    w = float(weights.get(tenant, 1.0))
    return w if w > 0 else 1.0


def dominant_shares(
    usage: Mapping[str, float],
    capacity: Optional[float],
    weights: Optional[Mapping[str, float]] = None,
) -> Dict[str, float]:
    """Per-tenant weighted dominant share on chips.

    `capacity` None (unlimited pool) falls back to total current usage as
    the denominator — the absolute value is then only meaningful relative
    to other tenants, which is all ordering and the fairness index need.
    """
    denom = capacity if capacity else sum(usage.values())
    if not denom:
        denom = 1.0
    return {
        t: (chips / denom) / tenant_weight(weights, t)
        for t, chips in usage.items()
    }


def policy_order(
    waiting: Sequence[GangRequest],
    usage: Mapping[str, float],
    capacity: Optional[float],
    weights: Optional[Mapping[str, float]] = None,
) -> List[GangRequest]:
    """Order waiting gangs by the queue discipline.

    `usage` is chips currently held per tenant (admitted gangs).  Within a
    class the order is built greedily: pick the head-of-FIFO gang of the
    tenant with the lowest weighted dominant share, then charge that gang's
    chips to the tenant *as if admitted* before picking the next — so a
    burst from one tenant interleaves with other tenants' queues instead of
    monopolizing the class band.  The hypothetical charges carry across
    class bands (admission would, too).
    """
    denom = capacity if capacity else None
    charged: Dict[str, float] = dict(usage)
    ordered: List[GangRequest] = []
    by_rank: Dict[int, Dict[str, List[GangRequest]]] = {}
    for req in waiting:
        by_rank.setdefault(req.rank, {}).setdefault(req.tenant, []).append(req)
    for rank in sorted(by_rank, reverse=True):
        queues = by_rank[rank]
        for fifo in queues.values():
            fifo.sort(key=lambda r: r.created)
        remaining = sum(len(q) for q in queues.values())
        while remaining:
            def share(tenant: str) -> float:
                d = denom or sum(charged.values()) or 1.0
                return (charged.get(tenant, 0.0) / d) / tenant_weight(weights, tenant)

            # min share; FIFO-then-name tiebreak keeps the order total.
            tenant = min(
                (t for t, q in queues.items() if q),
                key=lambda t: (share(t), queues[t][0].created),
            )
            req = queues[tenant].pop(0)
            charged[tenant] = charged.get(tenant, 0.0) + req.chips()
            ordered.append(req)
            remaining -= 1
    return ordered


def may_backfill(
    candidate: Dims,
    blocked_higher: Sequence[Dims],
    free: Dims,
) -> bool:
    """May `candidate` jump ahead of blocked strictly-higher-class gangs?

    Conservative rule: yes only when admitting the candidate provably
    cannot delay any blocked gang's *earliest feasible admission* — for
    every blocked gang H and every dimension d both request, the capacity
    left after the candidate still covers H in full
    (free[d] - candidate[d] >= H[d]).  A dimension absent from `free`
    is unlimited (chip pool with no total) and never blocks.

    This under-approximates (H may also be blocked on a dimension the
    candidate doesn't touch), trading a little backfill throughput for the
    guarantee that backfill can never push a higher-class admission back.
    """
    for higher in blocked_higher:
        for dim, want in candidate.items():
            if want <= 0:
                continue
            h_want = float(higher.get(dim, 0.0))
            if h_want <= 0:
                continue
            avail = free.get(dim)
            if avail is None:
                continue  # unlimited dimension
            if float(avail) - float(want) < h_want:
                return False
    return True


def shortfall(request: Dims, free: Dims) -> Dims:
    """Per-dimension capacity missing to admit `request` right now.
    Empty when the request fits.  Unlimited dimensions never fall short."""
    missing: Dims = {}
    for dim, want in request.items():
        if want <= 0:
            continue
        avail = free.get(dim)
        if avail is None:
            continue
        gap = float(want) - float(avail)
        if gap > 0:
            missing[dim] = gap
    return missing


def select_victims(
    missing: Dims,
    preemptor_rank: int,
    admitted: Sequence[GangRequest],
) -> Optional[List[GangRequest]]:
    """Choose admitted gangs to evict so `missing` is covered.

    Candidates must be preemptible and of strictly lower class than the
    preemptor — equal-class eviction would let two gangs evict each other
    forever, and "never above the preemptor's class" is the documented
    contract.  Victims are taken lowest class first, youngest first within
    a class (the gang with the least sunk work pays), and only gangs that
    actually reduce the remaining shortfall are taken.  Returns None when
    even evicting every candidate leaves a dimension short: a hopeless
    preemption must evict nobody.
    """
    remaining = {d: float(v) for d, v in missing.items() if v > 0}
    if not remaining:
        return []
    candidates = [
        g for g in admitted
        if g.policy.preemptible and g.rank < preemptor_rank
    ]
    # Youngest-first within a class: stable sort by created desc, then rank asc.
    candidates.sort(key=lambda g: g.created, reverse=True)
    candidates.sort(key=lambda g: g.rank)
    victims: List[GangRequest] = []
    for gang in candidates:
        if not remaining:
            break
        helps = False
        for dim in list(remaining):
            freed = float(gang.dims.get(dim, 0.0))
            if freed <= 0:
                continue
            helps = True
            left = remaining[dim] - freed
            if left > 0:
                remaining[dim] = left
            else:
                del remaining[dim]
        if helps:
            victims.append(gang)
    if remaining:
        return None
    return victims


def jain_index(values: Sequence[float]) -> float:
    """Jain's fairness index over per-tenant (weighted) shares: 1.0 when
    perfectly even, 1/n when one tenant holds everything.  Used by the
    BENCH_SCHED_POLICY arm's fairness report."""
    vals = [float(v) for v in values]
    if not vals:
        return 1.0
    square_of_sum = sum(vals) ** 2
    sum_of_squares = sum(v * v for v in vals)
    if sum_of_squares == 0:
        return 1.0
    return square_of_sum / (len(vals) * sum_of_squares)
