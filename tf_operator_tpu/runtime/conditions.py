"""Job condition bookkeeping.

Behavioral contract of the reference's status helpers
(/root/reference/vendor/github.com/kubeflow/common/pkg/util/status.go:35-122):
  - appending a condition replaces any existing one of the same type,
    preserving last_transition_time when (status, reason) are unchanged
  - Running and Restarting are mutually exclusive: setting one removes the other
  - a terminal condition (Succeeded/Failed) flips Running to False rather than
    removing it
"""
from __future__ import annotations

from typing import List, Optional

from ..api.types import JobCondition, JobConditionType, JobStatus
from ..utils import clock


def new_condition(
    ctype: JobConditionType, reason: str, message: str, status: bool = True
) -> JobCondition:
    now = clock.now()
    return JobCondition(
        type=ctype,
        status=status,
        reason=reason,
        message=message,
        last_update_time=now,
        last_transition_time=now,
    )


def get_condition(status: JobStatus, ctype: JobConditionType) -> Optional[JobCondition]:
    for c in status.conditions:
        if c.type == ctype:
            return c
    return None


def has_condition(status: JobStatus, ctype: JobConditionType) -> bool:
    c = get_condition(status, ctype)
    return c is not None and c.status


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.FAILED)


def is_finished(status: JobStatus) -> bool:
    return is_succeeded(status) or is_failed(status)


def is_running(status: JobStatus) -> bool:
    return has_condition(status, JobConditionType.RUNNING)


def update_job_conditions(
    status: JobStatus, ctype: JobConditionType, reason: str, message: str
) -> None:
    """Set condition `ctype` true, with the reference's exclusion rules
    (ref: util/status.go:55-122):
      - a Failed job is sticky: no further condition changes (status.go:76-79)
      - same (status, reason) → no-op (status.go:83-86)
    """
    # Sticky terminal failure (ref: setCondition "Do nothing if JobStatus
    # have failed condition").
    if is_failed(status):
        return
    current = get_condition(status, ctype)
    if current is not None and current.status is True and current.reason == reason:
        return

    cond = new_condition(ctype, reason, message, status=True)

    if ctype in (JobConditionType.SUCCEEDED, JobConditionType.FAILED):
        # Terminal: flip Running to False in place (ref: status.go:99-109).
        running = get_condition(status, JobConditionType.RUNNING)
        if running is not None and running.status:
            running.status = False
            running.last_transition_time = cond.last_transition_time
            running.last_update_time = cond.last_update_time
    elif ctype == JobConditionType.RUNNING:
        _remove_condition(status.conditions, JobConditionType.RESTARTING)
    elif ctype == JobConditionType.RESTARTING:
        _remove_condition(status.conditions, JobConditionType.RUNNING)
    elif ctype == JobConditionType.RESIZING:
        # A resizing gang is down (drained for the new topology document),
        # so Running comes off like it does for Restarting.  The flip back
        # is NOT removal: the reconciler retracts Resizing to status False
        # (reason RunningResized) via clear_condition once the resized gang
        # runs, keeping the transition in the condition list as history.
        _remove_condition(status.conditions, JobConditionType.RUNNING)
    elif ctype == JobConditionType.PREEMPTED:
        # A preempted gang is drained the same way a resizing one is; the
        # reconciler retracts Preempted (reason RunningAfterPreemption) via
        # clear_condition once the requeued gang runs again.
        _remove_condition(status.conditions, JobConditionType.RUNNING)

    _set_condition(status.conditions, cond)


def set_operational_condition(
    status: JobStatus, ctype: JobConditionType, reason: str, message: str
) -> None:
    """Set `ctype` true, bypassing the sticky-Failed rule.  Operational
    markers (Stuck) describe the controller's handling of the job, not the
    job's own state machine, so they must stay writable on a Failed job —
    a failed job whose cleanup sync keeps throwing still quarantines, and
    the condition is the documented signal for it.  Same (status, reason)
    still no-ops so repeated markers don't churn timestamps."""
    current = get_condition(status, ctype)
    if current is not None and current.status is True and current.reason == reason:
        return
    _set_condition(status.conditions, new_condition(ctype, reason, message))


def clear_condition(
    status: JobStatus, ctype: JobConditionType, reason: str, message: str
) -> bool:
    """Flip condition `ctype` to False in place (keeping it in the list as
    history, the way terminal conditions flip Running to False).  Returns
    True when a change was made — callers skip the status write otherwise.
    Used by the self-healing layer to retract Stuck once a quarantined job
    syncs again."""
    current = get_condition(status, ctype)
    if current is None or not current.status:
        return False
    now = clock.now()
    current.status = False
    current.reason = reason
    current.message = message
    current.last_update_time = now
    current.last_transition_time = now
    return True


def _set_condition(conditions: List[JobCondition], cond: JobCondition) -> None:
    current = next((c for c in conditions if c.type == cond.type), None)
    if current is not None:
        if current.status == cond.status and current.reason == cond.reason:
            cond.last_transition_time = current.last_transition_time
        conditions.remove(current)
    conditions.append(cond)


def _remove_condition(conditions: List[JobCondition], ctype: JobConditionType) -> None:
    conditions[:] = [c for c in conditions if c.type != ctype]
