"""ClusterInterface: the seam between the reconcile engine and the substrate.

The reference talks to a Kubernetes apiserver through client-go informers and
clientsets; its unit tests replace those with fake clients + indexer injection
(/root/reference/pkg/controller.v1/tensorflow/controller_test.go:45-66,
pkg/common/util/v1/testutil/).  This framework makes that seam explicit: the
controller only ever sees `ClusterInterface`, and backends provide it:

  - InMemoryCluster   — a synchronous in-process object store with watch
                        callbacks.  It is both the unit-test fake (tests mutate
                        pod phases directly, the analogue of SetPodsStatuses,
                        testutil/pod.go:67-95) and the base for the local
                        process runtime.
  - LocalProcessCluster (runtime/local.py) — pods become real subprocesses;
                        hermetic E2E and real single-host TPU runs.
  - KubernetesCluster  (runtime/k8s.py) — the real apiserver over the wire:
                        typed converters, watch streams with resourceVersion
                        resume/410 relist, leader-election Leases, and
                        pods/binding-based gang admission.

Watch events fire synchronously after the store mutation commits, mirroring
informer delivery order for a single writer.
"""
from __future__ import annotations

import itertools
from enum import Enum
from typing import Callable, Dict, List, Optional, Tuple

from ..api import constants
from ..api.core import Event, ObjectMeta, Pod, PodDisruptionBudget, PodGroup, Service
from ..api.types import JobStatus, TPUJob
from ..utils import clock, locks


class EventType(str, Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


WatchHandler = Callable[[EventType, object], None]


class NotFound(KeyError):
    pass


class EvictionBlocked(RuntimeError):
    """A voluntary eviction was refused because it would violate a PDB."""


class TooManyRequests(RuntimeError):
    """Apiserver throttling (HTTP 429 outside the eviction subresource).

    Transient by definition — the server refused the request before
    processing it, so any verb may be retried.  `retry_after` carries the
    server's Retry-After hint (seconds) when it sent one."""

    def __init__(self, message: str, retry_after: Optional[float] = None) -> None:
        super().__init__(message)
        self.retry_after = retry_after


class AlreadyExists(ValueError):
    pass


class ClusterInterface:
    """Abstract substrate API (create/get/list/update/delete + watch)."""

    # jobs
    def create_job(self, job: TPUJob) -> TPUJob: ...
    def get_job(self, namespace: str, name: str) -> TPUJob: ...
    def list_jobs(self, namespace: Optional[str] = None) -> List[TPUJob]: ...
    def update_job(self, job: TPUJob) -> TPUJob: ...
    def update_job_status(self, namespace: str, name: str, status: JobStatus) -> TPUJob: ...
    def delete_job(self, namespace: str, name: str) -> None: ...

    # pods
    def create_pod(self, pod: Pod) -> Pod: ...
    def get_pod(self, namespace: str, name: str) -> Pod: ...
    def list_pods(self, namespace: Optional[str] = None, selector: Optional[Dict[str, str]] = None) -> List[Pod]: ...
    def update_pod(self, pod: Pod) -> Pod: ...

    def update_pod_status(self, pod: Pod) -> Pod:
        """Write `pod`'s status explicitly (fault injection / fake-kubelet
        paths).  In-process substrates store whole objects so the default
        delegates to update_pod; the k8s backend overrides this because
        status is a separate subresource there and a plain update_pod must
        never write back a phase the kubelet owns."""
        return self.update_pod(pod)

    def delete_pod(self, namespace: str, name: str) -> None: ...

    # services
    def create_service(self, svc: Service) -> Service: ...
    def list_services(self, namespace: Optional[str] = None, selector: Optional[Dict[str, str]] = None) -> List[Service]: ...
    def delete_service(self, namespace: str, name: str) -> None: ...

    # pod groups (gang scheduling)
    def create_podgroup(self, pg: PodGroup) -> PodGroup: ...
    def get_podgroup(self, namespace: str, name: str) -> PodGroup: ...
    def delete_podgroup(self, namespace: str, name: str) -> None: ...

    # PodDisruptionBudgets (the non-Volcano gang mechanism,
    # ref: SyncPdb/DeletePdb, common/job_controller.go:242-316)
    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget: ...
    def get_pdb(self, namespace: str, name: str) -> PodDisruptionBudget: ...
    def update_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget: ...
    def delete_pdb(self, namespace: str, name: str) -> None: ...

    def evict_pod(self, namespace: str, name: str) -> None:
        """Voluntary eviction: delete the pod unless a PDB forbids it."""
        ...

    # events
    def record_event(self, event: Event) -> None: ...
    def list_events(self, namespace: Optional[str] = None, object_name: Optional[str] = None) -> List[Event]: ...

    # watches
    def watch_jobs(self, handler: WatchHandler) -> None: ...
    def watch_pods(self, handler: WatchHandler) -> None: ...
    def watch_services(self, handler: WatchHandler) -> None: ...

    # leases (leader election + shard-lease federation, runtime/shardlease.py)
    def try_acquire_lease(self, name: str, holder: str, ttl: float) -> bool: ...

    def release_lease(self, name: str, holder: str) -> bool:
        """Voluntarily give up `name` if (and only if) `holder` holds it —
        the graceful half of shard handoff; expiry covers crashes.  Returns
        True when a lease was actually released."""
        ...

    def list_leases(self, prefix: str = "") -> Dict[str, str]:
        """Unexpired leases whose name starts with `prefix`, as
        {name: holder} — the shard-lease membership read."""
        ...


def _matches(labels: Dict[str, str], selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class InMemoryCluster(ClusterInterface):
    """Thread-safe in-memory substrate with synchronous watch delivery."""

    def __init__(self) -> None:
        self._lock = locks.new_rlock("cluster")
        self._jobs: Dict[Tuple[str, str], TPUJob] = {}  # guarded-by: _lock
        self._pods: Dict[Tuple[str, str], Pod] = {}  # guarded-by: _lock
        self._services: Dict[Tuple[str, str], Service] = {}  # guarded-by: _lock
        self._podgroups: Dict[Tuple[str, str], PodGroup] = {}  # guarded-by: _lock
        self._pdbs: Dict[Tuple[str, str], PodDisruptionBudget] = {}  # guarded-by: _lock
        self._gang_scheduler_names: set = set()  # guarded-by: _lock
        self._events: List[Event] = []  # guarded-by: _lock
        self._leases: Dict[str, Tuple[str, float]] = {}  # guarded-by: _lock (name -> holder, expiry)
        self._job_handlers: List[WatchHandler] = []
        self._pod_handlers: List[WatchHandler] = []
        self._svc_handlers: List[WatchHandler] = []
        self._uid_counter = itertools.count(1)

    def _assign_uid(self, meta: ObjectMeta, kind: str) -> None:
        if not meta.uid:
            meta.uid = f"{kind}-{next(self._uid_counter)}"

    def _dispatch(self, handlers: List[WatchHandler], etype: EventType, obj) -> None:
        for h in list(handlers):
            h(etype, obj)

    # --- jobs ---

    def create_job(self, job: TPUJob) -> TPUJob:
        key = (job.metadata.namespace, job.metadata.name)
        with self._lock:
            if key in self._jobs:
                raise AlreadyExists(f"tpujob {key} already exists")
            self._assign_uid(job.metadata, "tpujob")
            self._jobs[key] = job
        self._dispatch(self._job_handlers, EventType.ADDED, job)
        return job

    def get_job(self, namespace: str, name: str) -> TPUJob:
        with self._lock:
            try:
                return self._jobs[(namespace, name)]
            except KeyError:
                raise NotFound(f"tpujob {namespace}/{name} not found") from None

    def list_jobs(self, namespace: Optional[str] = None) -> List[TPUJob]:
        with self._lock:
            return [
                j for (ns, _), j in self._jobs.items() if namespace in (None, ns)
            ]

    def update_job(self, job: TPUJob) -> TPUJob:
        key = (job.metadata.namespace, job.metadata.name)
        with self._lock:
            if key not in self._jobs:
                raise NotFound(f"tpujob {key} not found")
            self._jobs[key] = job
        self._dispatch(self._job_handlers, EventType.MODIFIED, job)
        return job

    def update_job_status(self, namespace: str, name: str, status: JobStatus) -> TPUJob:
        """Status-subresource write (ref: status.go:207-225)."""
        with self._lock:
            job = self.get_job(namespace, name)
            job.status = status
        self._dispatch(self._job_handlers, EventType.MODIFIED, job)
        return job

    def delete_job(self, namespace: str, name: str) -> None:
        with self._lock:
            job = self._jobs.pop((namespace, name), None)
        if job is None:
            raise NotFound(f"tpujob {namespace}/{name} not found")
        self._dispatch(self._job_handlers, EventType.DELETED, job)

    # --- pods ---

    def create_pod(self, pod: Pod) -> Pod:
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            if key in self._pods:
                raise AlreadyExists(f"pod {key} already exists")
            self._assign_uid(pod.metadata, "pod")
            self._pods[key] = pod
        if self._requires_gang_binding(pod):
            # Deferred binding: the gang scheduler admits the whole group
            # atomically via bind_pod (runtime/scheduler.py).
            self._dispatch(self._pod_handlers, EventType.ADDED, pod)
            return pod
        pod.metadata.annotations[constants.ANNOTATION_BOUND] = "true"
        self._started_pod(pod)
        self._dispatch(self._pod_handlers, EventType.ADDED, pod)
        return pod

    def register_gang_scheduler(self, scheduler_name: str) -> None:
        """A GangScheduler announces it owns admission for this name."""
        with self._lock:
            self._gang_scheduler_names.add(scheduler_name)

    def _requires_gang_binding(self, pod: Pod) -> bool:
        # Hold a pod unbound only when a registered gang scheduler owns its
        # scheduler name.  A template-set scheduler_name with nobody admitting
        # it (e.g. pdb-mode gangs, custom names) must start normally, not hang
        # Pending forever.  The registry read takes the (re-entrant) lock:
        # create_pod calls this after releasing it, racing a concurrent
        # register_gang_scheduler.
        with self._lock:
            owned = pod.spec.scheduler_name in self._gang_scheduler_names
        return bool(
            pod.spec.scheduler_name
            and owned
            and pod.metadata.annotations.get(constants.GANG_GROUP_ANNOTATION)
        )

    def bind_pod(self, namespace: str, name: str) -> int:
        """Admit a gang-held pod: mark bound and start it.  Returns the
        number of pods newly bound (0 if it was already bound) so callers
        can meter real bindings, not attempts."""
        with self._lock:
            pod = self.get_pod(namespace, name)
            if pod.metadata.annotations.get(constants.ANNOTATION_BOUND) == "true":
                return 0
            pod.metadata.annotations[constants.ANNOTATION_BOUND] = "true"
        self._started_pod(pod)
        self._dispatch(self._pod_handlers, EventType.MODIFIED, pod)
        return 1

    def _started_pod(self, pod: Pod) -> None:
        """Hook for subclasses that actually run pods (LocalProcessCluster)."""

    def get_pod(self, namespace: str, name: str) -> Pod:
        with self._lock:
            try:
                return self._pods[(namespace, name)]
            except KeyError:
                raise NotFound(f"pod {namespace}/{name} not found") from None

    def list_pods(self, namespace=None, selector=None) -> List[Pod]:
        with self._lock:
            return [
                p
                for (ns, _), p in self._pods.items()
                if namespace in (None, ns) and _matches(p.metadata.labels, selector)
            ]

    def update_pod(self, pod: Pod) -> Pod:
        key = (pod.metadata.namespace, pod.metadata.name)
        with self._lock:
            if key not in self._pods:
                raise NotFound(f"pod {key} not found")
            self._pods[key] = pod
        self._dispatch(self._pod_handlers, EventType.MODIFIED, pod)
        return pod

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            pod = self._pods.pop((namespace, name), None)
        if pod is None:
            raise NotFound(f"pod {namespace}/{name} not found")
        self._stopped_pod(pod)
        self._dispatch(self._pod_handlers, EventType.DELETED, pod)

    def _stopped_pod(self, pod: Pod) -> None:
        """Hook for subclasses that actually run pods."""

    # --- services ---

    def create_service(self, svc: Service) -> Service:
        key = (svc.metadata.namespace, svc.metadata.name)
        with self._lock:
            if key in self._services:
                raise AlreadyExists(f"service {key} already exists")
            self._assign_uid(svc.metadata, "svc")
            self._services[key] = svc
        self._dispatch(self._svc_handlers, EventType.ADDED, svc)
        return svc

    def list_services(self, namespace=None, selector=None) -> List[Service]:
        with self._lock:
            return [
                s
                for (ns, _), s in self._services.items()
                if namespace in (None, ns) and _matches(s.metadata.labels, selector)
            ]

    def delete_service(self, namespace: str, name: str) -> None:
        with self._lock:
            svc = self._services.pop((namespace, name), None)
        if svc is None:
            raise NotFound(f"service {namespace}/{name} not found")
        self._dispatch(self._svc_handlers, EventType.DELETED, svc)

    # --- pod groups ---

    def create_podgroup(self, pg: PodGroup) -> PodGroup:
        key = (pg.metadata.namespace, pg.metadata.name)
        with self._lock:
            if key in self._podgroups:
                raise AlreadyExists(f"podgroup {key} already exists")
            self._assign_uid(pg.metadata, "pg")
            self._podgroups[key] = pg
        return pg

    def get_podgroup(self, namespace: str, name: str) -> PodGroup:
        with self._lock:
            try:
                return self._podgroups[(namespace, name)]
            except KeyError:
                raise NotFound(f"podgroup {namespace}/{name} not found") from None

    def delete_podgroup(self, namespace: str, name: str) -> None:
        with self._lock:
            if self._podgroups.pop((namespace, name), None) is None:
                raise NotFound(f"podgroup {namespace}/{name} not found")

    # --- pod disruption budgets ---

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        key = (pdb.metadata.namespace, pdb.metadata.name)
        with self._lock:
            if key in self._pdbs:
                raise AlreadyExists(f"pdb {key} already exists")
            self._assign_uid(pdb.metadata, "pdb")
            self._pdbs[key] = pdb
        return pdb

    def get_pdb(self, namespace: str, name: str) -> PodDisruptionBudget:
        with self._lock:
            try:
                return self._pdbs[(namespace, name)]
            except KeyError:
                raise NotFound(f"pdb {namespace}/{name} not found") from None

    def update_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        key = (pdb.metadata.namespace, pdb.metadata.name)
        with self._lock:
            if key not in self._pdbs:
                raise NotFound(f"pdb {key} not found")
            self._pdbs[key] = pdb
        return pdb

    def delete_pdb(self, namespace: str, name: str) -> None:
        with self._lock:
            if self._pdbs.pop((namespace, name), None) is None:
                raise NotFound(f"pdb {namespace}/{name} not found")

    def evict_pod(self, namespace: str, name: str) -> None:
        """Voluntary eviction honoring PDBs (the k8s Eviction API contract:
        PDBs guard evictions, not direct deletes)."""
        from ..api.core import PodPhase

        with self._lock:
            pod = self.get_pod(namespace, name)
            for pdb in self._pdbs.values():
                if pdb.metadata.namespace != namespace:
                    continue
                if not _matches(pod.metadata.labels, pdb.selector):
                    continue
                healthy = [
                    p
                    for p in self._pods.values()
                    if p.metadata.namespace == namespace
                    and _matches(p.metadata.labels, pdb.selector)
                    and p.status.phase not in (PodPhase.SUCCEEDED, PodPhase.FAILED)
                ]
                # Evicting an already-terminal pod disrupts nothing: only
                # subtract when the target is part of the healthy set.
                after = len(healthy) - (1 if pod in healthy else 0)
                if after < pdb.min_available:
                    raise EvictionBlocked(
                        f"eviction of {namespace}/{name} would violate pdb "
                        f"{pdb.metadata.name}: {after} healthy < "
                        f"minAvailable {pdb.min_available}"
                    )
            # Remove inside the lock: check-then-delete must be atomic or two
            # concurrent evictions can each see the other's victim as still
            # healthy and jointly violate the budget.  Watch dispatch happens
            # outside — handlers take their own locks.
            self._pods.pop((namespace, name), None)
        self._stopped_pod(pod)
        self._dispatch(self._pod_handlers, EventType.DELETED, pod)

    # --- events ---

    def record_event(self, event: Event) -> None:
        with self._lock:
            self._events.append(event)

    def list_events(self, namespace=None, object_name=None) -> List[Event]:
        with self._lock:
            return [
                e
                for e in self._events
                if namespace in (None, e.namespace)
                and object_name in (None, e.object_name)
            ]

    # --- watches ---

    def watch_jobs(self, handler: WatchHandler) -> None:
        self._job_handlers.append(handler)

    def watch_pods(self, handler: WatchHandler) -> None:
        self._pod_handlers.append(handler)

    def watch_services(self, handler: WatchHandler) -> None:
        self._svc_handlers.append(handler)

    # --- leases ---

    def try_acquire_lease(self, name: str, holder: str, ttl: float) -> bool:
        """EndpointsLock analogue (ref: cmd/tf-operator.v1/app/server.go:159-184)."""
        now = clock.now()
        with self._lock:
            current = self._leases.get(name)
            if current is None or current[1] < now or current[0] == holder:
                self._leases[name] = (holder, now + ttl)
                return True
            return False

    def lease_holder(self, name: str) -> Optional[str]:
        with self._lock:
            current = self._leases.get(name)
            if current is None or current[1] < clock.now():
                return None
            return current[0]

    def release_lease(self, name: str, holder: str) -> bool:
        """Delete `name` iff `holder` holds it (expired or not): the
        holder-check keeps a slow ex-owner's late release from deleting a
        lease a successor already re-acquired."""
        with self._lock:
            current = self._leases.get(name)
            if current is not None and current[0] == holder:
                del self._leases[name]
                return True
            return False

    def list_leases(self, prefix: str = "") -> Dict[str, str]:
        now = clock.now()
        with self._lock:
            return {
                n: h for n, (h, expiry) in self._leases.items()
                if n.startswith(prefix) and expiry >= now
            }

    # --- test helpers (the SetPodsStatuses analogue, testutil/pod.go:67-95) ---

    def set_pod_phase(self, namespace: str, name: str, phase, exit_code=None,
                      restart_count: Optional[int] = None) -> Pod:
        from ..api.core import ContainerStatus, PodPhase

        with self._lock:
            pod = self.get_pod(namespace, name)
            pod.status.phase = phase
            if pod.status.start_time is None and phase != PodPhase.PENDING:
                pod.status.start_time = clock.now()
            if not pod.status.container_statuses:
                cname = pod.spec.containers[0].name if pod.spec.containers else "tensorflow"
                pod.status.container_statuses = [ContainerStatus(name=cname)]
            cs = pod.status.container_statuses[0]
            cs.running = phase == PodPhase.RUNNING
            if exit_code is not None:
                cs.terminated = True
                cs.exit_code = exit_code
            if restart_count is not None:
                cs.restart_count = restart_count
        self._dispatch(self._pod_handlers, EventType.MODIFIED, pod)
        return pod
