"""Rate-limited, deduplicating work queue.

Behavioral contract of client-go's workqueue as the reference uses it
(/root/reference/vendor/github.com/kubeflow/common/pkg/controller.v1/common/job_controller.go:129-135):
  - add(key) is idempotent while the key is queued (dedup)
  - a key being processed by one worker is never handed to another; if
    re-added meanwhile it is redelivered after done() (this is what makes
    per-job reconciles single-threaded without explicit locks — SURVEY.md §5
    race-detection notes)
  - add_rate_limited(key) applies per-key exponential backoff
    (base 5ms → max 1000s, client-go defaults)
  - add_after(key, delay) schedules a future enqueue (used to re-arm
    ActiveDeadlineSeconds, ref: pkg/controller.v1/tensorflow/job.go:153-168)
  - forget(key) resets the key's backoff
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional, Set

from ..utils import locks


class ShutDown(Exception):
    pass


class RateLimitingQueue:
    def __init__(
        self, base_delay: float = 0.005, max_delay: float = 1000.0
    ) -> None:
        self._cond = locks.new_condition("workqueue")
        self._queue: deque[str] = deque()  # guarded-by: _cond
        self._dirty: Set[str] = set()  # guarded-by: _cond
        self._processing: Set[str] = set()  # guarded-by: _cond
        self._failures: Dict[str, int] = {}  # guarded-by: _cond
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._shutting_down = False  # guarded-by: _cond
        self._timers: Set[threading.Timer] = set()  # guarded-by: _cond

    # --- core queue semantics ---

    def add(self, key: str) -> None:
        with self._cond:
            if self._shutting_down or key in self._dirty:
                return
            self._dirty.add(key)
            if key not in self._processing:
                self._queue.append(key)
                self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> str:
        """Block until a key is available; raises ShutDown when drained."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue:
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError()
                self._cond.wait(timeout=remaining)
            key = self._queue.popleft()
            self._processing.add(key)
            self._dirty.discard(key)
            return key

    def done(self, key: str) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._queue.append(key)
                self._cond.notify()

    # --- rate limiting ---

    def num_requeues(self, key: str) -> int:
        with self._cond:
            return self._failures.get(key, 0)

    def add_rate_limited(self, key: str) -> None:
        with self._cond:
            failures = self._failures.get(key, 0)
            self._failures[key] = failures + 1
        delay = min(self._base_delay * (2**failures), self._max_delay)
        self.add_after(key, delay)

    def forget(self, key: str) -> None:
        with self._cond:
            self._failures.pop(key, None)

    def add_after(self, key: str, delay: float) -> None:
        if delay <= 0:
            self.add(key)
            return
        timer: threading.Timer = threading.Timer(delay, lambda: self._timer_fire(key, timer))
        timer.name = f"tpujob-requeue-{key}"
        timer.daemon = True
        with self._cond:
            if self._shutting_down:
                return
            self._timers.add(timer)
        timer.start()

    def _timer_fire(self, key: str, timer: threading.Timer) -> None:
        with self._cond:
            self._timers.discard(timer)
        self.add(key)

    # --- observability ---

    def stats(self) -> Dict[str, int]:
        """One consistent snapshot for the health report / watchdog gauges:
        depth (keys deliverable now), dirty (pending incl. redeliveries),
        processing (keys a worker holds), and backoff_tracked (keys with
        rate-limiter state — the set forget() clears)."""
        with self._cond:
            return {
                "depth": len(self._queue),
                "dirty": len(self._dirty),
                "processing": len(self._processing),
                "backoff_tracked": len(self._failures),
            }

    # --- lifecycle ---

    def shutdown(self) -> None:
        with self._cond:
            self._shutting_down = True
            for t in self._timers:
                t.cancel()
            self._timers.clear()
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
