"""Rate-limited, deduplicating work queue — and its sharded composition.

Behavioral contract of client-go's workqueue as the reference uses it
(/root/reference/vendor/github.com/kubeflow/common/pkg/controller.v1/common/job_controller.go:129-135):
  - add(key) is idempotent while the key is queued (dedup)
  - a key being processed by one worker is never handed to another; if
    re-added meanwhile it is redelivered after done() (this is what makes
    per-job reconciles single-threaded without explicit locks — SURVEY.md §5
    race-detection notes)
  - add_rate_limited(key) applies per-key exponential backoff
    (base 5ms → max 1000s, client-go defaults)
  - add_after(key, delay) schedules a future enqueue (used to re-arm
    ActiveDeadlineSeconds, ref: pkg/controller.v1/tensorflow/job.go:153-168)
  - forget(key) resets the key's backoff

Two scale additions over the original single queue (ROADMAP item 1,
docs/informer-cache.md):

  - **Coalesced delayed delivery.**  add_after used to spawn one
    threading.Timer per call; a resync/probation burst at 5k jobs would
    leak thousands of timer threads.  Now each queue keeps one
    earliest-deadline-per-key map served by a single `tpujob-requeue-*`
    dispatcher thread: re-arming a key keeps the soonest pending deadline
    and later ones are absorbed.
  - **ShardedWorkQueue.**  N independent RateLimitingQueues selected by a
    stable key hash (crc32 — process-independent, unlike hash()), each with
    its own worker pool, so a hot tenant's backoff storm cannot serialize
    other tenants behind it.  With shards=1 it routes every call to one
    RateLimitingQueue and preserves the single-queue behavior exactly.

Every queue also records enqueue→dequeue age per delivery (bounded rolling
window) and serves p50/p95/p99 through stats() — the raw material for
`tpujob_queue_latency_seconds` and the /healthz queue section.
"""
from __future__ import annotations

import heapq
import threading
import time
import zlib
from collections import deque
from typing import Dict, List, Optional, Set, Tuple

from ..utils import locks

# Rolling window of per-delivery queue latencies kept per queue: big enough
# for stable p99 under load, small enough to be O(ms) to snapshot.
LATENCY_WINDOW = 1024

LATENCY_QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


class ShutDown(Exception):
    pass


def shard_for(key: str, num_shards: int) -> int:
    """Stable shard index for `key`: crc32, NOT hash() — Python string
    hashing is salted per process, and a key must land on the same shard
    across restarts for backoff/latency accounting to mean anything."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(key.encode("utf-8")) % num_shards


def _percentiles(sample: List[float]) -> Dict[str, float]:
    """Nearest-rank percentiles of `sample` (unsorted ok; empty -> zeros)."""
    if not sample:
        return {name: 0.0 for name, _q in LATENCY_QUANTILES}
    ordered = sorted(sample)
    out = {}
    for name, q in LATENCY_QUANTILES:
        rank = min(len(ordered) - 1, max(0, int(q * len(ordered) + 0.5) - 1))
        out[name] = ordered[rank]
    return out


class RateLimitingQueue:
    def __init__(
        self, base_delay: float = 0.005, max_delay: float = 1000.0,
        name: str = "workqueue",
    ) -> None:
        self.name = name
        self._cond = locks.new_condition("workqueue")
        self._queue: deque[str] = deque()  # guarded-by: _cond
        self._dirty: Set[str] = set()  # guarded-by: _cond
        self._processing: Set[str] = set()  # guarded-by: _cond
        self._failures: Dict[str, int] = {}  # guarded-by: _cond
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._shutting_down = False  # guarded-by: _cond
        # Coalesced delayed delivery: key -> earliest pending monotonic
        # deadline, plus a lazy-deletion heap the dispatcher thread drains.
        # Re-arming a key keeps only the soonest deadline, so resync and
        # probation bursts cost one map entry, not one timer thread each.
        self._pending: Dict[str, float] = {}  # guarded-by: _cond
        self._deadlines: List[Tuple[float, str]] = []  # guarded-by: _cond
        self._dispatcher: Optional[threading.Thread] = None  # guarded-by: _cond
        # The dispatcher parks on this Event (NOT on _cond — it must never
        # steal a notify() aimed at a get() waiter).
        self._timer_wake = threading.Event()
        # enqueue timestamp per deliverable key + rolling latency window
        self._enqueued_at: Dict[str, float] = {}  # guarded-by: _cond
        self._latencies: deque = deque(maxlen=LATENCY_WINDOW)  # guarded-by: _cond
        self._delivered = 0  # guarded-by: _cond

    # --- core queue semantics ---

    def add(self, key: str) -> None:
        with self._cond:
            if self._shutting_down or key in self._dirty:
                return
            self._dirty.add(key)
            if key not in self._processing:
                self._queue.append(key)
                self._enqueued_at.setdefault(key, time.monotonic())
                self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> str:
        """Block until a key is available; raises ShutDown when drained."""
        with self._cond:
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._queue:
                if self._shutting_down:
                    raise ShutDown()
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError()
                self._cond.wait(timeout=remaining)
            key = self._queue.popleft()
            self._processing.add(key)
            self._dirty.discard(key)
            enqueued = self._enqueued_at.pop(key, None)
            if enqueued is not None:
                self._latencies.append(time.monotonic() - enqueued)
            self._delivered += 1
            return key

    def done(self, key: str) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._queue.append(key)
                self._enqueued_at.setdefault(key, time.monotonic())
                self._cond.notify()

    # --- rate limiting ---

    def num_requeues(self, key: str) -> int:
        with self._cond:
            return self._failures.get(key, 0)

    def add_rate_limited(self, key: str) -> None:
        with self._cond:
            failures = self._failures.get(key, 0)
            self._failures[key] = failures + 1
        delay = min(self._base_delay * (2**failures), self._max_delay)
        self.add_after(key, delay)

    def forget(self, key: str) -> None:
        with self._cond:
            self._failures.pop(key, None)

    def add_after(self, key: str, delay: float) -> None:
        if delay <= 0:
            self.add(key)
            return
        deadline = time.monotonic() + delay
        with self._cond:
            if self._shutting_down:
                return
            current = self._pending.get(key)
            if current is not None and current <= deadline:
                return  # an earlier delivery is already pending: coalesce
            self._pending[key] = deadline
            heapq.heappush(self._deadlines, (deadline, key))
            if self._dispatcher is None or not self._dispatcher.is_alive():
                dispatcher = threading.Thread(
                    target=self._requeue_loop,
                    name=f"tpujob-requeue-{self.name}", daemon=True)
                self._dispatcher = dispatcher
                dispatcher.start()
        self._timer_wake.set()

    def _requeue_loop(self) -> None:
        """The one delayed-delivery thread per queue: sleeps until the
        soonest pending deadline, delivers every due key, repeats.  Heap
        entries superseded by an earlier re-arm are skipped lazily (the
        _pending map holds the authoritative deadline per key)."""
        while True:
            self._timer_wake.clear()
            due: List[str] = []
            with self._cond:
                if self._shutting_down:
                    return
                now = time.monotonic()
                while self._deadlines and self._deadlines[0][0] <= now:
                    deadline, key = heapq.heappop(self._deadlines)
                    if self._pending.get(key) == deadline:
                        del self._pending[key]
                        due.append(key)
                timeout = (self._deadlines[0][0] - now
                           if self._deadlines else None)
            for key in due:
                self.add(key)
            self._timer_wake.wait(timeout=timeout)

    # --- observability ---

    def stats(self, include_sample: bool = False) -> Dict[str, object]:
        """One consistent snapshot for the health report / watchdog gauges:
        depth (keys deliverable now), dirty (pending incl. redeliveries),
        processing (keys a worker holds), backoff_tracked (keys with
        rate-limiter state — the set forget() clears), pending_timers
        (coalesced delayed deliveries), delivered (keys handed to workers
        over this queue's lifetime), and enqueue→dequeue latency
        percentiles over the rolling window.  include_sample=True adds the
        raw window under "_sample" (ShardedWorkQueue pools it for the
        aggregate percentiles from the SAME snapshot, so the per-shard and
        pooled numbers in one report cannot disagree)."""
        with self._cond:
            sample = list(self._latencies)
            out: Dict[str, object] = {
                "depth": len(self._queue),
                "dirty": len(self._dirty),
                "processing": len(self._processing),
                "backoff_tracked": len(self._failures),
                "pending_timers": len(self._pending),
                "delivered": self._delivered,
                "latency": _percentiles(sample),
            }
        if include_sample:
            out["_sample"] = sample
        return out

    def purge(self) -> int:
        """Drop every queued, dirty, delayed, and backoff-tracked key —
        shard handoff (runtime/shardlease.py): the keys belong to another
        replica now, and popping them one by one just to skip each on the
        ownership fence would churn the worker pool.  Keys currently being
        processed are left to finish (their done() will not redeliver —
        the dirty mark is gone).  Returns how many keys were dropped."""
        with self._cond:
            dropped = len(self._queue) + len(self._pending)
            self._queue.clear()
            self._dirty.clear()
            self._enqueued_at.clear()
            self._pending.clear()
            self._deadlines.clear()
            self._failures.clear()
        self._timer_wake.set()  # re-evaluate the (now empty) deadline heap
        return dropped

    # --- lifecycle ---

    def shutdown(self) -> None:
        with self._cond:
            self._shutting_down = True
            self._pending.clear()
            self._deadlines.clear()
            self._cond.notify_all()
        self._timer_wake.set()  # release the dispatcher

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)


class ShardedWorkQueue:
    """N independent RateLimitingQueues addressed by stable key hash.

    Keyed operations (add/add_after/add_rate_limited/forget/num_requeues/
    done) route to `shard_for(key)`'s queue, so every per-key invariant of
    the single queue — dedup, never-concurrent processing, redelivery,
    backoff — holds unchanged within a shard, and a key always lands on the
    same shard.  Workers attach to one shard each via `shard(i).get()`:
    there is no cross-shard stealing, which is exactly the isolation
    property (a poisoned tenant saturating shard A's backoff cannot add a
    millisecond of queue latency to shard B).

    With num_shards=1 every call forwards to the single underlying
    RateLimitingQueue — today's behavior, preserved exactly.
    """

    def __init__(self, num_shards: int = 1, base_delay: float = 0.005,
                 max_delay: float = 1000.0) -> None:
        self.num_shards = max(1, int(num_shards))
        self.shards: List[RateLimitingQueue] = [
            RateLimitingQueue(base_delay=base_delay, max_delay=max_delay,
                              name=f"shard-{i}")
            for i in range(self.num_shards)
        ]

    # --- routing ---

    def shard_index(self, key: str) -> int:
        return shard_for(key, self.num_shards)

    def shard(self, index: int) -> RateLimitingQueue:
        return self.shards[index]

    def shard_of(self, key: str) -> RateLimitingQueue:
        return self.shards[self.shard_index(key)]

    # --- keyed operations (single-queue API, routed) ---

    def add(self, key: str) -> None:
        self.shard_of(key).add(key)

    def add_after(self, key: str, delay: float) -> None:
        self.shard_of(key).add_after(key, delay)

    def add_rate_limited(self, key: str) -> None:
        self.shard_of(key).add_rate_limited(key)

    def forget(self, key: str) -> None:
        self.shard_of(key).forget(key)

    def num_requeues(self, key: str) -> int:
        return self.shard_of(key).num_requeues(key)

    def done(self, key: str) -> None:
        self.shard_of(key).done(key)

    def purge_shard(self, index: int) -> int:
        """Drop shard `index`'s queued/delayed keys (lease handoff)."""
        return self.shards[index].purge()

    # --- observability ---

    def stats(self) -> Dict[str, object]:
        """Aggregate of the single-queue keys (so existing consumers keep
        reading the same shape) plus a per-shard breakdown under "shards".
        The aggregate latency percentiles pool every shard's window — the
        fleet-wide view; per-tenant isolation shows up in the per-shard
        numbers."""
        per_shard = [q.stats(include_sample=True) for q in self.shards]
        pooled: List[float] = []
        for s in per_shard:
            pooled.extend(s.pop("_sample"))
        agg: Dict[str, object] = {
            key: sum(s[key] for s in per_shard)
            for key in ("depth", "dirty", "processing", "backoff_tracked",
                        "pending_timers", "delivered")
        }
        agg["latency"] = _percentiles(pooled)
        agg["shards"] = per_shard
        return agg

    # --- lifecycle ---

    def shutdown(self) -> None:
        for q in self.shards:
            q.shutdown()

    def __len__(self) -> int:
        return sum(len(q) for q in self.shards)
