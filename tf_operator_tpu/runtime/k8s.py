"""Kubernetes backend for ClusterInterface — stdlib-only client-go analogue.

The reference drives a real apiserver through client-go clientsets and
shared informers (SURVEY.md §1 L0/L1).  This backend gives the same
controller that capability with no external dependencies: an HTTP(S) client
built on http.client + ssl, kubeconfig/in-cluster auth, typed converters
between the framework's object model (api/core.py) and Kubernetes JSON, and
watch threads translating the apiserver's chunked watch stream into the
ClusterInterface callback contract (the informer analogue,
ref: pkg/common/util/v1/unstructured/informer.go:25-63).

Resource mapping:
  TPUJob      -> apis/tpu-operator.dev/v1 tpujobs (manifests/crd.yaml)
  Pod/Service/Event -> core v1
  PodGroup    -> apis/scheduling.volcano.sh/v1beta1 podgroups (the gang unit
                 the reference stamps, vendor/.../common/pod.go:42-53), or
                 the operator's own CRD group (TPU_PODGROUP_API) when the
                 in-process gang scheduler is the consumer
  PodDisruptionBudget -> apis/policy/v1
  Lease       -> apis/coordination.k8s.io/v1 (leader election; the reference
                 uses an EndpointsLock, server.go:159-184 — Leases are the
                 modern equivalent)
"""
from __future__ import annotations

import datetime as _dt
import json
import math
import os
import random
import ssl
import threading
import time
from http.client import HTTPConnection, HTTPException, HTTPSConnection
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlencode, urlsplit

from ..api import constants, serialization
from ..api.core import (
    Container,
    ContainerPort,
    ContainerStatus,
    EnvVar,
    Event,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodGroup,
    PodPhase,
    PodStatus,
    PodTemplateSpec,
    Service,
    ServicePort,
)
from ..api.types import JobStatus, TPUJob
from ..utils import clock, locks
from ..utils import logging as tpulog
from ..utils import metrics
from .cluster import (
    AlreadyExists,
    ClusterInterface,
    EventType,
    EvictionBlocked,
    NotFound,
    TooManyRequests,
    WatchHandler,
)

log = tpulog.logger_for_key("k8s")

# Volcano's PodGroup group/version — used by --gang-mechanism volcano so a
# cluster-installed Volcano admits our gangs (reference parity,
# vendor/.../common/job_controller.go:211-239).
PODGROUP_API = "scheduling.volcano.sh/v1beta1"
# The operator's own PodGroup CRD (manifests/podgroup.yaml) — used by
# --gang-mechanism podgroup over --runtime k8s, where the in-process
# GangScheduler is the consumer and Volcano need not be installed.
TPU_PODGROUP_API = "scheduling.tpu-operator.dev/v1"
SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


# ---------------------------------------------------------------------------
# time / quantity helpers


def to_rfc3339(ts: Optional[float]) -> Optional[str]:
    if ts is None:
        return None
    return _dt.datetime.fromtimestamp(ts, _dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def to_rfc3339_micro(ts: float) -> str:
    """k8s MicroTime shape ('...T12:00:00.123456Z') — lease renew stamps,
    where flooring to whole seconds would eat the shard-lease ownership
    margin (lease_renew_time round-trips the fraction)."""
    return _dt.datetime.fromtimestamp(ts, _dt.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%S.%fZ"
    )


def from_rfc3339(text: Optional[str]) -> Optional[float]:
    if not text:
        return None
    try:
        dt = _dt.datetime.strptime(text, "%Y-%m-%dT%H:%M:%SZ")
    except ValueError:
        try:
            dt = _dt.datetime.fromisoformat(text.replace("Z", "+00:00"))
        except ValueError:
            return None
    return dt.replace(tzinfo=_dt.timezone.utc).timestamp()


def quantity_to_str(value: float) -> str:
    return str(int(value)) if float(value).is_integer() else str(value)


def lease_renew_time(spec: dict) -> Optional[float]:
    """Parse a coordination.k8s.io Lease spec's renewTime, tolerating both
    fractional ('...T12:00:00.123456Z' — what our writer stamps, k8s
    MicroTime) and fraction-less ('...T12:00:00Z' — what other clients may
    write) timestamps.  The ONE parse both try_acquire_lease and
    list_leases use.  The fraction is KEPT, not floored: the shard-lease
    ownership margin (runtime/shardlease.py) assumes peers compute expiry
    from the instant the holder actually stamped — flooring here would
    make peers see expiry up to 1s early and hand back most of the margin.
    (A naive split('.')[0]+'Z' also turns the fraction-less form into a
    double-Z string that parses to None, silently treating a live peer's
    lease as expired.)"""
    raw = (spec.get("renewTime") or "").rstrip("Z")
    if not raw:
        return None
    base, _, frac = raw.partition(".")
    ts = from_rfc3339(base + "Z")
    if ts is None or not frac:
        return ts
    try:
        return ts + float("0." + frac)
    except ValueError:
        return ts


def quantity_to_float(text: Any) -> float:
    """Parse the k8s quantity subset relevant to device counts ("4", "2k")."""
    s = str(text)
    suffixes = {"k": 1e3, "M": 1e6, "G": 1e9, "m": 1e-3}
    if s and s[-1] in suffixes:
        return float(s[:-1]) * suffixes[s[-1]]
    try:
        return float(s)
    except ValueError:
        return 0.0


# ---------------------------------------------------------------------------
# object converters (core model <-> Kubernetes JSON)


def meta_to_k8s(meta: ObjectMeta) -> Dict[str, Any]:
    out: Dict[str, Any] = {
        "name": meta.name,
        "namespace": meta.namespace,
        "labels": dict(meta.labels),
        "annotations": dict(meta.annotations),
    }
    if meta.uid:
        out["uid"] = meta.uid
    if meta.owner_kind:
        out["ownerReferences"] = [{
            "apiVersion": f"{constants.API_GROUP}/{constants.API_VERSION}",
            "kind": meta.owner_kind,
            "name": meta.owner_name,
            "uid": meta.owner_uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }]
    return out


def meta_from_k8s(raw: Dict[str, Any]) -> ObjectMeta:
    meta = ObjectMeta(
        name=raw.get("name", ""),
        namespace=raw.get("namespace", "default"),
        uid=raw.get("uid", ""),
        labels=dict(raw.get("labels") or {}),
        annotations=dict(raw.get("annotations") or {}),
    )
    created = from_rfc3339(raw.get("creationTimestamp"))
    if created is not None:
        meta.creation_timestamp = created
    meta.deletion_timestamp = from_rfc3339(raw.get("deletionTimestamp"))
    for ref in raw.get("ownerReferences") or []:
        if ref.get("controller"):
            meta.owner_kind = ref.get("kind", "")
            meta.owner_name = ref.get("name", "")
            meta.owner_uid = ref.get("uid", "")
            break
    return meta


_CONTAINER_KNOWN = {"name", "image", "command", "args", "env", "ports", "resources"}


def container_to_k8s(c: Container) -> Dict[str, Any]:
    out: Dict[str, Any] = {"name": c.name, "image": c.image}
    if c.command:
        out["command"] = list(c.command)
    if c.args:
        out["args"] = list(c.args)
    if c.env:
        out["env"] = [{"name": e.name, "value": e.value} for e in c.env]
    if c.ports:
        out["ports"] = [
            {"name": p.name, "containerPort": p.container_port} for p in c.ports
        ]
    if c.resources:
        limits = {k: quantity_to_str(v) for k, v in c.resources.items()}
        out["resources"] = {"limits": limits, "requests": dict(limits)}
    out.update(c.extra)  # volumeMounts, probes, ... passthrough
    return out


def container_from_k8s(raw: Dict[str, Any]) -> Container:
    resources: Dict[str, float] = {}
    for k, v in (raw.get("resources", {}).get("limits") or {}).items():
        resources[k] = quantity_to_float(v)
    return Container(
        name=raw.get("name", ""),
        image=raw.get("image", ""),
        command=list(raw.get("command") or []),
        args=list(raw.get("args") or []),
        env=[EnvVar(e.get("name", ""), e.get("value", ""))
             for e in raw.get("env") or [] if "valueFrom" not in e],
        ports=[ContainerPort(p.get("name", ""), int(p.get("containerPort", 0)))
               for p in raw.get("ports") or []],
        resources=resources,
        extra={k: v for k, v in raw.items() if k not in _CONTAINER_KNOWN},
    )


def pod_to_k8s(pod: Pod) -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "containers": [container_to_k8s(c) for c in pod.spec.containers],
        "restartPolicy": pod.spec.restart_policy or "Never",
    }
    if pod.spec.scheduler_name:
        spec["schedulerName"] = pod.spec.scheduler_name
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.node_name:
        spec["nodeName"] = pod.spec.node_name
    spec.update(pod.spec.extra)  # volumes, affinity, ... passthrough
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": meta_to_k8s(pod.metadata),
        "spec": spec,
    }


def pod_from_k8s(raw: Dict[str, Any]) -> Pod:
    spec_raw = raw.get("spec") or {}
    known = {"containers", "restartPolicy", "schedulerName", "nodeSelector",
             "nodeName"}
    template = PodTemplateSpec(
        containers=[container_from_k8s(c) for c in spec_raw.get("containers") or []],
        restart_policy=spec_raw.get("restartPolicy", ""),
        scheduler_name=spec_raw.get("schedulerName", ""),
        node_selector=dict(spec_raw.get("nodeSelector") or {}),
        node_name=spec_raw.get("nodeName", ""),
        extra={k: v for k, v in spec_raw.items() if k not in known},
    )
    status_raw = raw.get("status") or {}
    statuses: List[ContainerStatus] = []
    for cs in status_raw.get("containerStatuses") or []:
        state = cs.get("state") or {}
        terminated = state.get("terminated")
        statuses.append(ContainerStatus(
            name=cs.get("name", ""),
            restart_count=int(cs.get("restartCount", 0)),
            running="running" in state,
            terminated=terminated is not None,
            exit_code=(int(terminated["exitCode"])
                       if terminated and "exitCode" in terminated else None),
        ))
    try:
        phase = PodPhase(status_raw.get("phase", "Pending"))
    except ValueError:
        phase = PodPhase.UNKNOWN
    return Pod(
        metadata=meta_from_k8s(raw.get("metadata") or {}),
        spec=template,
        status=PodStatus(
            phase=phase,
            container_statuses=statuses,
            start_time=from_rfc3339(status_raw.get("startTime")),
            reason=status_raw.get("reason", ""),
            message=status_raw.get("message", ""),
        ),
    )


def service_to_k8s(svc: Service) -> Dict[str, Any]:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": meta_to_k8s(svc.metadata),
        "spec": {
            "clusterIP": svc.cluster_ip,  # "None" = headless (service.go:303-309)
            "selector": dict(svc.selector),
            "ports": [{"name": p.name or None, "port": p.port} for p in svc.ports],
        },
    }


def service_from_k8s(raw: Dict[str, Any]) -> Service:
    spec_raw = raw.get("spec") or {}
    return Service(
        metadata=meta_from_k8s(raw.get("metadata") or {}),
        selector=dict(spec_raw.get("selector") or {}),
        ports=[ServicePort(p.get("name") or "", int(p.get("port", 0)))
               for p in spec_raw.get("ports") or []],
        cluster_ip=spec_raw.get("clusterIP", "None"),
    )


def job_to_k8s(job: TPUJob) -> Dict[str, Any]:
    data = serialization.job_to_dict(job)
    data["metadata"] = meta_to_k8s(job.metadata)
    return data


def podgroup_to_k8s(pg: PodGroup, api: str = PODGROUP_API) -> Dict[str, Any]:
    return {
        "apiVersion": api,
        "kind": "PodGroup",
        "metadata": meta_to_k8s(pg.metadata),
        "spec": {"minMember": pg.min_member, "queue": pg.queue or "default"},
        "status": {"phase": pg.phase},
    }


def podgroup_from_k8s(raw: Dict[str, Any]) -> PodGroup:
    spec_raw = raw.get("spec") or {}
    return PodGroup(
        metadata=meta_from_k8s(raw.get("metadata") or {}),
        min_member=int(spec_raw.get("minMember", 0)),
        queue=spec_raw.get("queue", ""),
        phase=(raw.get("status") or {}).get("phase", "Pending"),
    )


def pdb_to_k8s(pdb: PodDisruptionBudget) -> Dict[str, Any]:
    return {
        "apiVersion": "policy/v1",
        "kind": "PodDisruptionBudget",
        "metadata": meta_to_k8s(pdb.metadata),
        "spec": {
            "minAvailable": pdb.min_available,
            "selector": {"matchLabels": dict(pdb.selector)},
        },
    }


def pdb_from_k8s(raw: Dict[str, Any]) -> PodDisruptionBudget:
    spec_raw = raw.get("spec") or {}
    return PodDisruptionBudget(
        metadata=meta_from_k8s(raw.get("metadata") or {}),
        min_available=int(spec_raw.get("minAvailable", 0)),
        selector=dict((spec_raw.get("selector") or {}).get("matchLabels") or {}),
    )


def event_to_k8s(event: Event, suffix: str) -> Dict[str, Any]:
    ts = to_rfc3339(event.timestamp)
    return {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {
            "name": f"{event.object_name}.{suffix}",
            "namespace": event.namespace,
        },
        "involvedObject": {
            "kind": event.object_kind,
            "name": event.object_name,
            "namespace": event.namespace,
        },
        "type": event.event_type,
        "reason": event.reason,
        "message": event.message,
        "firstTimestamp": ts,
        "lastTimestamp": ts,
        "count": 1,
        "source": {"component": "tpu-operator"},
    }


def event_from_k8s(raw: Dict[str, Any]) -> Event:
    involved = raw.get("involvedObject") or {}
    return Event(
        object_kind=involved.get("kind", ""),
        object_name=involved.get("name", ""),
        namespace=involved.get("namespace")
        or (raw.get("metadata") or {}).get("namespace", "default"),
        event_type=raw.get("type", "Normal"),
        reason=raw.get("reason", ""),
        message=raw.get("message", ""),
        timestamp=from_rfc3339(raw.get("lastTimestamp")) or clock.now(),
    )


# ---------------------------------------------------------------------------
# transport


class ApiError(RuntimeError):
    def __init__(self, code: int, message: str) -> None:
        super().__init__(f"{code}: {message}")
        self.code = code


class CRDNotInstalledError(RuntimeError):
    """The TPUJob CRD is absent from the cluster (startup check failed)."""


class TransportError(Exception):
    """A connection-level failure (reset, refused, truncated response).

    `before_send` records whether the failure happened before any request
    bytes reached the server — the property that makes retrying a write
    safe.  `original` is the underlying OSError/HTTPException."""

    def __init__(self, original: BaseException, before_send: bool) -> None:
        super().__init__(str(original) or type(original).__name__)
        self.original = original
        self.before_send = before_send


def _raise_for_status(status: int, path: str, message: str,
                      retry_after: Optional[float] = None) -> None:
    """Standard k8s error mapping for an HTTP error status.

    429 is apiserver throttling (retryable TooManyRequests) everywhere
    EXCEPT the eviction subresource, where it is the PDB's semantic answer
    "the budget blocks this eviction" (EvictionBlocked, never retried)."""
    if status == 404:
        raise NotFound(message)
    if status == 409:
        raise AlreadyExists(message)
    if status == 429:
        if path.split("?", 1)[0].endswith("/eviction"):
            raise EvictionBlocked(message)
        raise TooManyRequests(message, retry_after=retry_after)
    raise ApiError(status, message)


def _parse_retry_after(header: Optional[str]) -> Optional[float]:
    if not header:
        return None
    try:
        return max(0.0, float(header))
    except ValueError:
        return None  # HTTP-date form: not worth supporting here


class RetryPolicy:
    """Transient-error retry schedule for KubeClient.request.

    Exponential backoff with full jitter (delay ~ U[0, min(max_delay,
    base_delay * 2^attempt)]), the AWS-recommended shape that decorrelates
    a thundering herd of controllers retrying the same outage.  A 429's
    Retry-After overrides the jittered delay — the server's explicit
    instruction beats the client's guess.  Every request is bounded by a
    per-call `deadline` (seconds) on top of `max_retries`.

    Verb semantics (client-go's shouldRetry, adapted):
      - GET/DELETE are idempotent: retried on connection failures at any
        phase and on retryable statuses (429/500/502/503/504).
      - POST/PUT/PATCH are retried on connection failures only when the
        connection dropped BEFORE any request bytes were sent, plus on 429
        (the apiserver throttles before processing, so nothing applied).
    """

    IDEMPOTENT = frozenset({"GET", "DELETE"})
    RETRYABLE_STATUS = frozenset({429, 500, 502, 503, 504})

    def __init__(self, max_retries: int = 5, base_delay: float = 0.1,
                 max_delay: float = 5.0, deadline: float = 30.0,
                 rng: Optional[random.Random] = None) -> None:
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.deadline = float(deadline)
        self._rng = rng or random.Random()

    def backoff(self, attempt: int, retry_after: Optional[float] = None) -> float:
        if retry_after is not None:
            return retry_after
        cap = min(self.max_delay, self.base_delay * (2.0 ** attempt))
        return self._rng.uniform(0.0, cap)

    def should_retry(self, method: str, *, status: int = 0,
                     connection_error: bool = False,
                     before_send: bool = False) -> bool:
        if connection_error:
            return before_send or method in self.IDEMPOTENT
        if status == 429:
            return True
        return status in self.RETRYABLE_STATUS and method in self.IDEMPOTENT


# Consecutive giveups before the controller's degraded-mode backstop engages
# (widened resync + one ClusterDegraded event; controller/controller.py), and
# consecutive successes required to leave it again.
DEGRADED_GIVEUP_THRESHOLD = 3
DEGRADED_RECOVERY_THRESHOLD = 3


class ClientHealth:
    """Giveup tracker with hysteresis behind the degraded-mode backstop.

    Entry: `threshold` consecutive giveups — a retryable failure that
    exhausted its budget, or an unretryable connection failure.  Any
    completed request (even one answered with an HTTP error — the apiserver
    is alive and talking) resets that streak.

    Exit: `recovery_threshold` consecutive successes.  A single success
    must NOT end the episode: during a read-path outage the controller's
    own writes (the ClusterDegraded event, status patches) still land, and
    exiting on one of them would flap the episode — re-emitting the
    once-per-episode event every few ticks."""

    def __init__(self, threshold: int = DEGRADED_GIVEUP_THRESHOLD,
                 recovery_threshold: int = DEGRADED_RECOVERY_THRESHOLD) -> None:
        self.threshold = int(threshold)
        self.recovery_threshold = int(recovery_threshold)
        self._lock = locks.new_lock("client-health")
        self._consecutive_giveups = 0  # guarded-by: _lock
        self._consecutive_successes = 0  # guarded-by: _lock
        self._degraded = False  # guarded-by: _lock
        self._episodes = 0  # guarded-by: _lock

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_giveups = 0
            if self._degraded:
                self._consecutive_successes += 1
                if self._consecutive_successes >= self.recovery_threshold:
                    self._degraded = False
                    self._consecutive_successes = 0

    def record_giveup(self) -> None:
        with self._lock:
            self._consecutive_successes = 0
            self._consecutive_giveups += 1
            if self._consecutive_giveups >= self.threshold:
                if not self._degraded:
                    self._episodes += 1
                self._degraded = True

    @property
    def consecutive_giveups(self) -> int:
        with self._lock:
            return self._consecutive_giveups

    @property
    def episodes(self) -> int:
        """Total degraded episodes entered over this client's lifetime —
        surfaced in the deep health report so a flapping control plane is
        visible even when the current verdict is healthy."""
        with self._lock:
            return self._episodes

    def degraded(self) -> bool:
        with self._lock:
            return self._degraded


class KubeConfig:
    """Connection parameters for one apiserver."""

    def __init__(self, host: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 cert_file: Optional[str] = None,
                 key_file: Optional[str] = None,
                 verify: bool = True,
                 namespace: str = "default") -> None:
        self.host = host.rstrip("/")
        self.token = token
        self.ca_file = ca_file
        self.cert_file = cert_file
        self.key_file = key_file
        self.verify = verify
        self.namespace = namespace

    @classmethod
    def in_cluster(cls) -> "KubeConfig":
        """Pod-mounted service account (the deployment path,
        manifests/deployment.yaml)."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(os.path.join(SERVICE_ACCOUNT_DIR, "token")) as f:
            token = f.read().strip()
        ns_path = os.path.join(SERVICE_ACCOUNT_DIR, "namespace")
        namespace = "default"
        if os.path.exists(ns_path):
            with open(ns_path) as f:
                namespace = f.read().strip() or "default"
        return cls(
            host=f"https://{host}:{port}",
            token=token,
            ca_file=os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt"),
            namespace=namespace,
        )

    @classmethod
    def from_kubeconfig(cls, path: Optional[str] = None,
                        context: Optional[str] = None) -> "KubeConfig":
        """Parse the kubeconfig subset the reference relies on
        (clientcmd.BuildConfigFromFlags, server.go:94-109): cluster server +
        CA, user token or client cert/key.  Inline (base64) credentials are
        materialized to temp files."""
        import base64
        import tempfile

        import yaml

        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config")
        )
        with open(path) as f:
            cfg = yaml.safe_load(f)

        ctx_name = context or cfg.get("current-context")
        ctx = next(
            c["context"] for c in cfg.get("contexts", [])
            if c.get("name") == ctx_name
        )
        cluster = next(
            c["cluster"] for c in cfg.get("clusters", [])
            if c.get("name") == ctx["cluster"]
        )
        user = next(
            (u["user"] for u in cfg.get("users", [])
             if u.get("name") == ctx.get("user")),
            {},
        )

        def materialize(data_key: str, file_key: str, blob: dict) -> Optional[str]:
            if blob.get(file_key):
                return blob[file_key]
            if blob.get(data_key):
                tmp = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
                tmp.write(base64.b64decode(blob[data_key]))
                tmp.close()
                return tmp.name
            return None

        return cls(
            host=cluster["server"],
            token=user.get("token"),
            ca_file=materialize(
                "certificate-authority-data", "certificate-authority", cluster
            ),
            cert_file=materialize(
                "client-certificate-data", "client-certificate", user
            ),
            key_file=materialize("client-key-data", "client-key", user),
            verify=not cluster.get("insecure-skip-tls-verify", False),
            namespace=ctx.get("namespace", "default"),
        )


class TokenBucket:
    """Client-side request throttle (ref: the RESTClient rate limiter the
    reference configures via --qps/--burst, cmd/tf-operator.v1/app/
    server.go:102-109, app/options/options.go:81-82): refill at `qps`
    tokens/sec up to `burst`; acquire() blocks until a token is free, so a
    hot resync loop back-pressures itself instead of hammering the
    apiserver.  qps<=0 disables throttling (matching client-go, where a
    nil limiter means unthrottled)."""

    def __init__(self, qps: float, burst: int,
                 clock=time.monotonic, sleep=time.sleep) -> None:
        self.qps = float(qps)
        self.burst = max(1, int(burst))
        self._tokens = float(self.burst)
        self._clock = clock
        self._sleep = sleep
        self._last = clock()
        self._lock = locks.new_lock("token-bucket")
        # observability: how often/long callers were actually held back
        self.wait_count = 0
        self.wait_seconds = 0.0

    def acquire(self) -> float:
        """Take one token, sleeping until it accrues; returns seconds waited.

        Reservation-style (like client-go's rate.Limiter): the token is
        debited immediately — possibly into the negative — and the caller
        sleeps off exactly its own deficit.  A recheck loop would be
        vulnerable to a float-precision livelock: a refill landing at
        0.999…9 tokens yields a ~1e-17s sleep that a fake or coarse clock
        absorbs without advancing."""
        if self.qps <= 0:
            return 0.0
        with self._lock:
            now = self._clock()
            self._tokens = min(float(self.burst),
                               self._tokens + (now - self._last) * self.qps)
            self._last = now
            self._tokens -= 1.0
            wait = 0.0 if self._tokens >= 0 else -self._tokens / self.qps
            if wait:
                self.wait_count += 1
                self.wait_seconds += wait
        if wait:
            self._sleep(wait)
        return wait


class KubeClient:
    """Minimal REST client: one connection per request (watches hold theirs
    open), JSON in/out, standard k8s error mapping."""

    def __init__(self, config: KubeConfig, timeout: float = 30.0,
                 qps: float = 5.0, burst: int = 10,
                 retry: Optional[RetryPolicy] = None,
                 fault_injector: Optional[Any] = None,
                 clock=time.monotonic, sleep=time.sleep) -> None:
        self.config = config
        self.timeout = timeout
        self.retry = retry or RetryPolicy()
        # Deterministic fault injection (runtime/faults.py FaultInjector);
        # None in production.  Consulted per attempt in _request_once and
        # per stream in stream_watch.
        self.faults = fault_injector
        self.health = ClientHealth()
        self._clock = clock
        self._sleep = sleep
        # Per-verb request attempts, for the informer's deterministic
        # traffic-collapse assertions (tests and bench read these instead
        # of timing anything).  Mirrored onto tpujob_api_requests_total.
        self._count_lock = locks.new_lock("client-request-counts")
        self.request_counts: Dict[str, int] = {}  # guarded-by: _count_lock
        self.limiter = TokenBucket(qps, burst, clock=clock, sleep=sleep)
        parts = urlsplit(config.host)
        self._scheme = parts.scheme or "https"
        self._netloc = parts.netloc or parts.path
        self._ssl: Optional[ssl.SSLContext] = None
        if self._scheme == "https":
            ctx = ssl.create_default_context(cafile=config.ca_file)
            if not config.verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if config.cert_file:
                ctx.load_cert_chain(config.cert_file, config.key_file)
            self._ssl = ctx

    def _throttle(self) -> None:
        """Take a limiter token; report actual waits on /metrics.  The
        emission lives here, not in TokenBucket, so the bucket stays a
        side-effect-free utility (fake-clock test instances must not
        pollute the production counter) and the metric unambiguously
        means 'this process's apiserver client'."""
        waited = self.limiter.acquire()
        if waited:
            metrics.client_throttle_waits.labels().inc()
            metrics.client_throttle_wait_seconds.labels().inc(waited)

    def _connect(self, timeout: Optional[float]):
        if self._scheme == "https":
            return HTTPSConnection(self._netloc, timeout=timeout, context=self._ssl)
        return HTTPConnection(self._netloc, timeout=timeout)

    def _headers(self, content_type: str = "application/json") -> Dict[str, str]:
        headers = {"Accept": "application/json", "Content-Type": content_type}
        if self.config.token:
            headers["Authorization"] = f"Bearer {self.config.token}"
        return headers

    def request(self, method: str, path: str,
                body: Optional[dict] = None,
                params: Optional[Dict[str, str]] = None,
                content_type: str = "application/json",
                raw: bool = False,
                deadline: Optional[float] = None):
        """JSON request/response with transient-error retries; raw=True
        returns the body as text instead (the pod log endpoint serves
        text/plain, not JSON).

        Retry semantics live in RetryPolicy: exponential backoff with full
        jitter, Retry-After honored on 429, writes only re-sent when the
        connection failed before any bytes went out, everything bounded by
        `deadline` seconds (default RetryPolicy.deadline).  Retries and
        giveups are counted on tpujob_api_retries_total /
        tpujob_api_giveups_total, and giveups feed the degraded-mode
        backstop via ClientHealth."""
        if params:
            path = f"{path}?{urlencode(params)}"
        payload = json.dumps(body) if body is not None else None
        budget = self.retry.deadline if deadline is None else deadline
        deadline_at = self._clock() + budget
        attempt = 0
        while True:
            try:
                result = self._request_once(method, path, payload,
                                            content_type, raw)
            except (NotFound, AlreadyExists, EvictionBlocked):
                # The server answered; these are semantic outcomes, not
                # transport trouble.
                self.health.record_success()
                raise
            except TooManyRequests as err:
                self._backoff_or_giveup(method, path, attempt, deadline_at,
                                        err, retry_after=err.retry_after)
            except ApiError as err:
                if not self.retry.should_retry(method, status=err.code):
                    self.health.record_success()
                    raise
                self._backoff_or_giveup(method, path, attempt, deadline_at, err)
            except TransportError as err:
                if not self.retry.should_retry(
                        method, connection_error=True,
                        before_send=err.before_send):
                    # Unretryable by policy (write with bytes on the wire):
                    # still a giveup — the control plane dropped us.
                    metrics.api_giveups.labels().inc()
                    self.health.record_giveup()
                    raise err.original
                self._backoff_or_giveup(method, path, attempt, deadline_at,
                                        err.original)
            else:
                self.health.record_success()
                return result
            attempt += 1

    def _backoff_or_giveup(self, method: str, path: str, attempt: int,
                           deadline_at: float, err: BaseException,
                           retry_after: Optional[float] = None) -> None:
        """Sleep one backoff step, or raise `err` when the budget is gone."""
        delay = self.retry.backoff(attempt, retry_after)
        if attempt >= self.retry.max_retries or self._clock() + delay > deadline_at:
            metrics.api_giveups.labels().inc()
            self.health.record_giveup()
            log.warning("giving up on %s %s after %d attempt(s): %s",
                        method, path, attempt + 1, err)
            raise err
        metrics.api_retries.labels().inc()
        log.debug("retrying %s %s in %.3fs (attempt %d): %s",
                  method, path, delay, attempt + 1, err)
        self._sleep(delay)

    def _request_once(self, method: str, path: str, payload: Optional[str],
                      content_type: str, raw: bool):
        """One attempt: throttle, (optionally) inject a fault, do the HTTP
        round-trip, map the status.  Connect is issued separately from send
        so TransportError.before_send is accurate — the distinction that
        makes write retries safe."""
        self._throttle()
        self._count_request(method)
        if self.faults is not None:
            fault = self.faults.for_request(method, path)
            if fault is not None:
                self._apply_fault(fault, method, path)
        conn = self._connect(self.timeout)
        try:
            try:
                conn.connect()
            except OSError as err:
                raise TransportError(err, before_send=True) from err
            try:
                conn.request(method, path, body=payload,
                             headers=self._headers(content_type))
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, HTTPException) as err:
                raise TransportError(err, before_send=False) from err
            if resp.status >= 400:
                _raise_for_status(
                    resp.status, path, _error_message(data),
                    retry_after=_parse_retry_after(resp.getheader("Retry-After")),
                )
            if raw:
                return data.decode(errors="replace")
            return json.loads(data) if data else {}
        finally:
            conn.close()

    def _count_request(self, verb: str) -> None:
        with self._count_lock:
            self.request_counts[verb] = self.request_counts.get(verb, 0) + 1
        metrics.api_requests.labels(verb).inc()

    def request_count(self, *verbs: str) -> int:
        """Total request attempts issued, optionally restricted to `verbs`
        (e.g. request_count("GET") = reads the informer should have
        collapsed).  Watch streams are counted under "WATCH"."""
        with self._count_lock:
            if not verbs:
                return sum(self.request_counts.values())
            return sum(self.request_counts.get(v, 0) for v in verbs)

    def _apply_fault(self, fault: Any, method: str, path: str) -> None:
        """Translate an injected fault into the exact failure shape the real
        transport produces, so the retry policy can't tell them apart."""
        if fault.kind == "latency":
            self._sleep(fault.latency)
            return  # proceed with the real request after the stall
        if fault.kind == "reset":
            raise TransportError(
                ConnectionResetError(
                    f"injected connection reset ({method} {path})"),
                before_send=fault.before_send,
            )
        _raise_for_status(fault.status, path, fault.message,
                          retry_after=fault.retry_after)

    def stream_watch(self, path: str, params: Dict[str, str],
                     stop: threading.Event,
                     conn_registry: Optional[List[Any]] = None) -> "Any":
        """Yield watch events from a chunked watch response until the server
        closes the stream or `stop` is set.  `conn_registry`, when given,
        receives the live connection so the owner can close it to unblock a
        reader parked in recv (watch connections have no timeout)."""
        params = dict(params, watch="true")
        full = f"{path}?{urlencode(params)}"
        # Establishing a watch costs one token (client-go throttles watch
        # creation the same way); the long-lived stream itself is free.
        self._throttle()
        self._count_request("WATCH")
        events_left: Optional[int] = None
        if self.faults is not None:
            fault = self.faults.for_watch(path)
            if fault is not None:
                if fault.kind == "gone":
                    # 410 Expired: forces the owner's relist machinery.
                    raise ApiError(410, fault.message)
                if fault.kind == "watch_drop":
                    # Serve a few events, then end the stream mid-flight as
                    # a dying connection would.
                    events_left = max(1, fault.after_events)
        conn = self._connect(None)  # watches are long-lived
        if conn_registry is not None:
            conn_registry.append(conn)
        try:
            conn.request("GET", full, headers=self._headers())
            resp = conn.getresponse()
            if resp.status >= 400:
                raise ApiError(resp.status, _error_message(resp.read()))
            buf = b""
            while not stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if line.strip():
                        yield json.loads(line)
                        if events_left is not None:
                            events_left -= 1
                            if events_left <= 0:
                                return  # injected mid-stream drop
        finally:
            if conn_registry is not None:
                try:
                    conn_registry.remove(conn)
                except ValueError:
                    pass
            conn.close()


def _error_message(payload: bytes) -> str:
    try:
        return json.loads(payload).get("message", payload.decode(errors="replace"))
    except (ValueError, AttributeError):
        return payload.decode(errors="replace")


# ---------------------------------------------------------------------------
# the ClusterInterface backend


class _WatchState:
    """Supervision record for one watch stream: the heartbeat timestamp the
    staleness detector reads, the live connections a kick force-closes, and
    everything needed to respawn the thread if it ever dies."""

    def __init__(self, key: str, path: str, convert: Callable[[dict], Any],
                 handlers: List["WatchHandler"]) -> None:
        self.key = key
        self.path = path
        self.convert = convert
        self.handlers = handlers
        # monotonic time of the last sign of life: a relist completing, an
        # event line, or a bookmark.  Float writes are atomic under the GIL;
        # readers tolerate a torn-by-one-tick view.
        self.last_event = time.monotonic()
        self.conns: List[Any] = []


class KubernetesCluster(ClusterInterface):
    """Drives a real apiserver; the controller above it is unchanged."""

    def __init__(self, config: Optional[KubeConfig] = None,
                 namespace: Optional[str] = None,
                 podgroup_api: str = PODGROUP_API,
                 qps: float = 5.0, burst: int = 10,
                 retry: Optional[RetryPolicy] = None,
                 fault_injector: Optional[Any] = None) -> None:
        self.config = config or default_config()
        self._stop = threading.Event()
        # Stop-aware backoff: retry sleeps return early once close() sets
        # _stop, so watch threads mid-backoff wind down in milliseconds at
        # teardown instead of sleeping out their full retry schedule.
        self.client = KubeClient(self.config, qps=qps, burst=burst,
                                 retry=retry, fault_injector=fault_injector,
                                 sleep=self._stop.wait)
        # None = all namespaces (the reference's default, options.go:57-60)
        self.namespace = namespace
        self._job_handlers: List[WatchHandler] = []
        self._pod_handlers: List[WatchHandler] = []
        self._service_handlers: List[WatchHandler] = []
        self._watch_threads: Dict[str, threading.Thread] = {}
        self._watch_state: Dict[str, _WatchState] = {}
        self._event_seq = 0
        self._identity = f"tpu-operator-{os.getpid()}"
        # Which API group PodGroups live in: Volcano's (default, reference
        # parity) or the operator's own CRD for the in-process gang path.
        self.podgroup_api = podgroup_api
        # (ns, name) pods already warned FailedScheduling this dry spell —
        # the 30s retry sweep must not mint a new Event object per attempt.
        self._sched_warned: set = set()

    @property
    def health(self) -> ClientHealth:
        """Consecutive-giveup tracker the controller's degraded-mode
        backstop polls (duck-typed: substrates without it are never
        considered degraded)."""
        return self.client.health

    # -- paths --

    def _ns(self, namespace: Optional[str]) -> str:
        return namespace or self.namespace or self.config.namespace

    def _job_path(self, namespace: Optional[str], name: str = "") -> str:
        base = (f"/apis/{constants.API_GROUP}/{constants.API_VERSION}"
                f"/namespaces/{self._ns(namespace)}/{constants.PLURAL}")
        return f"{base}/{name}" if name else base

    @staticmethod
    def _core_path(namespace: str, kind: str, name: str = "") -> str:
        base = f"/api/v1/namespaces/{namespace}/{kind}"
        return f"{base}/{name}" if name else base

    # -- startup checks --

    def check_crd_exists(self) -> None:
        """Fail fast with an actionable error when the TPUJob CRD isn't
        installed (ref: checkCRDExists, cmd/tf-operator.v1/app/
        server.go:215-227): without this, a missing CRD surfaces as opaque
        404s from the middle of the reconcile loop."""
        ns = self.namespace or self.config.namespace
        base = f"/apis/{constants.API_GROUP}/{constants.API_VERSION}"
        path = (f"{base}/namespaces/{ns}/{constants.PLURAL}" if ns
                else f"{base}/{constants.PLURAL}")
        try:
            self.client.request("GET", path, params={"limit": "1"})
        except NotFound as e:
            raise CRDNotInstalledError(
                f"TPUJob CRD ({constants.PLURAL}.{constants.API_GROUP} "
                f"{constants.API_VERSION}) is not installed on this cluster "
                f"(LIST {path} -> 404: {e}); install it with "
                "`kubectl apply -f manifests/crd.yaml` and restart the "
                "operator") from e

    # -- jobs --

    def create_job(self, job: TPUJob) -> TPUJob:
        raw = self.client.request(
            "POST", self._job_path(job.metadata.namespace), body=job_to_k8s(job)
        )
        return serialization.job_from_dict(raw)

    def get_job(self, namespace: str, name: str) -> TPUJob:
        return serialization.job_from_dict(
            self.client.request("GET", self._job_path(namespace, name))
        )

    def list_jobs(self, namespace: Optional[str] = None) -> List[TPUJob]:
        if namespace or self.namespace:
            raw = self.client.request("GET", self._job_path(namespace))
        else:
            raw = self.client.request(
                "GET",
                f"/apis/{constants.API_GROUP}/{constants.API_VERSION}/{constants.PLURAL}",
            )
        return [serialization.job_from_dict(item) for item in raw.get("items", [])]

    def update_job(self, job: TPUJob) -> TPUJob:
        # CR updates require metadata.resourceVersion; TPUJob doesn't carry
        # one, so read-inject-PUT with one retry on a write conflict.
        path = self._job_path(job.metadata.namespace, job.metadata.name)
        body = job_to_k8s(job)
        for attempt in (0, 1):
            current = self.client.request("GET", path)
            body["metadata"]["resourceVersion"] = (
                current.get("metadata") or {}
            ).get("resourceVersion", "")
            try:
                raw = self.client.request("PUT", path, body=body)
                return serialization.job_from_dict(raw)
            except AlreadyExists:  # 409 conflict: refetch and retry once
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def update_job_status(self, namespace: str, name: str, status: JobStatus) -> TPUJob:
        # Status subresource write (ref: UpdateJobStatusInApiServer,
        # status.go:207-225); merge-patch avoids read-modify-write races.
        raw = self.client.request(
            "PATCH", f"{self._job_path(namespace, name)}/status",
            body={"status": serialization.status_to_dict(status)},
            content_type="application/merge-patch+json",
        )
        return serialization.job_from_dict(raw)

    def patch_job(self, namespace: str, name: str, patch: Dict[str, Any]) -> TPUJob:
        """JSON-merge-patch a TPUJob (the reference SDK's patch semantics,
        tf_job_client.py:114-136) — a single apiserver-side merge, so
        concurrent patches to different fields can't lose updates the way
        read-modify-write PUT does."""
        raw = self.client.request(
            "PATCH", self._job_path(namespace, name), body=patch,
            content_type="application/merge-patch+json",
        )
        return serialization.job_from_dict(raw)

    def delete_job(self, namespace: str, name: str) -> None:
        self.client.request("DELETE", self._job_path(namespace, name))

    # -- pods --

    def create_pod(self, pod: Pod) -> Pod:
        raw = self.client.request(
            "POST", self._core_path(pod.metadata.namespace, "pods"),
            body=pod_to_k8s(pod),
        )
        return pod_from_k8s(raw)

    def get_pod(self, namespace: str, name: str) -> Pod:
        return pod_from_k8s(
            self.client.request("GET", self._core_path(namespace, "pods", name))
        )

    def list_pods(self, namespace: Optional[str] = None,
                  selector: Optional[Dict[str, str]] = None) -> List[Pod]:
        params = {}
        if selector:
            params["labelSelector"] = ",".join(f"{k}={v}" for k, v in selector.items())
        if namespace or self.namespace:
            path = self._core_path(self._ns(namespace), "pods")
        else:
            path = "/api/v1/pods"
        raw = self.client.request("GET", path, params=params or None)
        return [pod_from_k8s(item) for item in raw.get("items", [])]

    def update_pod(self, pod: Pod) -> Pod:
        """Metadata-only write (labels/annotations — slice-id stamping,
        scheduler.py).  A whole-object PUT would be rejected — pod spec is
        immutable and our converter cannot round-trip admission-injected
        fields — and the kubelet owns status, so writing the caller's
        snapshot of it back here would regress a phase that advanced between
        the caller's read and this patch.  Callers that mean to write status
        (fault injection) use update_pod_status."""
        path = self._core_path(pod.metadata.namespace, "pods", pod.metadata.name)
        raw = self.client.request(
            "PATCH", path,
            body={"metadata": {
                "labels": dict(pod.metadata.labels),
                "annotations": dict(pod.metadata.annotations),
            }},
            content_type="application/merge-patch+json",
        )
        return pod_from_k8s(raw)

    def update_pod_status(self, pod: Pod) -> Pod:
        """Explicit status write via the pods/status subresource (the
        fake-slice-provider preemption path marking victims Failed)."""
        raw = pod_to_k8s(self.update_pod(pod))  # metadata first
        path = self._core_path(pod.metadata.namespace, "pods", pod.metadata.name)
        status_body = {"status": {
            "phase": pod.status.phase.value,
            "reason": pod.status.reason or None,
            "message": pod.status.message or None,
            "containerStatuses": [
                {
                    "name": cs.name,
                    "restartCount": cs.restart_count,
                    "state": (
                        {"terminated": {"exitCode": cs.exit_code}}
                        if cs.terminated and cs.exit_code is not None
                        else {"running": {}} if cs.running else {}
                    ),
                }
                for cs in pod.status.container_statuses
            ] or None,
        }}
        try:
            raw = self.client.request(
                "PATCH", f"{path}/status", body=status_body,
                content_type="application/merge-patch+json",
            )
        except (ApiError, NotFound, TooManyRequests) as err:
            # Real clusters may deny pods/status to the operator (kubelet
            # owns it), or throttle it past the retry budget; the metadata
            # patch above already landed.
            log.debug("pod status patch skipped: %s", err)
        return pod_from_k8s(raw)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.client.request("DELETE", self._core_path(namespace, "pods", name))

    def pod_logs(self, namespace: str, name: str) -> str:
        """Container log retrieval (ref SDK get_logs: read_namespaced_pod_log,
        tf_job_client.py:340-356) — makes `cli logs` / SDK get_logs work on
        the k8s runtime, not just local/in-memory substrates."""
        return self.client.request(
            "GET", f"{self._core_path(namespace, 'pods', name)}/log", raw=True
        )

    def evict_pod(self, namespace: str, name: str) -> None:
        """PDB-guarded voluntary eviction (Eviction subresource; a 429 means
        the budget blocks it -> EvictionBlocked, matching InMemoryCluster)."""
        self.client.request(
            "POST", f"{self._core_path(namespace, 'pods', name)}/eviction",
            body={
                "apiVersion": "policy/v1",
                "kind": "Eviction",
                "metadata": {"name": name, "namespace": namespace},
            },
        )

    # -- scheduling (pods/binding subresource) --
    #
    # The in-process GangScheduler (runtime/scheduler.py) defers pod startup
    # until the whole gang is admitted, then binds each member.  On the k8s
    # backend "binding" is the real thing: pods stamped with our scheduler
    # name are ignored by kube-scheduler (schedulerName mismatch), sit
    # unscheduled, and start only when we POST the pods/binding subresource —
    # the same protocol every custom scheduler uses.  The reference never
    # binds (it delegates gang admission to Volcano, job_controller.go:211-239);
    # here the operator itself can be the gang scheduler on a plain cluster.

    def list_nodes(self) -> List[Dict[str, Any]]:
        """Raw node objects — metadata.labels for selector matching and
        status.allocatable for resource fit."""
        raw = self.client.request("GET", "/api/v1/nodes")
        return list(raw.get("items", []))

    @staticmethod
    def _pod_tpu_request(spec: Dict[str, Any]) -> float:
        total = 0.0
        for c in spec.get("containers") or []:
            limits = ((c.get("resources") or {}).get("limits")
                      or (c.get("resources") or {}).get("requests") or {})
            total += quantity_to_float(limits.get(constants.TPU_RESOURCE, 0))
        return total

    def bind_pod(self, namespace: str, name: str) -> int:
        """Schedule one admitted gang pod (see bind_pods)."""
        return self.bind_pods([(namespace, name)])

    def bind_pods(self, targets: List[Tuple[str, str]]) -> int:
        """Schedule admitted gang pods: pick a feasible node per pod and POST
        the pods/binding subresource.  Feasibility = the pod's nodeSelector
        is a subset of the node's labels, and the node's allocatable TPU
        chips cover the request on top of non-terminal pods already bound
        there.  The node and usage snapshots are taken ONCE per call — one
        nodes LIST + one pods LIST for the whole gang, not per member.  A
        pod with no feasible node stays Pending with a FailedScheduling
        event; the gang scheduler's periodic retry picks it up once nodes
        change (node churn produces no pod watch events).  Returns the
        number of bindings actually posted."""
        if not targets:
            return 0
        nodes = self.list_nodes()
        used: Dict[str, float] = {}
        wanted = set(targets)
        raw_pods: Dict[Tuple[str, str], Dict[str, Any]] = {}
        live_uids = set()
        for other in self.client.request("GET", "/api/v1/pods").get("items", []):
            meta = other.get("metadata") or {}
            key = (meta.get("namespace", "default"), meta.get("name", ""))
            live_uids.add(key + (meta.get("uid", ""),))
            if key in wanted:
                raw_pods[key] = other
            ospec = other.get("spec") or {}
            node = ospec.get("nodeName")
            # Terminal pods keep spec.nodeName forever but hold no chips —
            # counting them would permanently starve the node.
            if not node or (other.get("status") or {}).get("phase") in (
                    "Succeeded", "Failed"):
                continue
            used[node] = used.get(node, 0.0) + self._pod_tpu_request(ospec)
        # Warned-set hygiene: entries are keyed by (ns, name, uid) so a
        # deleted-and-recreated pod (same deterministic name, new uid) gets
        # its own FailedScheduling event, and pruning against the live uid
        # set bounds the set's size on a long-lived operator.
        self._sched_warned &= live_uids

        # Phase 1 — place every member against the snapshot WITHOUT posting
        # anything.  If any live, unbound member has no feasible node, bind
        # nothing: starting the feasible subset would be a partial gang,
        # the exact state gang scheduling exists to prevent.  The gang keeps
        # its admission; the periodic retry re-attempts once nodes change.
        plan: List[Tuple[str, str, str]] = []
        infeasible: List[Tuple[str, str, dict, float]] = []
        for namespace, name in targets:
            raw = raw_pods.get((namespace, name))
            if raw is None:
                continue  # deleted between admission snapshot and bind
            spec = raw.get("spec") or {}
            if spec.get("nodeName"):
                continue  # already bound
            selector = spec.get("nodeSelector") or {}
            requested = self._pod_tpu_request(spec)
            target = None
            for node in nodes:
                labels = (node.get("metadata") or {}).get("labels") or {}
                if any(labels.get(k) != v for k, v in selector.items()):
                    continue
                node_name = (node.get("metadata") or {}).get("name", "")
                if requested:
                    allocatable = quantity_to_float(
                        ((node.get("status") or {}).get("allocatable") or {})
                        .get(constants.TPU_RESOURCE, 0))
                    if used.get(node_name, 0.0) + requested > allocatable:
                        continue
                target = node_name
                break
            if target is None:
                infeasible.append((namespace, name, selector, requested))
            else:
                plan.append((namespace, name, target))
                used[target] = used.get(target, 0.0) + requested
        if infeasible:
            # One FailedScheduling event per pod per dry spell — the 30s
            # retry sweep re-runs this path indefinitely and must not mint
            # a fresh Event object every attempt.
            for namespace, name, selector, requested in infeasible:
                uid = ((raw_pods.get((namespace, name)) or {})
                       .get("metadata") or {}).get("uid", "")
                if (namespace, name, uid) in self._sched_warned:
                    continue
                self._sched_warned.add((namespace, name, uid))
                self.record_event(Event(
                    object_kind="Pod", object_name=name, namespace=namespace,
                    event_type="Warning", reason="FailedScheduling",
                    message=(f"no node satisfies nodeSelector {selector} with "
                             f"{requested:g} {constants.TPU_RESOURCE} "
                             "available; holding the whole gang unbound"),
                ))
            return 0

        # Phase 2 — post the bindings.
        for namespace, name, target in plan:
            self.client.request(
                "POST", f"{self._core_path(namespace, 'pods', name)}/binding",
                body={
                    "apiVersion": "v1",
                    "kind": "Binding",
                    "metadata": {"name": name, "namespace": namespace},
                    "target": {"apiVersion": "v1", "kind": "Node", "name": target},
                },
            )
            uid = ((raw_pods.get((namespace, name)) or {})
                   .get("metadata") or {}).get("uid", "")
            self._sched_warned.discard((namespace, name, uid))
        return len(plan)

    # -- services --

    def create_service(self, svc: Service) -> Service:
        raw = self.client.request(
            "POST", self._core_path(svc.metadata.namespace, "services"),
            body=service_to_k8s(svc),
        )
        return service_from_k8s(raw)

    def list_services(self, namespace: Optional[str] = None,
                      selector: Optional[Dict[str, str]] = None) -> List[Service]:
        params = {}
        if selector:
            params["labelSelector"] = ",".join(f"{k}={v}" for k, v in selector.items())
        raw = self.client.request(
            "GET", self._core_path(self._ns(namespace), "services"),
            params=params or None,
        )
        return [service_from_k8s(item) for item in raw.get("items", [])]

    def delete_service(self, namespace: str, name: str) -> None:
        self.client.request("DELETE", self._core_path(namespace, "services", name))

    # -- podgroups / pdbs --

    def _podgroup_path(self, namespace: str, name: str = "") -> str:
        base = f"/apis/{self.podgroup_api}/namespaces/{namespace}/podgroups"
        return f"{base}/{name}" if name else base

    def create_podgroup(self, pg: PodGroup) -> PodGroup:
        raw = self.client.request(
            "POST", self._podgroup_path(pg.metadata.namespace),
            body=podgroup_to_k8s(pg, self.podgroup_api),
        )
        return podgroup_from_k8s(raw)

    def get_podgroup(self, namespace: str, name: str) -> PodGroup:
        return podgroup_from_k8s(
            self.client.request("GET", self._podgroup_path(namespace, name))
        )

    def update_podgroup(self, pg: PodGroup) -> PodGroup:
        """Persist PodGroup mutations (the gang scheduler's phase writes —
        on InMemoryCluster the returned object is shared so mutation sticks;
        over the wire it must be written back).  CR updates require
        metadata.resourceVersion, so read-inject-PUT with one retry on a
        write conflict, same as update_job.  Only meaningful against the
        operator's own PodGroup CRD (manifests/podgroup.yaml, no status
        subresource); under --gang-mechanism volcano the in-process
        scheduler — the only phase writer — doesn't run at all."""
        path = self._podgroup_path(pg.metadata.namespace, pg.metadata.name)
        body = podgroup_to_k8s(pg, self.podgroup_api)
        for attempt in (0, 1):
            current = self.client.request("GET", path)
            body["metadata"]["resourceVersion"] = (
                current.get("metadata") or {}
            ).get("resourceVersion", "")
            try:
                raw = self.client.request("PUT", path, body=body)
                return podgroup_from_k8s(raw)
            except AlreadyExists:  # 409 conflict: refetch and retry once
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def delete_podgroup(self, namespace: str, name: str) -> None:
        self.client.request("DELETE", self._podgroup_path(namespace, name))

    def _pdb_path(self, namespace: str, name: str = "") -> str:
        base = f"/apis/policy/v1/namespaces/{namespace}/poddisruptionbudgets"
        return f"{base}/{name}" if name else base

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        raw = self.client.request(
            "POST", self._pdb_path(pdb.metadata.namespace), body=pdb_to_k8s(pdb)
        )
        return pdb_from_k8s(raw)

    def get_pdb(self, namespace: str, name: str) -> PodDisruptionBudget:
        return pdb_from_k8s(
            self.client.request("GET", self._pdb_path(namespace, name))
        )

    def update_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        raw = self.client.request(
            "PUT", self._pdb_path(pdb.metadata.namespace, pdb.metadata.name),
            body=pdb_to_k8s(pdb),
        )
        return pdb_from_k8s(raw)

    def delete_pdb(self, namespace: str, name: str) -> None:
        self.client.request("DELETE", self._pdb_path(namespace, name))

    # -- events --

    def record_event(self, event: Event) -> None:
        self._event_seq += 1
        try:
            self.client.request(
                "POST", self._core_path(event.namespace, "events"),
                body=event_to_k8s(
                    event, f"{int(event.timestamp * 1000):x}.{self._event_seq}"
                ),
            )
        except Exception as err:  # noqa: BLE001 — events are best-effort; a
            # failed write (404 terminating namespace, socket error, ...)
            # must never abort the reconcile/scheduling step that emitted it.
            log.warning("event write failed: %s", err)

    def list_events(self, namespace: Optional[str] = None,
                    object_name: Optional[str] = None) -> List[Event]:
        params = {}
        if object_name:
            params["fieldSelector"] = f"involvedObject.name={object_name}"
        raw = self.client.request(
            "GET", self._core_path(self._ns(namespace), "events"),
            params=params or None,
        )
        return [event_from_k8s(item) for item in raw.get("items", [])]

    # -- watches (the informer analogue) --

    def watch_jobs(self, handler: WatchHandler) -> None:
        self._job_handlers.append(handler)
        self._ensure_watch(
            "jobs",
            f"/apis/{constants.API_GROUP}/{constants.API_VERSION}/{constants.PLURAL}"
            if not (self.namespace or None)
            else self._job_path(None),
            serialization.job_from_dict,
            self._job_handlers,
        )

    def watch_pods(self, handler: WatchHandler) -> None:
        self._pod_handlers.append(handler)
        path = ("/api/v1/pods" if not (self.namespace or None)
                else self._core_path(self._ns(None), "pods"))
        self._ensure_watch("pods", path, pod_from_k8s, self._pod_handlers)

    def watch_services(self, handler: WatchHandler) -> None:
        self._service_handlers.append(handler)
        path = ("/api/v1/services" if not (self.namespace or None)
                else self._core_path(self._ns(None), "services"))
        self._ensure_watch("services", path, service_from_k8s, self._service_handlers)

    def _ensure_watch(self, key: str, path: str,
                      convert: Callable[[dict], Any],
                      handlers: List[WatchHandler]) -> None:
        state = self._watch_state.get(key)
        if state is None:
            state = _WatchState(key, path, convert, handlers)
            self._watch_state[key] = state
        existing = self._watch_threads.get(key)
        if existing is not None and existing.is_alive():
            return
        if existing is not None:
            # A watch thread died (it shouldn't — the loop retries on any
            # exception — but a dead informer silently blinds the controller,
            # so supervise anyway; client-go informers always reconnect).
            log.warning("watch thread %s found dead; restarting", key)
        thread = threading.Thread(
            target=self._watch_loop, args=(state,),
            daemon=True, name=f"k8s-watch-{key}",
        )
        self._watch_threads[key] = thread
        thread.start()

    def _watch_loop(self, state: _WatchState) -> None:
        path, convert, handlers = state.path, state.convert, state.handlers
        resource_version = ""
        # ns/name -> last converted object: lets a relist after a stream gap
        # emit synthetic DELETEDs for objects that vanished during the gap
        # (informer cache-diff semantics) — gang release and terminal cleanup
        # are driven purely by DELETED events.
        known: Dict[str, Any] = {}
        while not self._stop.is_set():
            try:
                if not resource_version:
                    # List first: replay current state as ADDED / diff
                    # against the cache, pin the resourceVersion.
                    raw = self.client.request("GET", path)
                    resource_version = (raw.get("metadata") or {}).get(
                        "resourceVersion", ""
                    )
                    seen: Dict[str, Any] = {}
                    for item in raw.get("items", []):
                        obj = convert(item)
                        obj_key = f"{obj.metadata.namespace}/{obj.metadata.name}"
                        seen[obj_key] = obj
                        etype = (EventType.MODIFIED if obj_key in known
                                 else EventType.ADDED)
                        self._dispatch(handlers, etype, obj)
                    for gone_key in set(known) - set(seen):
                        self._dispatch(handlers, EventType.DELETED, known[gone_key])
                    known = seen
                    state.last_event = time.monotonic()
                params = {"resourceVersion": resource_version,
                          "allowWatchBookmarks": "true"}
                for evt in self.client.stream_watch(
                    path, params, self._stop, conn_registry=state.conns
                ):
                    # Any frame — data, bookmark, even an ERROR — is a
                    # heartbeat: the stream demonstrably still delivers.
                    state.last_event = time.monotonic()
                    etype = evt.get("type", "")
                    obj_raw = evt.get("object") or {}
                    if etype == "BOOKMARK":
                        resource_version = (obj_raw.get("metadata") or {}).get(
                            "resourceVersion", resource_version
                        )
                        continue
                    if etype == "ERROR":
                        resource_version = ""  # 410 Gone -> relist
                        break
                    resource_version = (obj_raw.get("metadata") or {}).get(
                        "resourceVersion", resource_version
                    )
                    mapping = {
                        "ADDED": EventType.ADDED,
                        "MODIFIED": EventType.MODIFIED,
                        "DELETED": EventType.DELETED,
                    }
                    if etype in mapping:
                        obj = convert(obj_raw)
                        obj_key = f"{obj.metadata.namespace}/{obj.metadata.name}"
                        if etype == "DELETED":
                            known.pop(obj_key, None)
                        else:
                            known[obj_key] = obj
                        self._dispatch(handlers, mapping[etype], obj)
            except (OSError, HTTPException, ApiError, NotFound,
                    TooManyRequests, ValueError) as err:
                # HTTPException covers IncompleteRead/BadStatusLine from a
                # mid-chunk truncated watch stream — without it the daemon
                # thread dies and the controller silently stops seeing events.
                # TooManyRequests: the relist GET exhausted its retry budget
                # under sustained throttling; back off and try again.
                if self._stop.is_set():
                    return
                log.warning("watch %s error: %s; reconnecting", path, err)
                resource_version = ""
                self._stop.wait(1.0)
            except Exception as err:  # noqa: BLE001 — last resort: a watch
                # loop must never die while the cluster is open (informer
                # contract); relist and keep going.
                if self._stop.is_set():
                    return
                log.exception("watch %s unexpected error: %s; relisting", path, err)
                resource_version = ""
                self._stop.wait(1.0)

    @staticmethod
    def _dispatch(handlers: List[WatchHandler], etype: EventType, obj: Any) -> None:
        for handler in list(handlers):
            try:
                handler(etype, obj)
            except Exception:  # noqa: BLE001 — one handler must not kill the watch
                log.exception("watch handler failed")

    # -- watch staleness (the self-healing heartbeat; docs/self-healing.md) --

    def watch_ages(self) -> Dict[str, float]:
        """Seconds since each watch stream last showed a sign of life (a
        relist completing, an event, or a bookmark).  Feeds the deep health
        report's per-watch freshness detail.  Called from HTTP handler
        threads while _ensure_watch may be registering a new stream, so
        iterate a snapshot — a plain dict comprehension would raise
        'dictionary changed size during iteration'."""
        now = time.monotonic()
        return {key: now - state.last_event
                for key, state in list(self._watch_state.items())}

    def kick_stale_watches(self, max_age: float) -> List[str]:
        """Force-reconnect every watch stream older than `max_age`.

        A watch can be 'alive' (thread running) yet blind: the connection's
        peer is gone but TCP never noticed, so the reader is parked in recv
        forever and the controller silently stops seeing events.  Closing
        the socket from here makes the read fail, which sends the loop
        through its normal error path: reconnect + relist (replaying missed
        state as ADDED/MODIFIED/synthetic DELETED).  The heartbeat is reset
        on kick so a reconnecting watch isn't re-kicked every sweep.
        Returns the kicked watch keys; increments tpujob_watch_stale_total
        per kick."""
        now = time.monotonic()
        stale: List[str] = []
        for key, state in list(self._watch_state.items()):
            age = now - state.last_event
            if age <= max_age:
                continue
            stale.append(key)
            state.last_event = now  # re-arm: give the reconnect a full window
            metrics.watch_stale_total.labels(key).inc()
            log.warning("watch %s stale for %.1fs (deadline %.1fs); "
                        "forcing reconnect", key, age, max_age)
            self._close_conns(state.conns)
            # Belt and braces: if the thread itself died, the supervisor
            # respawns it from the recorded state.
            self._ensure_watch(key, state.path, state.convert, state.handlers)
        return stale

    @staticmethod
    def _close_conns(conns: List[Any]) -> None:
        """Break live watch connections so parked readers wake with EOF.
        shutdown() first: it unblocks a recv from another thread, whereas
        conn.close() alone can DEADLOCK — the watch thread holds the
        response buffer lock inside read1() (chunked decoding), and
        HTTPConnection.close() -> response.close() -> fp.close() blocks
        acquiring that same lock."""
        import socket as _socket

        for conn in list(conns):
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(_socket.SHUT_RDWR)
                except OSError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    # -- leases (leader election) --

    def try_acquire_lease(self, name: str, holder: str, ttl: float) -> bool:
        """coordination.k8s.io Lease acquire/renew (the reference's
        EndpointsLock semantics, server.go:53-58,159-184)."""
        namespace = self._ns(None)
        path = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        # Lease calls must not ride the default ~30s retry budget: a renew
        # blocked past the lease duration keeps a deposed leader reconciling
        # (split brain) instead of letting the elector observe the loss on
        # its next cycle.  Bound every attempt well inside the ttl.
        deadline = ttl / 3.0

        def stamped_body() -> dict:
            # Stamped at write time, not method entry: peers compute expiry
            # from the LANDED renewTime, so a stamp taken before the
            # (possibly retrying) GET would hand back the margin the
            # per-call deadline above buys.
            now = clock.now()
            return {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": name, "namespace": namespace},
                "spec": {
                    "holderIdentity": holder,
                    # ceil, not int: the API field is integral, and a
                    # truncated fractional ttl would make peers compute
                    # expiry EARLIER than the holder's local float claim —
                    # eating into the shard-lease ownership margin.
                    # Rounding up only delays adoption, the safe direction.
                    "leaseDurationSeconds": math.ceil(ttl),
                    # Real microseconds (k8s MicroTime), not a floored
                    # stamp with a fake .000000: lease_renew_time keeps
                    # the fraction, so peers reconstruct this exact
                    # instant and the ownership margin stays whole.
                    "renewTime": to_rfc3339_micro(now),
                    "acquireTime": to_rfc3339_micro(now),
                },
            }

        try:
            raw = self.client.request("GET", f"{path}/{name}",
                                      deadline=deadline)
        except NotFound:
            try:
                self.client.request("POST", path, body=stamped_body(),
                                    deadline=deadline)
                return True
            except (AlreadyExists, ApiError, TooManyRequests,
                    OSError, HTTPException):
                # Lost/failed acquisition — including sustained throttling
                # that exhausted the retry budget.  The elector loop retries;
                # an escaped exception here would kill its thread silently.
                return False
        except (ApiError, TooManyRequests, OSError, HTTPException):
            # Unreachable/refusing apiserver past the (short) lease retry
            # budget: report not-acquired.  A standby keeps polling; a
            # leader reaches on_lost gracefully instead of dying mid-renew
            # with a traceback.
            return False
        spec = raw.get("spec") or {}
        current_holder = spec.get("holderIdentity", "")
        renew = lease_renew_time(spec)
        duration = float(spec.get("leaseDurationSeconds") or ttl)
        expired = renew is None or (clock.now() - renew) > duration
        if current_holder and current_holder != holder and not expired:
            return False
        body = stamped_body()
        body["metadata"]["resourceVersion"] = (raw.get("metadata") or {}).get(
            "resourceVersion", ""
        )
        try:
            self.client.request("PUT", f"{path}/{name}", body=body,
                                deadline=deadline)
            return True
        except (ApiError, AlreadyExists, NotFound, TooManyRequests,
                OSError, HTTPException):
            # Conflict (someone renewed first), lease deleted under us,
            # throttled past the retry budget, or transport trouble: treat
            # as not-acquired and let the elector loop retry.
            return False

    def release_lease(self, name: str, holder: str) -> bool:
        """Voluntary lease handoff (runtime/shardlease.py): DELETE the Lease
        iff `holder` still holds it.  Best-effort by design — every failure
        mode (conflict, transport, already gone) returns False and expiry
        remains the backstop, exactly like a crashed holder."""
        namespace = self._ns(None)
        path = (f"/apis/coordination.k8s.io/v1/namespaces/{namespace}"
                f"/leases/{name}")
        deadline = 5.0  # short, like the lease acquire path: never wedge a handoff
        try:
            raw = self.client.request("GET", path, deadline=deadline)
        except (NotFound, ApiError, TooManyRequests, OSError, HTTPException):
            return False
        if ((raw.get("spec") or {}).get("holderIdentity", "")) != holder:
            return False  # a successor already re-acquired: leave it alone
        try:
            # resourceVersion precondition: between the GET above and this
            # DELETE a successor may have re-acquired the (expired) lease
            # via PUT — an unconditional DELETE would then remove ITS
            # fresh lease while it still answers owns()=True locally.  A
            # conflict means exactly that; report not-released.
            self.client.request(
                "DELETE", path,
                body={
                    "kind": "DeleteOptions", "apiVersion": "v1",
                    "preconditions": {
                        "resourceVersion": (raw.get("metadata") or {}).get(
                            "resourceVersion", ""),
                    },
                },
                deadline=deadline)
            return True
        except (NotFound, AlreadyExists, ApiError, TooManyRequests,
                OSError, HTTPException):
            # AlreadyExists is what a 409 — the precondition conflict this
            # DELETE exists to detect — surfaces as.
            return False

    def list_leases(self, prefix: str = "") -> Dict[str, str]:
        """Unexpired {name: holder} with a name prefix filter (client-side;
        the shard-lease membership read).  Expiry follows the same
        renewTime+duration rule try_acquire_lease applies."""
        namespace = self._ns(None)
        path = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        raw = self.client.request("GET", path, deadline=5.0)
        out: Dict[str, str] = {}
        for item in raw.get("items") or []:
            name = (item.get("metadata") or {}).get("name", "")
            if not name.startswith(prefix):
                continue
            spec = item.get("spec") or {}
            holder = spec.get("holderIdentity", "")
            if not holder:
                continue
            renew = lease_renew_time(spec)
            duration = float(spec.get("leaseDurationSeconds") or 0)
            if renew is None or (clock.now() - renew) > duration:
                continue
            out[name] = holder
        return out

    def close(self) -> None:
        self._stop.set()
        # Unblock watch threads parked in recv on timeout-less connections
        # (see _close_conns for why shutdown-then-close, in that order).
        for state in list(self._watch_state.values()):
            self._close_conns(state.conns)


def default_config() -> KubeConfig:
    """In-cluster when running as a Deployment, kubeconfig otherwise —
    the reference's resolution order (server.go:94-99 KUBECONFIG override)."""
    if (os.path.exists(os.path.join(SERVICE_ACCOUNT_DIR, "token"))
            and "KUBECONFIG" not in os.environ):
        return KubeConfig.in_cluster()
    return KubeConfig.from_kubeconfig()
