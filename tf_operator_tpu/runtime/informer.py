"""Shared informer cache: the controller's local, watch-fed read path.

The reference controller reads through client-go SharedInformers (SURVEY.md
§1 L0/L1): every GET/LIST the reconcile loop issues is served from an
in-process store that watch streams keep fresh, so steady-state reconciles
cost the apiserver nothing.  Until this module, our controller paid real
wire traffic per sync — one GET in `controller._sync_job` plus two
label-selected LISTs in `reconciler.get_pods_for_job`/`get_services_for_job`
— which is exactly the per-job cost that caps a fleet at O(100) concurrent
TPUJobs ("Exploring the limits of Concurrency in ML Training on Google
TPUs", PAPERS.md).  `InformerCache` is the client-go analogue for
ClusterInterface substrates:

  - one `_Store` per resource kind (jobs, pods, services): objects keyed by
    "ns/name" with two indexes — by namespace, and by the job-name owner
    label (`gen_labels`' LABEL_JOB_NAME) that every reconcile LIST selects on
    — so the hot list path is an index lookup, not a scan;
  - watch-fed: the cache registers its handlers BEFORE the controller's, so
    by the time a watch event enqueues a key the store already reflects it
    (both substrates dispatch each event to handlers in registration order);
  - a relist loop (`tpujob-informer-relist`) that re-LISTs every kind each
    `relist_period` seconds and repairs the store with full diff semantics
    (upserts + removal of gone objects).  This is the backstop for the one
    failure watch supervision can't see: events lost while the stream stayed
    "alive" (PR 5's `kick_stale_watches` heartbeat machinery handles dead
    streams; the controller's watchdog calls `relist_soon()` after every
    kick so repair happens immediately, not at the next period);
  - read API mirroring the ClusterInterface read verbs (`get_job`,
    `list_jobs`, `list_pods`, `list_services`): list reads always come from
    the store; a `get_job` miss falls back to the wire (cold cache, or a
    genuinely deleted job whose NotFound the controller needs) and is
    counted on `tpujob_informer_cache_misses_total`.

Writes never touch this module — create/delete/status stay on the wire path,
and their watch echoes are what keep the store honest.  Staleness semantics
and how the expectations cache makes stale reads safe are documented in
docs/informer-cache.md.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ..api import constants
from ..utils import locks
from ..utils import logging as tpulog
from ..utils import metrics
from .cluster import ClusterInterface, EventType

log = tpulog.logger_for_key("informer")

# Default period of the repair relist.  Deliberately long: watches carry the
# steady state, and kick_stale_watches + relist_soon() cover the failure
# case, so the period only bounds staleness nobody detected.
DEFAULT_RELIST_PERIOD = 300.0

# How long a deletion tombstone outlives its DELETED event.  A LIST snapshot
# older than this cannot still be being applied (every prime/relist is one
# bounded request + an in-memory walk), so pruning at this horizon keeps the
# tombstone map O(recent deletions) without reopening the resurrect race.
TOMBSTONE_TTL = 120.0


def _matches(labels: Dict[str, str], selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class _Store:
    """One resource kind's indexed object store.

    Objects are stored by "ns/name" key; `_by_namespace` and `_by_owner`
    (namespace, job-name label) hold key sets for the two lookups the
    controller actually does.  All three maps move together under one leaf
    lock; no method calls out while holding it."""

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self._lock = locks.new_lock(f"informer-{kind}")
        self._objects: Dict[str, Any] = {}  # guarded-by: _lock
        self._by_namespace: Dict[str, Set[str]] = {}  # guarded-by: _lock
        # (namespace, job-name label) -> keys; only objects carrying the
        # label are indexed (jobs themselves aren't)
        self._by_owner: Dict[Tuple[str, str], Set[str]] = {}  # guarded-by: _lock
        # key -> monotonic deletion time.  A LIST snapshot is taken at some
        # instant; a DELETED watch event processed after that instant but
        # before the snapshot is merged must win, or the merge resurrects
        # the object (a ghost the controller would then reconcile forever).
        # merge()/replace_all() carry the snapshot time and skip any key
        # whose tombstone is newer; a watch upsert (a genuine recreate,
        # stream-ordered after the DELETED) clears the tombstone.
        self._tombstones: Dict[str, float] = {}  # guarded-by: _lock
        # key -> monotonic time of the last WATCH write.  The symmetric
        # guard: an object created/modified by a watch event after the
        # snapshot instant must not be evicted or reverted by applying
        # that older snapshot (eviction would un-observe a creation the
        # expectations cache already counted -> duplicate pod creates;
        # reversion would roll a terminal pod back to Running with no
        # further event to fix it).  One entry per live key, dropped with
        # the key.
        self._fresh: Dict[str, float] = {}  # guarded-by: _lock

    @staticmethod
    def _key(obj: Any) -> str:
        return f"{obj.metadata.namespace}/{obj.metadata.name}"

    @staticmethod
    def _owner(obj: Any) -> Optional[Tuple[str, str]]:
        job_name = obj.metadata.labels.get(constants.LABEL_JOB_NAME)
        if not job_name:
            return None
        return (obj.metadata.namespace, job_name)

    # -- mutation (watch events + relist repair) --

    def upsert(self, obj: Any) -> None:
        """Watch-event write: the stream's ordering is authoritative, so an
        ADDED/MODIFIED after a DELETED is a genuine recreate and clears the
        tombstone; the freshness stamp protects this write from any older
        LIST snapshot still being applied."""
        key = self._key(obj)
        now = time.monotonic()
        with self._lock:
            self._tombstones.pop(key, None)
            self._fresh[key] = now
            self._unindex_locked(key)
            self._objects[key] = obj
            self._index_locked(key, obj)

    def remove(self, obj: Any) -> None:
        key = self._key(obj)
        now = time.monotonic()
        with self._lock:
            self._unindex_locked(key)
            self._objects.pop(key, None)
            self._fresh.pop(key, None)
            self._tombstones[key] = now
            if len(self._tombstones) > 64:  # amortized prune
                horizon = now - TOMBSTONE_TTL
                for old_key in [k for k, t in self._tombstones.items()
                                if t < horizon]:
                    del self._tombstones[old_key]

    # requires-lock: _lock
    def _snapshot_wins_locked(self, key: str, as_of: float) -> bool:
        """May a LIST snapshot taken at `as_of` write `key`?  No when a
        watch event — deletion (tombstone) or creation/update (freshness
        stamp) — touched the key after the snapshot: the stream is more
        current than the snapshot by construction."""
        return (self._tombstones.get(key, -1.0) < as_of
                and self._fresh.get(key, -1.0) < as_of)

    def merge(self, objs: List[Any], as_of: float) -> None:
        """Prime-path write: upsert `objs` from a LIST snapshot taken at
        monotonic time `as_of`, never deleting — and never resurrecting,
        reverting, or evicting anything a watch event touched after the
        snapshot."""
        with self._lock:
            for obj in objs:
                key = self._key(obj)
                if not self._snapshot_wins_locked(key, as_of):
                    continue
                self._unindex_locked(key)
                self._objects[key] = obj
                self._index_locked(key, obj)

    def replace_all(self, objs: List[Any], as_of: float) -> None:
        """Relist repair: make the store exactly the `as_of` LIST snapshot —
        upsert everything listed, drop everything that vanished — except
        where a watch event outran the snapshot (see
        _snapshot_wins_locked): an object created after the snapshot
        survives, one modified after it keeps the newer state, one deleted
        after it stays gone."""
        fresh = {self._key(obj): obj for obj in objs}
        now = time.monotonic()
        with self._lock:
            gone = [k for k in self._objects
                    if k not in fresh and self._fresh.get(k, -1.0) < as_of]
            for key in gone:
                self._unindex_locked(key)
                del self._objects[key]
                self._fresh.pop(key, None)
            for key, obj in fresh.items():
                if not self._snapshot_wins_locked(key, as_of):
                    continue
                self._unindex_locked(key)
                self._objects[key] = obj
                self._index_locked(key, obj)
            # the snapshot is the full truth as of `as_of`: tombstones at
            # or before it have served their purpose
            for key in [k for k, t in self._tombstones.items()
                        if t < as_of or t < now - TOMBSTONE_TTL]:
                del self._tombstones[key]

    # requires-lock: _lock
    def _index_locked(self, key: str, obj: Any) -> None:
        self._by_namespace.setdefault(obj.metadata.namespace, set()).add(key)
        owner = self._owner(obj)
        if owner is not None:
            self._by_owner.setdefault(owner, set()).add(key)

    # requires-lock: _lock
    def _unindex_locked(self, key: str) -> None:
        old = self._objects.get(key)
        if old is None:
            return
        bucket = self._by_namespace.get(old.metadata.namespace)
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._by_namespace[old.metadata.namespace]
        owner = self._owner(old)
        if owner is not None:
            obucket = self._by_owner.get(owner)
            if obucket is not None:
                obucket.discard(key)
                if not obucket:
                    del self._by_owner[owner]

    # -- reads --

    def get(self, namespace: str, name: str) -> Optional[Any]:
        with self._lock:
            return self._objects.get(f"{namespace}/{name}")

    def keys(self) -> List[str]:
        """Every stored "ns/name" key, without materializing objects —
        the shard-adoption scan (controller._on_shard_adopted) only needs
        keys to route through shard_for()."""
        with self._lock:
            return list(self._objects)

    def list(self, namespace: Optional[str] = None,
             selector: Optional[Dict[str, str]] = None) -> List[Any]:
        with self._lock:
            job_name = (selector or {}).get(constants.LABEL_JOB_NAME)
            if job_name and namespace:
                keys = set(self._by_owner.get((namespace, job_name), ()))
            elif namespace:
                keys = set(self._by_namespace.get(namespace, ()))
            else:
                keys = set(self._objects)
            out = [self._objects[k] for k in keys if k in self._objects]
        # Verify the full selector outside the lock: the owner index narrows
        # to one job's objects; remaining selector keys still filter.
        return [o for o in out if _matches(o.metadata.labels, selector)]

    def __len__(self) -> int:
        with self._lock:
            return len(self._objects)


class InformerCache:
    """Watch-fed read path over a ClusterInterface (see module docstring).

    Construct BEFORE registering any other watch handler on `cluster`, so
    this cache's handlers run first on every event; then use `get_job`/
    `list_jobs`/`list_pods`/`list_services` wherever the controller used to
    hit the wire.  `start_relist()` spawns the periodic repair thread (call
    it from the controller's start(); constructing alone never spawns
    threads so never-started controllers stay thread-free)."""

    def __init__(self, cluster: ClusterInterface,
                 relist_period: float = DEFAULT_RELIST_PERIOD) -> None:
        self.cluster = cluster
        self.relist_period = relist_period
        self.jobs = _Store("jobs")
        self.pods = _Store("pods")
        self.services = _Store("services")
        self._stop = threading.Event()
        self._relist_now = threading.Event()
        self._relist_thread: Optional[threading.Thread] = None
        self._counter_lock = locks.new_lock("informer-counters")
        # per-instance counters (the process-global metrics aggregate across
        # every controller a test process creates; health reports want ours)
        self._hits = 0  # guarded-by: _counter_lock
        self._misses = 0  # guarded-by: _counter_lock
        self._relists = 0  # guarded-by: _counter_lock

        cluster.watch_jobs(self._on_job)
        cluster.watch_pods(self._on_pod)
        cluster.watch_services(self._on_service)
        self._prime()

    # -- watch handlers --

    def _on_job(self, etype: EventType, obj: Any) -> None:
        self._apply(self.jobs, etype, obj)

    def _on_pod(self, etype: EventType, obj: Any) -> None:
        self._apply(self.pods, etype, obj)

    def _on_service(self, etype: EventType, obj: Any) -> None:
        self._apply(self.services, etype, obj)

    @staticmethod
    def _apply(store: _Store, etype: EventType, obj: Any) -> None:
        if etype == EventType.DELETED:
            store.remove(obj)
        else:
            store.upsert(obj)

    # -- priming / relist --

    def _kinds(self):
        """(kind, store, list_fn) for every cached resource — the ONE
        place to extend when a new kind joins the cache; _prime(), relist()
        and their error handling all iterate this table."""
        return (("jobs", self.jobs, self.cluster.list_jobs),
                ("pods", self.pods, self.cluster.list_pods),
                ("services", self.services, self.cluster.list_services))

    @staticmethod
    def _fill(store: _Store, list_fn, replace: bool) -> None:
        """One kind's snapshot application.  `as_of` is captured BEFORE
        the LIST so any watch event processed after this instant wins over
        the (by then older) snapshot."""
        as_of = time.monotonic()
        objs = list_fn()
        if replace:
            store.replace_all(objs, as_of)
        else:
            store.merge(objs, as_of)

    def _prime(self) -> None:
        """Initial fill.  Watches are registered first, so anything created
        during the prime arrives as an event; the prime itself merges
        (never deletes) and deletion tombstones stop it resurrecting an
        object a concurrent DELETED event just removed.  Each LIST is
        guarded independently — a faulted/flaky substrate at construction
        time leaves that kind cold, and watches + the relist loop repair
        it."""
        for kind, store, list_fn in self._kinds():
            try:
                self._fill(store, list_fn, replace=False)
            except Exception as err:  # noqa: BLE001 — cold start is legal
                log.warning("informer prime of %s failed (%s); relying on "
                            "watch replay / relist", kind, err)

    def relist(self) -> None:
        """One full repair pass over every kind, synchronously.  Guarded
        per kind: a failing LIST leaves that store as-was (stale beats
        empty) and the next pass retries."""
        for kind, store, list_fn in self._kinds():
            try:
                self._fill(store, list_fn, replace=True)
                metrics.informer_relists.labels(kind).inc()
                with self._counter_lock:
                    self._relists += 1
            except Exception as err:  # noqa: BLE001 — repair must not die
                log.warning("informer relist of %s failed: %s", kind, err)

    def relist_soon(self) -> None:
        """Wake the relist loop now (the watchdog calls this right after
        kick_stale_watches force-reconnects a blind stream, so repair does
        not wait out the period)."""
        self._relist_now.set()

    def start_relist(self) -> None:
        """Spawn the repair thread (idempotent).  With relist_period <= 0
        the thread still runs but only fires on relist_soon() — the
        stale-watch repair path must work even when the periodic relist is
        disabled, or a blind stream's lost deletions would never be
        repaired."""
        if self._relist_thread is not None and self._relist_thread.is_alive():
            return
        thread = threading.Thread(target=self._relist_loop,
                                  name="tpujob-informer-relist", daemon=True)
        self._relist_thread = thread
        thread.start()

    def _relist_loop(self) -> None:
        period = self.relist_period if self.relist_period > 0 else None
        while not self._stop.is_set():
            self._relist_now.wait(timeout=period)
            self._relist_now.clear()
            if self._stop.is_set():
                return
            self.relist()

    def stop(self) -> None:
        self._stop.set()
        self._relist_now.set()
        thread = self._relist_thread
        if thread is not None:
            thread.join(timeout=5)

    # -- counters --

    def _count(self, resource: str, hit: bool) -> None:
        with self._counter_lock:
            if hit:
                self._hits += 1
            else:
                self._misses += 1
        (metrics.informer_cache_hits if hit
         else metrics.informer_cache_misses).labels(resource).inc()

    def counters(self) -> Dict[str, int]:
        with self._counter_lock:
            return {"hits": self._hits, "misses": self._misses,
                    "relists": self._relists}

    def report(self) -> dict:
        """Store sizes + counters for the deep health report."""
        out: Dict[str, Any] = {
            "jobs": len(self.jobs),
            "pods": len(self.pods),
            "services": len(self.services),
            "relist_period_seconds": self.relist_period,
        }
        out.update(self.counters())
        return out

    # -- the ClusterInterface read verbs, served locally --

    def get_job(self, namespace: str, name: str) -> Any:
        job = self.jobs.get(namespace, name)
        if job is not None:
            self._count("jobs", hit=True)
            return job
        # Miss: cold cache or a deleted job.  The wire GET disambiguates —
        # its NotFound is exactly what the controller's cleanup path needs.
        # The result is deliberately NOT written back into the store: a
        # GET racing a DELETED watch event could resurrect a deleted job as
        # a permanent cache hit (the NotFound cleanup path would then be
        # unreachable).  The watch stream is the only steady-state writer;
        # a cold key pays the wire until its ADDED arrives, which is the
        # same moment the controller would learn about it anyway.
        self._count("jobs", hit=False)
        return self.cluster.get_job(namespace, name)

    def job_keys(self) -> List[str]:
        """All cached job keys ("ns/name") — the cheap shard-adoption scan."""
        self._count("jobs", hit=True)
        return self.jobs.keys()

    def list_jobs(self, namespace: Optional[str] = None) -> List[Any]:
        self._count("jobs", hit=True)
        return self.jobs.list(namespace)

    def list_pods(self, namespace: Optional[str] = None,
                  selector: Optional[Dict[str, str]] = None) -> List[Any]:
        self._count("pods", hit=True)
        return self.pods.list(namespace, selector)

    def list_services(self, namespace: Optional[str] = None,
                      selector: Optional[Dict[str, str]] = None) -> List[Any]:
        self._count("services", hit=True)
        return self.services.list(namespace, selector)
