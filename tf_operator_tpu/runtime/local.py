"""LocalProcessCluster: pods are real OS processes.

The reference delegates pod execution to kubelet and tests multi-node
behavior with a controllable in-container flask app (SURVEY.md §4 Tier 3).
This backend collapses that stack for single-host use: `create_pod` launches
the pod's container command as a subprocess with the controller-injected env
(TF_CONFIG + TPUJOB_*), a monitor thread turns process exits into pod phase
transitions (exit 0 → Succeeded, else Failed with the exit code), and logs
are captured per pod for `TPUJobClient.get_logs` parity
(ref: sdk tf_job_client.py get_logs, :340-356).

Replica addresses resolve to 127.0.0.1 with a deterministic per-replica port
(the headless-DNS analogue: stable identity across restarts —
ref service naming, vendor/.../common/service.go:303-317).
"""
from __future__ import annotations

import itertools
import os
import signal
import subprocess
import threading
import time
from pathlib import Path
from typing import Dict, Optional, Tuple

from ..api import constants
from ..api.core import ContainerStatus, Pod, PodPhase
from ..api.types import ReplicaType, TPUJob
from ..utils import clock, locks
from ..utils import logging as tpulog
from .cluster import EventType, InMemoryCluster

# per-process cluster counter; feeds the default port-range spreading
_CLUSTER_SEQ = itertools.count()

# Ports handed to one cluster's replicas all come from a block of this many
# contiguous ports; the block's first port is bound as a claim marker so
# concurrent clusters (any process) collide at claim time, not at replica
# rendezvous time.
PORT_BLOCK = 512
_PORT_FLOOR = 20000
_PORT_CEILING = 32768  # Linux ephemeral range starts here; stay below

log = tpulog.logger_for_key("local-cluster")


class LocalProcessCluster(InMemoryCluster):
    def __init__(self, workdir: Optional[str] = None,
                 base_port: Optional[int] = None,
                 extra_env: Optional[Dict[str, str]] = None) -> None:
        super().__init__()
        self.workdir = Path(workdir or ".tpujob-local")
        self.workdir.mkdir(parents=True, exist_ok=True)
        self._port_marker = None
        if base_port is None:
            # Spread the default range by PID and per-process instance:
            # two clusters in different processes (concurrent pytest runs)
            # or sequential clusters in one process (a killed predecessor's
            # sockets may not be reaped yet) must not hand the same
            # 127.0.0.1 port to different jobs' coordinators — colliding
            # groups rendezvous across tests and wedge.  Hashing reduces but
            # cannot rule out overlap, so probe-bind the block's first port
            # and rehash on conflict; the block is capped at PORT_BLOCK
            # ports below Linux's ephemeral range (32768+) so no
            # kernel-assigned outgoing connection can squat a replica port.
            seed = os.getpid() * 2654435761 ^ next(_CLUSTER_SEQ) * 0x9E3779B9
            base_port = self._claim_port_block(seed)
        self.base_port = base_port
        self.extra_env = dict(extra_env or {})
        # image -> (command, args): the "pulled image entrypoint" analogue.
        # A kubelet runs a command-less container through the image's
        # entrypoint; this substrate has no images, so reference manifests
        # (image-only containers, e.g. examples/v1/dist-mnist) run by
        # registering what each image name executes locally.  Keyed by full
        # image ref, falling back to the tagless name.
        self._image_entrypoints: Dict[str, Tuple[list, list]] = {}
        self._procs: Dict[Tuple[str, str], subprocess.Popen] = {}
        self._ports: Dict[str, int] = {}  # guarded-by: _port_lock
        self._port_lock = locks.new_lock("local-ports")
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="tpujob-monitor", daemon=True)
        self._monitor_started = False
        self._closed = False

    # ------------------------------------------------------------------
    # address resolution (plugs into TPUJobController(resolver=...))

    def resolver(self, job: TPUJob, rtype: ReplicaType, index: int, port: int) -> str:
        return f"127.0.0.1:{self.port_for(job.metadata.name, rtype.value, index)}"

    def _claim_port_block(self, seed: int) -> int:
        """Pick a PORT_BLOCK-sized range and bind its first port as a claim
        marker (held for the cluster's lifetime).  A bind conflict means
        another live cluster hashed into the same block — rehash instead of
        handing out ports that would cross-connect two jobs' coordinators."""
        import socket as _socket

        slots = (_PORT_CEILING - _PORT_FLOOR) // PORT_BLOCK
        slot = (seed >> 8) % slots
        for attempt in range(slots):
            base = _PORT_FLOOR + ((slot + attempt) % slots) * PORT_BLOCK
            marker = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            try:
                marker.bind(("127.0.0.1", base))
            except OSError:
                marker.close()
                continue
            marker.listen(1)
            self._port_marker = marker
            return base + 1  # replica ports follow the marker port
        raise RuntimeError(
            f"no free {PORT_BLOCK}-port block in "
            f"[{_PORT_FLOOR}, {_PORT_CEILING})")

    def port_for(self, job_name: str, rtype: str, index: int) -> int:
        key = f"{job_name}/{rtype.lower()}/{index}"
        with self._port_lock:
            if key not in self._ports:
                if len(self._ports) >= PORT_BLOCK - 1:
                    raise RuntimeError(
                        f"cluster exhausted its {PORT_BLOCK}-port block "
                        f"(base {self.base_port}); raise PORT_BLOCK or use "
                        "fewer replicas per cluster")
                self._ports[key] = self.base_port + len(self._ports)
            return self._ports[key]

    # ------------------------------------------------------------------
    # image entrypoints (the "docker pull" analogue for this substrate)

    def register_image(self, image: str, command: list,
                       args: Optional[list] = None) -> None:
        """Declare what `image` executes when a container specifies no
        command — the local analogue of an image entrypoint, letting
        reference TFJob manifests (command-less containers) run unmodified."""
        self._image_entrypoints[image] = (list(command), list(args or []))

    def resolve_image(self, image: str) -> Optional[Tuple[list, list]]:
        entry = self._image_entrypoints.get(image)
        if entry is None and ":" in image:
            entry = self._image_entrypoints.get(image.rsplit(":", 1)[0])
        return entry

    # ------------------------------------------------------------------
    # pod lifecycle hooks

    def _started_pod(self, pod: Pod) -> None:
        if not self._monitor_started:
            self._monitor_started = True
            self._monitor.start()
        container = pod.spec.container(
            constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME
        )
        if container is None:
            return
        if container.command or container.args:
            argv = list(container.command) + list(container.args)
        else:
            entry = self.resolve_image(container.image)
            if entry is None:
                return  # unknown image, no command; stays Pending
            command, args = entry
            argv = list(command) + list(args)
        env = dict(os.environ)
        env.update(self.extra_env)
        for e in container.env:
            env[e.name] = e.value
        env.setdefault("PYTHONUNBUFFERED", "1")
        # Pods run with cwd=workdir; make sure `python -m tf_operator_tpu...`
        # workloads resolve regardless of where the operator was launched from.
        pkg_root = str(Path(__file__).resolve().parents[2])
        parts = env.get("PYTHONPATH", "").split(os.pathsep) if env.get("PYTHONPATH") else []
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join(parts + [pkg_root])
        log_path = self.workdir / f"{pod.metadata.namespace}-{pod.metadata.name}.log"
        try:
            logf = open(log_path, "ab")
            proc = subprocess.Popen(
                argv, env=env, stdout=logf, stderr=subprocess.STDOUT,
                cwd=str(self.workdir), start_new_session=True,
            )
        except OSError as err:
            log.warning("failed to start pod %s: %s", pod.metadata.name, err)
            self._transition(pod, PodPhase.FAILED, exit_code=127)
            return
        self._procs[(pod.metadata.namespace, pod.metadata.name)] = proc
        pod.metadata.annotations["local.tpu-operator.dev/pid"] = str(proc.pid)
        pod.metadata.annotations["local.tpu-operator.dev/log"] = str(log_path)
        self._transition(pod, PodPhase.RUNNING)

    def _stopped_pod(self, pod: Pod) -> None:
        proc = self._procs.pop((pod.metadata.namespace, pod.metadata.name), None)
        if proc is not None and proc.poll() is None:
            try:
                # SIGTERM to the process group, kubelet-style grace.
                os.killpg(proc.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass

    def _transition(self, pod: Pod, phase: PodPhase, exit_code: Optional[int] = None) -> None:
        pod.status.phase = phase
        if pod.status.start_time is None and phase != PodPhase.PENDING:
            pod.status.start_time = clock.now()
        cname = pod.spec.containers[0].name if pod.spec.containers else "tensorflow"
        if not pod.status.container_statuses:
            pod.status.container_statuses = [ContainerStatus(name=cname)]
        cs = pod.status.container_statuses[0]
        cs.running = phase == PodPhase.RUNNING
        if exit_code is not None:
            cs.terminated = True
            cs.exit_code = exit_code
        self._dispatch(self._pod_handlers, EventType.MODIFIED, pod)

    def _monitor_loop(self) -> None:
        while not self._closed:
            for key, proc in list(self._procs.items()):
                rc = proc.poll()
                if rc is None:
                    continue
                self._procs.pop(key, None)
                try:
                    pod = self.get_pod(*key)
                except KeyError:
                    continue
                # Negative returncode = killed by signal N; containers report
                # 128+N (the convention the exit-code classifier expects,
                # ref train_util.go:18-53).
                exit_code = 128 - rc if rc < 0 else rc
                phase = PodPhase.SUCCEEDED if exit_code == 0 else PodPhase.FAILED
                log.info("pod %s exited rc=%s -> %s", key[1], exit_code, phase.value)
                self._transition(pod, phase, exit_code=exit_code)
            time.sleep(0.05)

    # ------------------------------------------------------------------

    def pod_logs(self, namespace: str, name: str) -> str:
        pod = self.get_pod(namespace, name)
        path = pod.metadata.annotations.get("local.tpu-operator.dev/log")
        if not path or not os.path.exists(path):
            return ""
        with open(path, "rb") as f:
            return f.read().decode(errors="replace")

    def close(self) -> None:
        self._closed = True
        for proc in list(self._procs.values()):
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        self._procs.clear()
        marker = getattr(self, "_port_marker", None)
        if marker is not None:
            self._port_marker = None
            try:
                marker.close()  # release the port-block claim
            except OSError:
                pass
