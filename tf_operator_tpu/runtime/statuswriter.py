"""Coalesced TPUJob status writes: keep wire traffic flat as the fleet grows.

Status PUTs are the controller's dominant steady-state write (the informer
collapsed the reads to ~zero, docs/informer-cache.md); at 10k jobs every
avoidable PUT matters.  Three coalescing rules, all per sync pass:

  1. **No-op suppression.**  A pass whose computed status equals what the
     pass read performs no write at all (the reference's DeepEqual guard,
     status.go:207-225).  This is what makes an idle resync backstop tick
     cost zero wire writes per job.
  2. **Transition merging.**  A pass that flips several things at once
     (Created+Running on a fast start, Succeeded+completion-time+count
     flips on finish) still performs exactly ONE write; the extra
     transitions are counted on `tpujob_status_writes_coalesced_total`.
  3. **Stale-read echo suppression.**  The informer can serve a status
     that predates our own last write; recomputing on top of it often
     reproduces exactly what we already wrote.  The writer remembers the
     last-written snapshot per key and skips the redundant PUT (counted as
     coalesced) instead of re-sending it every pass until the watch echo
     lands.

`tpujob_status_writes_total` counts the PUTs that actually went out, so
`writes_total / jobs` is the per-job wire cost the soak bench gates on and
`coalesced_total` is the deterministic evidence the optimization fired.
"""
from __future__ import annotations

import json
from collections import OrderedDict
from typing import Callable, Optional, Tuple

from ..api.types import JobStatus
from ..utils import locks, metrics

# Bound on the per-key last-written-snapshot map: one entry per live job,
# LRU-evicted so a leak of delete events cannot grow it forever.  Eviction
# only costs an extra (correct) write if the key comes back.
MAX_TRACKED_KEYS = 65536


def snapshot_status(status: JobStatus) -> Tuple:
    """Hashable deep snapshot for the DeepEqual guard (times that only
    tick, like last_reconcile_time, are excluded)."""
    return (
        tuple(
            (c.type, c.status, c.reason, c.message) for c in status.conditions
        ),
        tuple(
            sorted(
                (k, v.active, v.succeeded, v.failed)
                for k, v in status.replica_statuses.items()
            )
        ),
        status.start_time,
        status.completion_time,
        # Canonical JSON keeps the snapshot hashable (the doc is a dict);
        # stamping/clearing the plan must count as a status change.
        json.dumps(status.zero_sharding_plan, sort_keys=True)
        if status.zero_sharding_plan is not None else None,
        # Same treatment for the elastic mapping doc: a resize (generation
        # bump, width change, history append) is exactly one transition.
        json.dumps(status.elastic, sort_keys=True)
        if status.elastic is not None else None,
    )


def _transition_count(old: Optional[Tuple], new: Tuple) -> int:
    """How many distinct status transitions separate two snapshots: new or
    changed condition states, plus one for any replica-count/time change.
    Never less than 1 when the snapshots differ — the denominator for
    "N transitions merged into one write"."""
    if old is None:
        return 1
    transitions = len(set(new[0]) - set(old[0]))
    if new[1:] != old[1:]:
        transitions += 1
    return max(1, transitions)


@locks.shared_state
class CoalescingStatusWriter:
    """The one path every TPUJob status PUT takes (rules in the module
    docstring).  One instance per controller replica; shard ownership
    (runtime/shardlease.py) keeps replicas from writing the same key, and
    `forget`/`forget_where` drop snapshots whose keys changed hands.

    `@shared_state`: one writer is shared by every worker thread, so its
    fields feed the dynamic race detector (analysis/racedetect.py) when a
    tracker is installed; in production the decorator costs one global
    read per attribute operation."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self._lock = locks.new_lock("status-writer")
        # key -> snapshot of the status we last PUT, newest last
        self._last: "OrderedDict[str, Tuple]" = OrderedDict()  # guarded-by: _lock
        self._writes = 0  # guarded-by: _lock
        self._coalesced = 0  # guarded-by: _lock

    # ------------------------------------------------------------------
    # the write paths

    def write_if_changed(self, job, old_snapshot: Optional[Tuple]) -> bool:
        """End-of-pass write: PUT `job.status` unless it is a no-op against
        what the pass read (`old_snapshot`) or against what we last wrote
        (stale-read echo).  Returns True when a wire write happened."""
        key = job.key()
        new = snapshot_status(job.status)
        if new == old_snapshot:
            return False  # rule 1: nothing changed, nothing counted
        with self._lock:
            last = self._last.get(key)
        if last is not None and new == last:
            # rule 3: the pass re-derived exactly our own last write from a
            # stale read — the transition already landed once.
            self._count(coalesced=1)
            return False
        baseline = last if last is not None else old_snapshot
        self.cluster.update_job_status(
            job.metadata.namespace, job.metadata.name, job.status
        )
        merged = _transition_count(baseline, new) - 1  # rule 2
        self._remember(key, new, coalesced=merged)
        return True

    def write(self, namespace: str, name: str, status: JobStatus) -> None:
        """Unconditional PUT for the rare out-of-pass writers (Stuck
        marker/clear, validation reject).  Recorded like any other write so
        the next pass's echo suppression stays correct."""
        self.cluster.update_job_status(namespace, name, status)
        self._remember(f"{namespace}/{name}", snapshot_status(status),
                       coalesced=0)

    # ------------------------------------------------------------------
    # bookkeeping

    def _remember(self, key: str, snapshot: Tuple, coalesced: int) -> None:
        with self._lock:
            self._writes += 1
            self._coalesced += coalesced
            self._last[key] = snapshot
            self._last.move_to_end(key)
            while len(self._last) > MAX_TRACKED_KEYS:
                self._last.popitem(last=False)
        metrics.status_writes.labels().inc()
        if coalesced:
            metrics.status_writes_coalesced.labels().inc(coalesced)

    def _count(self, coalesced: int) -> None:
        with self._lock:
            self._coalesced += coalesced
        metrics.status_writes_coalesced.labels().inc(coalesced)

    def forget(self, key: str) -> None:
        """Drop `key`'s snapshot (job deleted, or its shard changed hands —
        another replica may write it now, so our memory of "what the wire
        holds" is no longer trustworthy)."""
        with self._lock:
            self._last.pop(key, None)

    def forget_where(self, predicate: Callable[[str], bool]) -> None:
        """forget() every tracked key matching `predicate` (shard handoff)."""
        with self._lock:
            for key in [k for k in self._last if predicate(k)]:
                del self._last[key]

    def counters(self) -> dict:
        """Per-instance counts (the process-global metrics aggregate across
        every controller a test process creates; tests and /healthz want
        ours)."""
        with self._lock:
            return {"writes": self._writes, "coalesced": self._coalesced}
