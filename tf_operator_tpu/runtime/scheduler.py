"""Gang scheduler: all-or-nothing admission of TPU slice gangs.

The reference delegates gang semantics to Volcano (PodGroup MinMember,
vendor/.../common/job_controller.go:211-239) and trusts the cluster to
enforce them.  Our substrates are the cluster, so this module enforces them:
pods stamped with the gang scheduler name are held unbound (Pending) until

  1. the whole gang is present (count >= PodGroup.min_member), and
  2. the fabric has capacity for the gang — whole slices for slice-shaped
     replicas (via the SliceProvider), chip counts for plain ones

— then every member binds atomically.  A partial TPU slice is useless, so
admission is all-or-nothing by construction; capacity is released when gang
pods are deleted.

Reservations are gang-lifetime: once admitted, a gang keeps its chips and
slices until every member departs.  Restarted pods (deterministic names)
reclaim their original slice host slot; elastic growth packs new pods into
free host slots of held slices before allocating fresh slices.

The pool models the driver-visible fabric (e.g. one v5e-32 = 32 chips).
`google.com/tpu` container requests (injected by defaults from the replica's
topology block) are the unit of accounting for plain pods.

Admission order is a policy queue, not pod-scan order (runtime/policy.py,
docs/scheduling-policy.md): strict priority across classes, weighted fair
share across tenants within a class, FIFO within a tenant — with
conservative backfill (a small gang jumps only when it provably cannot
delay any blocked higher-class gang) and graceful preemption (victims are
drained through the reconciler with exit 143 / reason "GangPreempted" and
requeued; the preemptor admits only after the victims' chips and slices
are verifiably back in the pool).
"""
from __future__ import annotations

import math
import threading
from collections import OrderedDict, deque
from typing import Callable, Deque, Dict, List, Mapping, Optional, Set, Tuple

from ..api import constants
from ..api.core import Event, Pod
from ..api.types import DEFAULT_PRIORITY_CLASS, DEFAULT_TENANT, priority_rank
from ..utils import clock, locks
from ..utils import logging as tpulog
from ..utils import metrics
from . import policy
from .cluster import ClusterInterface, EventType, NotFound
from .slices import (
    Slice,
    SliceProvider,
    SliceState,
    normalize_topology,
    topology_hosts,
)

log = tpulog.logger_for_key("gang-scheduler")

# pod name -> (namespace, slice id, host rank)
SlotMap = Dict[str, Tuple[str, str, int]]

# Keep at most this many (gang, shape) unsatisfiable-warning marks: the set
# is advisory dedup state, and an adversarial churn of doomed gangs must not
# grow scheduler memory without bound.  Oldest marks are evicted first — the
# worst case is a repeated Warning event for an ancient gang, not a leak.
MAX_WARNED_MARKS = 1024

# How a preemption eviction reads on the failed pods.  Mirrors the
# "SlicePreempted" fabric-preemption protocol (reconciler: retryable exit,
# backoffLimit-exempt, job requeues instead of failing); exit 143 is
# SIGTERM's code, the retryable preemption signal (runtime/exit_codes.py).
GANG_PREEMPTED_REASON = "GangPreempted"

# Queue-wait quantiles exported per priority class, over a rolling window.
_WAIT_QUANTILES = (0.5, 0.9, 0.99)
_WAIT_WINDOW = 256


def _pod_replica_order(pod: Pod):
    idx = pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX)
    try:
        return (0, int(idx), pod.metadata.name)
    except (TypeError, ValueError):
        return (1, 0, pod.metadata.name)


def _pod_shape(pod: Pod) -> Tuple[str, str, str]:
    return (
        pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE, ""),
        pod.metadata.annotations.get(constants.ANNOTATION_ACCELERATOR, ""),
        pod.metadata.annotations[constants.ANNOTATION_SLICE_TOPOLOGY],
    )


def pod_chip_request(pod: Pod) -> float:
    total = 0.0
    for container in pod.spec.containers:
        total += float(container.resources.get(constants.TPU_RESOURCE, 0.0))
    return total


class SlicePool:
    """Chip-capacity accounting. capacity None = unlimited."""

    def __init__(self, total_chips: Optional[float] = None) -> None:
        self.total = total_chips
        self.used = 0.0  # guarded-by: _lock
        self._lock = locks.new_lock("slice-pool")

    def try_reserve(self, chips: float) -> bool:
        with self._lock:
            if self.total is not None and self.used + chips > self.total:
                return False
            self.used += chips
            return True

    def release(self, chips: float) -> None:
        with self._lock:
            self.used = max(0.0, self.used - chips)


class GangScheduler:
    """Watches pods; binds complete gangs atomically.

    The substrate must support deferred binding: pods whose
    `spec.scheduler_name` equals the gang scheduler name are created Pending
    and only start when `cluster.bind_pod(ns, name)` is called
    (InMemoryCluster implements this)."""

    def __init__(self, cluster: ClusterInterface,
                 total_chips: Optional[float] = None,
                 scheduler_name: str = constants.GANG_SCHEDULER_NAME,
                 slice_provider: Optional[SliceProvider] = None,
                 retry_interval: float = 30.0,
                 tenant_weights: Optional[Mapping[str, float]] = None,
                 owns_gang: Optional[Callable[[str], bool]] = None) -> None:
        self.cluster = cluster
        self.pool = SlicePool(total_chips)
        self.scheduler_name = scheduler_name
        self.slice_provider = slice_provider
        # Fair-share weights per tenant (policy.policy_order); tenants not
        # listed weigh 1.  Operator-level config, deliberately NOT part of
        # spec.scheduling — a job must not set its own weight.
        self.tenant_weights = dict(tenant_weights) if tenant_weights else {}
        # Shard-ownership gate for admit/evict decisions in a federated
        # deployment: when set, the policy sweep only admits (and therefore
        # only evicts, victims being prior admissions) gangs whose key this
        # instance owns.  The controller wires its owns_key here when it
        # adopts a scheduler that has no gate yet.
        self.owns_gang = owns_gang
        self._stopped = threading.Event()
        # Serializes bind batches across threads (watch dispatch vs the
        # periodic retry sweep).  Binds run outside self._lock by design,
        # but two concurrent bind_pods calls would each snapshot node usage
        # before either posts, overcommitting a node's chips.
        self._bind_lock = locks.new_lock("gang-bind")
        self._lock = locks.new_lock("gang-state")
        # group key -> reserved chips (admitted gangs)
        self._admitted: Dict[str, float] = {}  # guarded-by: _lock
        # group key -> member pod names currently existing
        self._members: Dict[str, Set[str]] = {}  # guarded-by: _lock
        # group key -> slice slot per pod NAME — name-keyed so a restarted
        # pod (deterministic name) reclaims its slice host.  Recorded under
        # the lock at allocation time so preemption handling never depends
        # on annotation writes that happen after the lock is dropped.
        self._slots: Dict[str, SlotMap] = {}  # guarded-by: _lock
        # (group key, accelerator, topology) already warned unsatisfiable.
        # Insertion-ordered so the size bound evicts oldest first; entries
        # clear when the fabric reports a slice of that shape repaired (the
        # shape exists again) and when the gang departs.
        self._warned: "OrderedDict[tuple, bool]" = OrderedDict()  # guarded-by: _lock
        # group key -> policy-layer request recorded at admission, the
        # ground truth for fair-share usage and victim selection.
        self._policy_info: Dict[str, policy.GangRequest] = {}  # guarded-by: _lock
        # victim group key -> preemptor group key, while the victim drains.
        # Suppresses re-eviction for the same shortfall on every sweep the
        # drain's own pod events trigger; cleared when the victim departs.
        self._evicting: Dict[str, str] = {}  # guarded-by: _lock
        # group key -> clock.now() when first seen waiting (queue-wait metric)
        self._wait_started: Dict[str, float] = {}  # guarded-by: _lock
        # priority class -> rolling window of observed queue waits
        self._wait_samples: Dict[str, Deque[float]] = {}  # guarded-by: _lock
        # tenants currently exported on the dominant-share gauge, so a
        # tenant whose gangs all departed reads 0 instead of a stale share
        self._share_tenants: Set[str] = set()  # guarded-by: _lock
        # Policy-sweep re-entrancy: evicting a victim dispatches pod events
        # synchronously, whose departure handling asks for another sweep.
        # The running sweep absorbs those requests by looping instead of
        # recursing (guarded-by: _lock).
        self._sweeping = False
        self._sweep_again = False
        register = getattr(cluster, "register_gang_scheduler", None)
        if register is not None:
            register(scheduler_name)
        cluster.watch_pods(self._on_pod_event)
        if slice_provider is not None:
            slice_provider.watch(self._on_slice_event)
        # Node-side changes (labels added, capacity freed by non-gang pods,
        # new nodes) produce no POD watch events, so event-driven retries
        # alone can strand a waiting gang forever on a quiet cluster.  A
        # periodic sweep re-attempts admission/binding for unbound gang pods;
        # it is idempotent (admission is lock-guarded, binds skip bound pods).
        if retry_interval:
            threading.Thread(
                target=self._retry_loop, args=(retry_interval,),
                daemon=True, name="tpujob-gang-retry",
            ).start()

    def _retry_loop(self, interval: float) -> None:
        while not self._stopped.wait(interval):
            try:
                self._retry_waiting()
            except Exception as exc:  # noqa: BLE001 — keep the sweep alive
                log.warning("periodic gang retry failed: %r", exc)

    def close(self) -> None:
        """Stop the periodic retry sweep (tests / controller shutdown)."""
        self._stopped.set()

    @staticmethod
    def _group_key(pod: Pod) -> Optional[str]:
        group = pod.metadata.annotations.get(constants.GANG_GROUP_ANNOTATION)
        if not group:
            return None
        return f"{pod.metadata.namespace}/{group}"

    def _on_pod_event(self, etype: EventType, pod: Pod) -> None:
        if pod.spec.scheduler_name != self.scheduler_name:
            return
        key = self._group_key(pod)
        if key is None:
            return
        if etype == EventType.ADDED:
            with self._lock:
                self._members.setdefault(key, set()).add(pod.metadata.name)
            # Admission goes through the policy sweep, never directly: a
            # gang completing its member set must still queue behind a
            # blocked higher-priority gang (strict priority would otherwise
            # depend on event arrival order).
            self._retry_waiting()
        elif etype == EventType.DELETED:
            self._handle_departure(key, pod)
        elif etype == EventType.MODIFIED:
            # A terminal pod holds no chips: treat Succeeded/Failed members
            # as departed so completed gangs free the slice.
            from ..api.core import PodPhase

            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                self._handle_departure(key, pod)

    def _handle_departure(self, key: str, pod: Pod) -> None:
        with self._lock:
            members = self._members.get(key)
            if members is not None:
                members.discard(pod.metadata.name)
                if not members:
                    # Gang fully gone: release its reservation.  A partial
                    # departure keeps everything — the slot map retains the
                    # pod's slice host so its restarted namesake reclaims it.
                    chips = self._admitted.pop(key, None)
                    self._members.pop(key, None)
                    self._slots.pop(key, None)
                    self._policy_info.pop(key, None)
                    self._evicting.pop(key, None)
                    self._wait_started.pop(key, None)
                    for mark in [m for m in self._warned if m[0] == key]:
                        del self._warned[mark]
                    if chips:
                        self.pool.release(chips)
                        log.info("released %.0f chips from gang %s", chips, key)
                    # Provider release stays under the lock (ordering
                    # scheduler->provider, same as _allocate_slices): doing
                    # it after dropping the lock races a concurrent
                    # re-admission of the same gang and would free slices
                    # the new incarnation just allocated.
                    if chips is not None and self.slice_provider is not None:
                        self.slice_provider.release(key)
        # Capacity may have freed: retry other waiting gangs.
        self._retry_waiting()

    def _try_admit(self, key: str, namespace: str) -> bool:
        """One admission attempt.  Returns True when the gang holds (or now
        holds) a reservation, False when it is waiting — the policy sweep
        uses the verdict to build its blocked-gang set for backfill."""
        group_name = key.split("/", 1)[1]
        try:
            podgroup = self.cluster.get_podgroup(namespace, group_name)
        except NotFound:
            return False  # controller hasn't synced the PodGroup yet; retried on next event
        from ..api.core import PodPhase

        pods = [
            p for p in self.cluster.list_pods(namespace)
            if self._group_key(p) == key
            and p.status.phase not in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        ]
        unbound = [p for p in pods if not self._is_bound(p)]
        with self._lock:
            admitted = key in self._admitted
        if admitted:
            self._assign_late(key, unbound)
            return True
        request = self._gang_request(key, pods)
        # Atomic check-admit section: the already-admitted check, the chip
        # reservation, and the admitted record must not interleave with a
        # concurrent _try_admit for the same gang (double-reserve would leak
        # pool capacity permanently).  Phase writes are deferred out of the
        # lock — on the k8s backend they are network round-trips.
        assignment: List[tuple] = []
        waiting = False
        wait_seconds = None
        with self._lock:
            if key in self._admitted:
                assignment = None  # lost the race; another thread admitted
            else:
                if len(pods) < podgroup.min_member:
                    return False
                sliced, plain = self._partition_sliced(pods)
                chips = sum(pod_chip_request(p) for p in plain)
                if not self.pool.try_reserve(chips):
                    log.info(
                        "gang %s waiting: %.0f chips requested, %.0f/%s in use",
                        key, chips, self.pool.used, self.pool.total,
                    )
                    waiting = True
                else:
                    granted = self._allocate_slices(key, sliced)
                    if granted is None:
                        # Slice shapes unavailable: whole gang stays Pending —
                        # a partial slice set is as useless as a partial gang.
                        self.pool.release(chips)
                        self._warn_unsatisfiable(key, namespace, group_name, sliced)
                        waiting = True
                    else:
                        assignment = granted
                        self._admitted[key] = chips
                        self._policy_info[key] = request
                        started = self._wait_started.pop(key, None)
                        if started is not None:
                            wait_seconds = max(0.0, clock.now() - started)
        if waiting:
            with self._lock:
                self._wait_started.setdefault(key, clock.now())
            self._set_podgroup_phase(podgroup, "Pending")
            return False
        if assignment is None:
            self._assign_late(key, unbound)
            return True
        # Annotation writes dispatch watch events, so they happen unlocked.
        self._apply_slice_assignment(assignment)
        self._set_podgroup_phase(podgroup, "Running")
        log.info("admitting gang %s (%d pods, %.0f chips)", key, len(pods), chips)
        metrics.admitted_gangs.labels().inc()
        if wait_seconds is not None:
            self._observe_wait(request.policy.priority_class, wait_seconds)
        self._bind_all(unbound)
        return True

    # ------------------------------------------------------------------
    # slice-shaped allocation (runtime/slices.py; no reference analogue)

    def _partition_sliced(self, pods: List[Pod]) -> tuple:
        """Split gang members into slice-shaped ones (annotated with an
        accelerator topology, allocated through the SliceProvider) and plain
        chip-counted ones (the reference's opaque-resource model)."""
        if self.slice_provider is None:
            return [], list(pods)
        sliced: List[Pod] = []
        plain: List[Pod] = []
        for p in pods:
            if p.metadata.annotations.get(constants.ANNOTATION_SLICE_TOPOLOGY):
                sliced.append(p)
            else:
                plain.append(p)
        return sliced, plain

    def _allocate_slices(self, key: str, sliced: List[Pod]):  # requires-lock: _lock
        """All-or-nothing slice allocation for the gang's sliced members.

        Returns the pod->slice assignment [(pod, slice_id, host_rank)] or
        None if any shape is unavailable (everything granted is rolled back).
        One pod == one slice host; pods are grouped per replica type (so the
        packing agrees with the per-type MEGASCALE document the topology
        injector emits) and packed in replica-index order so host ranks
        match process ids.  Caller holds self._lock.
        """
        if not sliced:
            return []
        groups: Dict[tuple, List[Pod]] = {}
        for pod in sliced:
            groups.setdefault(_pod_shape(pod), []).append(pod)
        assignment: List[tuple] = []
        slots: SlotMap = {}
        for (_rtype, accelerator, topology), members in sorted(groups.items()):
            hosts = topology_hosts(topology)
            count = math.ceil(len(members) / hosts)
            granted = self.slice_provider.allocate(key, accelerator, topology, count)
            if granted is None:
                self.slice_provider.release(key)
                log.info(
                    "gang %s waiting: %d x %s/%s slice(s) unavailable",
                    key, count, accelerator, topology,
                )
                return None
            members.sort(key=_pod_replica_order)
            for i, pod in enumerate(members):
                slc = granted[i // hosts]
                assignment.append((pod, slc.id, i % hosts))
                slots[pod.metadata.name] = (
                    pod.metadata.namespace, slc.id, i % hosts
                )
        self._slots[key] = slots
        return assignment

    def _assign_late(self, key: str, unbound: List[Pod]) -> None:
        """Bind late members of an admitted gang — the reservation is
        gang-lifetime.  Plain pods bind against the held chip reservation.
        A sliced pod reclaims its name-keyed slot (a restarted pod returns
        to its slice host); a new name (elastic growth) packs into a free
        host slot of a held slice, allocating fresh slices when none fit.
        Pods whose slice is preempted, or whose shape is unavailable, stay
        Pending — a repair or any departure retries them."""
        assignment: List[tuple] = []
        bind_plain: List[Pod] = []
        with self._lock:
            if key not in self._admitted:
                # The gang departed between the caller's admitted-snapshot
                # and here (its reservation is gone): allocating now would
                # park slices under a dead key forever.  The pods that
                # prompted this call re-enter through fresh admission.
                return
            slots = self._slots.setdefault(key, {})
            fresh: Dict[tuple, List[Pod]] = {}
            for pod in unbound:
                topo = pod.metadata.annotations.get(
                    constants.ANNOTATION_SLICE_TOPOLOGY
                )
                if self.slice_provider is None or not topo:
                    bind_plain.append(pod)
                    continue
                name = pod.metadata.name
                slot = slots.get(name)
                if slot is not None:
                    _ns, slice_id, rank = slot
                    slc = self.slice_provider.get_slice(slice_id)
                    if (slc is not None and slc.holder == key
                            and slc.state == SliceState.ALLOCATED):
                        assignment.append((pod, slice_id, rank))
                        continue
                    if (slc is not None and slc.holder == key
                            and slc.state == SliceState.PREEMPTED):
                        continue  # wait for repair
                    del slots[name]  # stale: slice repaired/released/gone
                fresh.setdefault(_pod_shape(pod), []).append(pod)
            for (_rtype, accelerator, topology), members in sorted(fresh.items()):
                hosts = topology_hosts(topology)
                topo_norm = normalize_topology(topology)
                # Free host slots on held slices of this shape.
                used_ranks: Dict[str, Set[int]] = {}
                for _ns, sid, rank in slots.values():
                    used_ranks.setdefault(sid, set()).add(rank)
                open_slots: List[tuple] = []
                seen_sids: Set[str] = set()
                for _ns, sid, _rank in list(slots.values()):
                    if sid in seen_sids:
                        continue
                    seen_sids.add(sid)
                    slc = self.slice_provider.get_slice(sid)
                    if (slc is None or slc.holder != key
                            or slc.state != SliceState.ALLOCATED
                            or slc.accelerator != accelerator
                            or slc.topology != topo_norm):
                        continue
                    open_slots.extend(
                        (sid, r) for r in range(slc.hosts)
                        if r not in used_ranks.get(sid, set())
                    )
                open_slots.sort()
                need = len(members) - len(open_slots)
                if need > 0:
                    count = math.ceil(need / hosts)
                    granted = self.slice_provider.allocate(
                        key, accelerator, topology, count
                    )
                    if granted is None:
                        log.info(
                            "gang %s late members waiting: %d x %s/%s "
                            "slice(s) unavailable", key, count, accelerator,
                            topology,
                        )
                        continue  # these pods stay Pending
                    open_slots.extend(
                        (s.id, r) for s in granted for r in range(s.hosts)
                    )
                members.sort(key=_pod_replica_order)
                for pod, (sid, rank) in zip(members, open_slots):
                    assignment.append((pod, sid, rank))
                    slots[pod.metadata.name] = (
                        pod.metadata.namespace, sid, rank
                    )
        self._apply_slice_assignment(assignment)
        self._bind_all(bind_plain + [pod for pod, _sid, _rank in assignment])

    # requires-lock: _lock
    def _warn_unsatisfiable(self, key: str, namespace: str, group_name: str,
                            sliced: List[Pod]) -> None:
        """Surface 'this shape can NEVER be satisfied' (vs transient
        capacity waits) as a Warning event on the job.  Caller holds the
        lock; record_event is safe there (no re-entrant pod watch)."""
        for pod in sliced:
            _rtype, accelerator, topology = _pod_shape(pod)
            if self.slice_provider.has_shape(accelerator, topology):
                continue
            mark = (key, accelerator, normalize_topology(topology))
            if mark in self._warned:
                continue
            self._warned[mark] = True
            while len(self._warned) > MAX_WARNED_MARKS:
                self._warned.popitem(last=False)
            self.cluster.record_event(Event(
                object_kind="TPUJob",
                object_name=group_name,
                namespace=namespace,
                event_type="Warning",
                reason="UnschedulableSliceShape",
                message=(
                    f"no slice of shape {accelerator}/{topology} exists in "
                    "the fabric inventory; the gang cannot be admitted"
                ),
            ))

    def _apply_slice_assignment(self, assignment: List[tuple]) -> None:
        for pod, slice_id, host_rank in assignment:
            pod.metadata.annotations[constants.ANNOTATION_SLICE_ID] = slice_id
            pod.metadata.annotations[constants.ANNOTATION_SLICE_HOST] = str(host_rank)
            try:
                self.cluster.update_pod(pod)
            except NotFound:
                pass  # deleted while admitting; departure handling reconciles

    def _on_slice_event(self, slc: Slice, event: str) -> None:
        """Fabric notifications: whole-slice preemption and repair."""
        if event == "repaired":
            # A repaired slice is fresh capacity with no holder; any slot
            # entry still referencing it belongs to a gang whose claim died
            # at preemption (its pods on the slice were failed then).
            # Purge eagerly — left in place the stale entries pollute the
            # gang's host-rank accounting if it ever re-allocates the same
            # slice, and they misrepresent state between events.
            with self._lock:
                for slot_map in self._slots.values():
                    stale = [
                        name for name, (_ns, sid, _rank) in slot_map.items()
                        if sid == slc.id
                    ]
                    for name in stale:
                        del slot_map[name]
                # The fabric proved a slice of this shape exists again, so
                # every "can never be satisfied" verdict for the shape is
                # stale: drop the marks so the next failed admission of
                # those gangs re-evaluates (and re-warns if still true).
                shape = (slc.accelerator, normalize_topology(slc.topology))
                for mark in [m for m in self._warned if (m[1], m[2]) == shape]:
                    del self._warned[mark]
            self._retry_waiting()
            return
        if event != "preempted" or slc.holder is None:
            return
        key = slc.holder
        # Only the pods on the dead slice are failed here; the gang's
        # reservation (including its healthy slices) stays in place until the
        # pods depart — the controller's gang-restart deletes them, the
        # departure path releases everything, and re-admission re-allocates
        # (the preempted slice is out of the pool until repaired).  Releasing
        # eagerly would double-book the healthy slices under live pods.
        # The victim set comes from the slot map written under the admission
        # lock, not from annotations — annotation writes happen after the
        # lock is dropped, so a preemption racing admission would otherwise
        # find nothing to fail.
        with self._lock:
            victims = [
                (ns, name)
                for name, (ns, sid, _rank) in self._slots.get(key, {}).items()
                if sid == slc.id
            ]
        log.info("slice %s preempted: failing %d pod(s) of gang %s on it",
                 slc.id, len(victims), key)
        # Pods on the dead slice terminate with SIGTERM's code (143) — the
        # retryable preemption signal (runtime/exit_codes.py); the
        # controller's gang-restart machinery does the rest.
        from ..api.core import ContainerStatus, PodPhase

        for namespace, name in victims:
            try:
                pod = self.cluster.get_pod(namespace, name)
            except NotFound:
                continue
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            pod.status.phase = PodPhase.FAILED
            pod.status.reason = "SlicePreempted"
            pod.status.message = f"TPU slice {slc.id} was preempted"
            names = [c.name for c in pod.spec.containers] or ["tensorflow"]
            pod.status.container_statuses = [
                ContainerStatus(name=n, terminated=True, exit_code=143)
                for n in names
            ]
            try:
                self.cluster.update_pod_status(pod)
            except NotFound:
                continue

    @staticmethod
    def _is_bound(pod: Pod) -> bool:
        # InMemory/local substrates stamp the bound annotation; the k8s
        # backend binds via the pods/binding subresource, which materializes
        # as spec.nodeName.
        return bool(
            pod.spec.node_name
            or pod.metadata.annotations.get(constants.ANNOTATION_BOUND) == "true"
        )

    def _set_podgroup_phase(self, podgroup, phase: str) -> None:
        """Mutate + persist the PodGroup phase.  InMemoryCluster hands out
        the stored object so mutation alone sticks; remote backends expose
        update_podgroup for the write-back.  Never called under self._lock
        (the write is a network round-trip on the k8s backend), and never
        allowed to raise — a failed phase write must not abort the binds
        that follow it (the phase is observability, not admission state)."""
        if podgroup.phase == phase:
            return
        podgroup.phase = phase
        writer = getattr(self.cluster, "update_podgroup", None)
        if writer is None:
            return
        try:
            writer(podgroup)
        except NotFound:
            pass  # group deleted mid-admission; departure path reconciles
        except Exception as exc:  # noqa: BLE001 — see docstring
            log.warning("podgroup %s phase write failed: %r",
                        podgroup.metadata.name, exc)

    def _bind_all(self, pods: List[Pod]) -> None:
        """Bind every pod, isolating failures: one member's transient bind
        error (5xx, racing 409) must not abort the siblings — a partially
        started gang is the exact state gang scheduling exists to prevent.
        Failed members stay Pending and the periodic retry re-attempts them
        (the gang is already admitted, so _assign_late just re-binds).
        Batches through cluster.bind_pods when the backend has it (one
        node/usage snapshot per gang instead of per member)."""
        if not pods:
            return
        with self._bind_lock:
            batch = getattr(self.cluster, "bind_pods", None)
            if batch is not None:
                try:
                    bound = batch([(p.metadata.namespace, p.metadata.name)
                                   for p in pods])
                    if bound:
                        metrics.bound_gang_pods.labels().inc(int(bound))
                    return
                except Exception as exc:  # noqa: BLE001 — fall back to singles
                    log.warning("batch bind failed (%r); retrying individually",
                                exc)
            for pod in pods:
                self._bind(pod)

    def _bind(self, pod: Pod) -> None:
        binder = getattr(self.cluster, "bind_pod", None)
        if binder is None:
            return
        try:
            bound = binder(pod.metadata.namespace, pod.metadata.name)
            if bound:
                # bind_pod reports NEWLY bound pods (0/None for no-ops), so
                # retry sweeps don't re-count the same pod
                metrics.bound_gang_pods.labels().inc(int(bound))
        except NotFound:
            pass  # deleted between admission snapshot and bind
        except Exception as exc:  # noqa: BLE001 — isolate member failures
            log.warning("bind of %s/%s failed: %r; it stays Pending until "
                        "the next retry", pod.metadata.namespace,
                        pod.metadata.name, exc)

    def _retry_waiting(self) -> None:
        """Run the policy sweep, absorbing re-entrant requests.

        Every capacity or membership change funnels here.  Evicting a
        victim (and admitting a gang) dispatches pod events synchronously
        on the in-memory substrate, and those events' departure handling
        asks for another sweep — the running sweep absorbs the request by
        looping instead of recursing (recursion would both overflow on
        large drains and re-evict for a shortfall already being drained).
        """
        with self._lock:
            if self._sweeping:
                self._sweep_again = True
                return
            self._sweeping = True
        try:
            while True:
                self._sweep_once()
                with self._lock:
                    if not self._sweep_again:
                        break
                    self._sweep_again = False
        finally:
            with self._lock:
                self._sweeping = False
                self._sweep_again = False

    def _sweep_once(self) -> None:
        """One deterministic pass over every gang with unbound pods.

        Deterministic by construction: candidates are rebuilt from a pod
        snapshot and ordered by the policy queue (class rank, then weighted
        fair share, then earliest gang creation, then key) — never by
        pod-list scan order, so two sweeps over the same state attempt the
        same admissions in the same order regardless of how the list is
        returned.  Admitted gangs with Pending members get late assignment
        first (they hold reservations already, so they cannot take anything
        a queued gang is owed); waiting gangs then get admission attempts
        in policy order with conservative backfill; finally the
        highest-priority blocked gang may trigger one eviction round.
        """
        from ..api.core import PodPhase

        pods_by_key: Dict[str, List[Pod]] = {}
        for pod in self.cluster.list_pods():
            if pod.spec.scheduler_name != self.scheduler_name:
                continue
            key = self._group_key(pod)
            if key is None:
                continue
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            pods_by_key.setdefault(key, []).append(pod)
        with self._lock:
            admitted_keys = set(self._admitted)
            usage: Dict[str, float] = {}
            for k in admitted_keys:
                info = self._policy_info.get(k)
                if info is not None:
                    usage[info.tenant] = usage.get(info.tenant, 0.0) + info.chips()
        for key in sorted(k for k in pods_by_key if k in admitted_keys):
            unbound = [p for p in pods_by_key[key] if not self._is_bound(p)]
            if unbound:
                self._assign_late(key, unbound)
        owns = self.owns_gang
        requests = [
            self._gang_request(key, pods)
            for key, pods in pods_by_key.items()
            if key not in admitted_keys
            and any(not self._is_bound(p) for p in pods)
            and (owns is None or owns(key))
        ]
        metrics.waiting_gangs.labels().set(len(requests))
        ordered = policy.policy_order(
            requests, usage, self.pool.total, self.tenant_weights
        )
        now = clock.now()
        with self._lock:
            for req in ordered:
                self._wait_started.setdefault(req.key, now)
        blocked: List[policy.GangRequest] = []
        preemptor: Optional[policy.GangRequest] = None
        for req in ordered:
            higher = [b.dims for b in blocked if b.rank > req.rank]
            if higher and not policy.may_backfill(
                req.dims, higher, self._free_dims(ordered)
            ):
                # Jumping could delay a blocked higher-class gang's earliest
                # feasible admission: the candidate queues behind instead.
                blocked.append(req)
                continue
            if self._try_admit(req.key, req.namespace):
                continue
            if self._is_unsatisfiable(req):
                # A shape that does not exist in the fabric blocks nobody:
                # holding backfill (or evicting victims) for it would
                # deadlock the whole queue behind a gang that can never run.
                continue
            blocked.append(req)
            if preemptor is None:
                preemptor = req  # highest-priority blocked gang (policy order)
        if preemptor is not None:
            self._maybe_preempt(preemptor)
        self._update_share_gauge()

    # ------------------------------------------------------------------
    # policy queue plumbing (runtime/policy.py, docs/scheduling-policy.md)

    def _gang_request(self, key: str, pods: List[Pod]) -> policy.GangRequest:
        """Policy view of a gang from its live pods.  The scheduling knobs
        ride on pod annotations (stamped by the reconciler from
        spec.scheduling); pods without them — older controllers, plain
        manifests — read as the default class/tenant, non-preemptible, so a
        pre-policy deployment queues exactly as it always has."""
        cls = DEFAULT_PRIORITY_CLASS
        tenant = DEFAULT_TENANT
        preemptible = False
        for pod in sorted(pods, key=lambda p: p.metadata.name):
            ann = pod.metadata.annotations
            if (constants.ANNOTATION_PRIORITY_CLASS in ann
                    or constants.ANNOTATION_TENANT in ann
                    or constants.ANNOTATION_PREEMPTIBLE in ann):
                cls = (ann.get(constants.ANNOTATION_PRIORITY_CLASS)
                       or DEFAULT_PRIORITY_CLASS)
                tenant = ann.get(constants.ANNOTATION_TENANT) or DEFAULT_TENANT
                preemptible = ann.get(constants.ANNOTATION_PREEMPTIBLE) == "true"
                break
        dims: policy.Dims = {}
        sliced, plain = self._partition_sliced(pods)
        chips = sum(pod_chip_request(p) for p in plain)
        if chips:
            dims[policy.CHIPS] = chips
        # Whole-slice demand per shape, grouped exactly the way
        # _allocate_slices packs (per replica type), so the feasibility
        # arithmetic matches what admission will actually request.
        groups: Dict[tuple, int] = {}
        for pod in sliced:
            rtype, accel, topo = _pod_shape(pod)
            shape = (rtype, accel, normalize_topology(topo))
            groups[shape] = groups.get(shape, 0) + 1
        for (_rtype, accel, topo), members in groups.items():
            hosts = topology_hosts(topo)
            dim = (accel, topo)
            dims[dim] = dims.get(dim, 0.0) + float(math.ceil(members / hosts))
        created = min(
            (p.metadata.creation_timestamp for p in pods), default=0.0
        )
        namespace = pods[0].metadata.namespace if pods else key.split("/", 1)[0]
        return policy.GangRequest(
            key=key,
            namespace=namespace,
            policy=policy.GangPolicy(
                priority_class=cls,
                rank=priority_rank(cls),
                tenant=tenant,
                preemptible=preemptible,
            ),
            dims=dims,
            created=(created, key),
        )

    def _free_dims(self, requests=()) -> policy.Dims:
        """Currently free capacity per dimension.  The chip dimension is
        absent when the pool is unlimited (absent == unlimited to the
        policy layer); slice shapes always get an entry — 0 both when
        nothing of the shape is free and when the shape does not exist at
        all — so feasibility arithmetic never mistakes 'none free' for
        'unlimited'."""
        free: policy.Dims = {}
        if self.pool.total is not None:
            free[policy.CHIPS] = max(0.0, self.pool.total - self.pool.used)
        if self.slice_provider is not None:
            for slc in self.slice_provider.list_slices():
                shape = (slc.accelerator, normalize_topology(slc.topology))
                free.setdefault(shape, 0.0)
                if slc.state == SliceState.FREE:
                    free[shape] += 1.0
            for req in requests:
                for dim in req.dims:
                    if isinstance(dim, tuple):
                        free.setdefault(dim, 0.0)
        return free

    def _is_unsatisfiable(self, req: policy.GangRequest) -> bool:
        """True when the gang waits on a shape the fabric does not have at
        all (the _warn_unsatisfiable verdict), as opposed to a transient
        capacity wait.  Such a gang never joins the blocked set."""
        with self._lock:
            return any(
                (req.key, dim[0], dim[1]) in self._warned
                for dim in req.dims
                if isinstance(dim, tuple)
            )

    def _maybe_preempt(self, preemptor: policy.GangRequest) -> None:
        """Graceful eviction to unblock the highest-priority blocked gang.

        Victims (chosen by policy.select_victims: preemptible, strictly
        lower class, lowest class first, youngest first) are drained
        through the reconciler — their pods fail with the preemption exit
        protocol — and requeue at their own priority.  The preemptor is
        NOT admitted here: its reservation happens on a later sweep, after
        the victims' departure verifiably returned their chips and slices
        to the pool, and the backfill rule keeps lower-class gangs off the
        freed capacity in the meantime."""
        missing = policy.shortfall(
            preemptor.dims, self._free_dims((preemptor,))
        )
        if not missing:
            return  # blocked on membership (gang still assembling), not capacity
        with self._lock:
            if preemptor.key in self._evicting.values():
                return  # a drain for this preemptor is already in flight
            candidates = [
                info for k, info in self._policy_info.items()
                if k in self._admitted and k not in self._evicting
            ]
            victims = policy.select_victims(
                missing, preemptor.rank, candidates)
            if not victims:
                # even evicting everything eligible leaves it short:
                # evict nobody
                return
            for victim in victims:
                self._evicting[victim.key] = preemptor.key
        for victim in victims:
            self._evict_gang(victim, preemptor)

    def _evict_gang(self, victim: policy.GangRequest,
                    preemptor: policy.GangRequest) -> None:
        """Fail every live pod of the victim gang with the preemption exit
        protocol: phase Failed, reason GangPreempted, exit 143.  The
        controller observes the reason, exempts the job's backoff budget,
        resets its rate-limiter state and requeues it; the departure path
        here releases the gang's chips and slices once the members drain.
        Mirrors the fabric's SlicePreempted flow, with the whole gang as
        the blast radius instead of one slice."""
        from ..api.core import ContainerStatus, PodPhase

        group_name = victim.key.split("/", 1)[1]
        log.info(
            "preempting gang %s (class %s) to admit %s (class %s)",
            victim.key, victim.policy.priority_class,
            preemptor.key, preemptor.policy.priority_class,
        )
        metrics.preemptions.labels(victim.policy.priority_class).inc()
        self.cluster.record_event(Event(
            object_kind="TPUJob",
            object_name=group_name,
            namespace=victim.namespace,
            event_type="Normal",
            reason=GANG_PREEMPTED_REASON,
            message=(
                f"gang evicted for higher-priority gang {preemptor.key} "
                f"(class {preemptor.policy.priority_class}); the job "
                "requeues at its own priority with its backoff budget "
                "untouched"
            ),
        ))
        for pod in self.cluster.list_pods(victim.namespace):
            if self._group_key(pod) != victim.key:
                continue
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            pod.status.phase = PodPhase.FAILED
            pod.status.reason = GANG_PREEMPTED_REASON
            pod.status.message = (
                f"gang preempted for higher-priority gang {preemptor.key}"
            )
            names = [c.name for c in pod.spec.containers] or ["tensorflow"]
            pod.status.container_statuses = [
                ContainerStatus(name=n, terminated=True, exit_code=143)
                for n in names
            ]
            try:
                self.cluster.update_pod_status(pod)
            except NotFound:
                continue

    def _observe_wait(self, priority_class: str, seconds: float) -> None:
        """Fold one admission's queue wait into the per-class rolling
        window and republish the quantile gauges."""
        with self._lock:
            window = self._wait_samples.setdefault(
                priority_class, deque(maxlen=_WAIT_WINDOW)
            )
            window.append(seconds)
            ordered = sorted(window)
        for q in _WAIT_QUANTILES:
            idx = min(len(ordered) - 1, int(q * len(ordered)))
            metrics.gang_queue_wait.labels(
                priority_class, str(q)
            ).set(ordered[idx])

    def _update_share_gauge(self) -> None:
        """Publish each tenant's weighted dominant share of the pool from
        the admitted set; tenants whose gangs all departed read 0 rather
        than their last share."""
        with self._lock:
            usage: Dict[str, float] = {}
            for k in self._admitted:
                info = self._policy_info.get(k)
                if info is not None:
                    usage[info.tenant] = usage.get(info.tenant, 0.0) + info.chips()
            shares = policy.dominant_shares(
                usage, self.pool.total, self.tenant_weights
            )
            stale = self._share_tenants - set(shares)
            self._share_tenants = set(shares)
        for tenant in stale:
            metrics.tenant_dominant_share.labels(tenant).set(0.0)
        for tenant, share in shares.items():
            metrics.tenant_dominant_share.labels(tenant).set(share)
