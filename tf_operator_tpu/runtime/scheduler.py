"""Gang scheduler: all-or-nothing admission of TPU slice gangs.

The reference delegates gang semantics to Volcano (PodGroup MinMember,
vendor/.../common/job_controller.go:211-239) and trusts the cluster to
enforce them.  Our substrates are the cluster, so this module enforces them:
pods stamped with the gang scheduler name are held unbound (Pending) until

  1. the whole gang is present (count >= PodGroup.min_member), and
  2. the fabric has capacity for the gang — whole slices for slice-shaped
     replicas (via the SliceProvider), chip counts for plain ones

— then every member binds atomically.  A partial TPU slice is useless, so
admission is all-or-nothing by construction; capacity is released when gang
pods are deleted.

Reservations are gang-lifetime: once admitted, a gang keeps its chips and
slices until every member departs.  Restarted pods (deterministic names)
reclaim their original slice host slot; elastic growth packs new pods into
free host slots of held slices before allocating fresh slices.

The pool models the driver-visible fabric (e.g. one v5e-32 = 32 chips).
`google.com/tpu` container requests (injected by defaults from the replica's
topology block) are the unit of accounting for plain pods.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Set, Tuple

from ..api import constants
from ..api.core import Event, Pod
from ..utils import locks
from ..utils import logging as tpulog
from ..utils import metrics
from .cluster import ClusterInterface, EventType, NotFound
from .slices import (
    Slice,
    SliceProvider,
    SliceState,
    normalize_topology,
    topology_hosts,
)

log = tpulog.logger_for_key("gang-scheduler")

# pod name -> (namespace, slice id, host rank)
SlotMap = Dict[str, Tuple[str, str, int]]


def _pod_replica_order(pod: Pod):
    idx = pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX)
    try:
        return (0, int(idx), pod.metadata.name)
    except (TypeError, ValueError):
        return (1, 0, pod.metadata.name)


def _pod_shape(pod: Pod) -> Tuple[str, str, str]:
    return (
        pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE, ""),
        pod.metadata.annotations.get(constants.ANNOTATION_ACCELERATOR, ""),
        pod.metadata.annotations[constants.ANNOTATION_SLICE_TOPOLOGY],
    )


def pod_chip_request(pod: Pod) -> float:
    total = 0.0
    for container in pod.spec.containers:
        total += float(container.resources.get(constants.TPU_RESOURCE, 0.0))
    return total


class SlicePool:
    """Chip-capacity accounting. capacity None = unlimited."""

    def __init__(self, total_chips: Optional[float] = None) -> None:
        self.total = total_chips
        self.used = 0.0  # guarded-by: _lock
        self._lock = locks.new_lock("slice-pool")

    def try_reserve(self, chips: float) -> bool:
        with self._lock:
            if self.total is not None and self.used + chips > self.total:
                return False
            self.used += chips
            return True

    def release(self, chips: float) -> None:
        with self._lock:
            self.used = max(0.0, self.used - chips)


class GangScheduler:
    """Watches pods; binds complete gangs atomically.

    The substrate must support deferred binding: pods whose
    `spec.scheduler_name` equals the gang scheduler name are created Pending
    and only start when `cluster.bind_pod(ns, name)` is called
    (InMemoryCluster implements this)."""

    def __init__(self, cluster: ClusterInterface,
                 total_chips: Optional[float] = None,
                 scheduler_name: str = constants.GANG_SCHEDULER_NAME,
                 slice_provider: Optional[SliceProvider] = None,
                 retry_interval: float = 30.0) -> None:
        self.cluster = cluster
        self.pool = SlicePool(total_chips)
        self.scheduler_name = scheduler_name
        self.slice_provider = slice_provider
        self._stopped = threading.Event()
        # Serializes bind batches across threads (watch dispatch vs the
        # periodic retry sweep).  Binds run outside self._lock by design,
        # but two concurrent bind_pods calls would each snapshot node usage
        # before either posts, overcommitting a node's chips.
        self._bind_lock = locks.new_lock("gang-bind")
        self._lock = locks.new_lock("gang-state")
        # group key -> reserved chips (admitted gangs)
        self._admitted: Dict[str, float] = {}  # guarded-by: _lock
        # group key -> member pod names currently existing
        self._members: Dict[str, Set[str]] = {}  # guarded-by: _lock
        # group key -> slice slot per pod NAME — name-keyed so a restarted
        # pod (deterministic name) reclaims its slice host.  Recorded under
        # the lock at allocation time so preemption handling never depends
        # on annotation writes that happen after the lock is dropped.
        self._slots: Dict[str, SlotMap] = {}  # guarded-by: _lock
        # (group key, shape) already warned unsatisfiable
        self._warned: Set[tuple] = set()  # guarded-by: _lock
        register = getattr(cluster, "register_gang_scheduler", None)
        if register is not None:
            register(scheduler_name)
        cluster.watch_pods(self._on_pod_event)
        if slice_provider is not None:
            slice_provider.watch(self._on_slice_event)
        # Node-side changes (labels added, capacity freed by non-gang pods,
        # new nodes) produce no POD watch events, so event-driven retries
        # alone can strand a waiting gang forever on a quiet cluster.  A
        # periodic sweep re-attempts admission/binding for unbound gang pods;
        # it is idempotent (admission is lock-guarded, binds skip bound pods).
        if retry_interval:
            threading.Thread(
                target=self._retry_loop, args=(retry_interval,),
                daemon=True, name="tpujob-gang-retry",
            ).start()

    def _retry_loop(self, interval: float) -> None:
        while not self._stopped.wait(interval):
            try:
                self._retry_waiting()
            except Exception as exc:  # noqa: BLE001 — keep the sweep alive
                log.warning("periodic gang retry failed: %r", exc)

    def close(self) -> None:
        """Stop the periodic retry sweep (tests / controller shutdown)."""
        self._stopped.set()

    @staticmethod
    def _group_key(pod: Pod) -> Optional[str]:
        group = pod.metadata.annotations.get(constants.GANG_GROUP_ANNOTATION)
        if not group:
            return None
        return f"{pod.metadata.namespace}/{group}"

    def _on_pod_event(self, etype: EventType, pod: Pod) -> None:
        if pod.spec.scheduler_name != self.scheduler_name:
            return
        key = self._group_key(pod)
        if key is None:
            return
        if etype == EventType.ADDED:
            with self._lock:
                self._members.setdefault(key, set()).add(pod.metadata.name)
            self._try_admit(key, pod.metadata.namespace)
        elif etype == EventType.DELETED:
            self._handle_departure(key, pod)
        elif etype == EventType.MODIFIED:
            # A terminal pod holds no chips: treat Succeeded/Failed members
            # as departed so completed gangs free the slice.
            from ..api.core import PodPhase

            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                self._handle_departure(key, pod)

    def _handle_departure(self, key: str, pod: Pod) -> None:
        with self._lock:
            members = self._members.get(key)
            if members is not None:
                members.discard(pod.metadata.name)
                if not members:
                    # Gang fully gone: release its reservation.  A partial
                    # departure keeps everything — the slot map retains the
                    # pod's slice host so its restarted namesake reclaims it.
                    chips = self._admitted.pop(key, None)
                    self._members.pop(key, None)
                    self._slots.pop(key, None)
                    if chips:
                        self.pool.release(chips)
                        log.info("released %.0f chips from gang %s", chips, key)
                    # Provider release stays under the lock (ordering
                    # scheduler->provider, same as _allocate_slices): doing
                    # it after dropping the lock races a concurrent
                    # re-admission of the same gang and would free slices
                    # the new incarnation just allocated.
                    if chips is not None and self.slice_provider is not None:
                        self.slice_provider.release(key)
        # Capacity may have freed: retry other waiting gangs.
        self._retry_waiting()

    def _try_admit(self, key: str, namespace: str) -> None:
        group_name = key.split("/", 1)[1]
        try:
            podgroup = self.cluster.get_podgroup(namespace, group_name)
        except NotFound:
            return  # controller hasn't synced the PodGroup yet; retried on next event
        from ..api.core import PodPhase

        pods = [
            p for p in self.cluster.list_pods(namespace)
            if self._group_key(p) == key
            and p.status.phase not in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        ]
        unbound = [p for p in pods if not self._is_bound(p)]
        with self._lock:
            admitted = key in self._admitted
        if admitted:
            self._assign_late(key, unbound)
            return
        # Atomic check-admit section: the already-admitted check, the chip
        # reservation, and the admitted record must not interleave with a
        # concurrent _try_admit for the same gang (double-reserve would leak
        # pool capacity permanently).  Phase writes are deferred out of the
        # lock — on the k8s backend they are network round-trips.
        assignment: List[tuple] = []
        waiting = False
        with self._lock:
            if key in self._admitted:
                assignment = None  # lost the race; another thread admitted
            else:
                if len(pods) < podgroup.min_member:
                    return
                sliced, plain = self._partition_sliced(pods)
                chips = sum(pod_chip_request(p) for p in plain)
                if not self.pool.try_reserve(chips):
                    log.info(
                        "gang %s waiting: %.0f chips requested, %.0f/%s in use",
                        key, chips, self.pool.used, self.pool.total,
                    )
                    waiting = True
                else:
                    granted = self._allocate_slices(key, sliced)
                    if granted is None:
                        # Slice shapes unavailable: whole gang stays Pending —
                        # a partial slice set is as useless as a partial gang.
                        self.pool.release(chips)
                        self._warn_unsatisfiable(key, namespace, group_name, sliced)
                        waiting = True
                    else:
                        assignment = granted
                        self._admitted[key] = chips
        if waiting:
            self._set_podgroup_phase(podgroup, "Pending")
            return
        if assignment is None:
            self._assign_late(key, unbound)
            return
        # Annotation writes dispatch watch events, so they happen unlocked.
        self._apply_slice_assignment(assignment)
        self._set_podgroup_phase(podgroup, "Running")
        log.info("admitting gang %s (%d pods, %.0f chips)", key, len(pods), chips)
        metrics.admitted_gangs.labels().inc()
        self._bind_all(unbound)

    # ------------------------------------------------------------------
    # slice-shaped allocation (runtime/slices.py; no reference analogue)

    def _partition_sliced(self, pods: List[Pod]) -> tuple:
        """Split gang members into slice-shaped ones (annotated with an
        accelerator topology, allocated through the SliceProvider) and plain
        chip-counted ones (the reference's opaque-resource model)."""
        if self.slice_provider is None:
            return [], list(pods)
        sliced: List[Pod] = []
        plain: List[Pod] = []
        for p in pods:
            if p.metadata.annotations.get(constants.ANNOTATION_SLICE_TOPOLOGY):
                sliced.append(p)
            else:
                plain.append(p)
        return sliced, plain

    def _allocate_slices(self, key: str, sliced: List[Pod]):  # requires-lock: _lock
        """All-or-nothing slice allocation for the gang's sliced members.

        Returns the pod->slice assignment [(pod, slice_id, host_rank)] or
        None if any shape is unavailable (everything granted is rolled back).
        One pod == one slice host; pods are grouped per replica type (so the
        packing agrees with the per-type MEGASCALE document the topology
        injector emits) and packed in replica-index order so host ranks
        match process ids.  Caller holds self._lock.
        """
        if not sliced:
            return []
        groups: Dict[tuple, List[Pod]] = {}
        for pod in sliced:
            groups.setdefault(_pod_shape(pod), []).append(pod)
        assignment: List[tuple] = []
        slots: SlotMap = {}
        for (_rtype, accelerator, topology), members in sorted(groups.items()):
            hosts = topology_hosts(topology)
            count = math.ceil(len(members) / hosts)
            granted = self.slice_provider.allocate(key, accelerator, topology, count)
            if granted is None:
                self.slice_provider.release(key)
                log.info(
                    "gang %s waiting: %d x %s/%s slice(s) unavailable",
                    key, count, accelerator, topology,
                )
                return None
            members.sort(key=_pod_replica_order)
            for i, pod in enumerate(members):
                slc = granted[i // hosts]
                assignment.append((pod, slc.id, i % hosts))
                slots[pod.metadata.name] = (
                    pod.metadata.namespace, slc.id, i % hosts
                )
        self._slots[key] = slots
        return assignment

    def _assign_late(self, key: str, unbound: List[Pod]) -> None:
        """Bind late members of an admitted gang — the reservation is
        gang-lifetime.  Plain pods bind against the held chip reservation.
        A sliced pod reclaims its name-keyed slot (a restarted pod returns
        to its slice host); a new name (elastic growth) packs into a free
        host slot of a held slice, allocating fresh slices when none fit.
        Pods whose slice is preempted, or whose shape is unavailable, stay
        Pending — a repair or any departure retries them."""
        assignment: List[tuple] = []
        bind_plain: List[Pod] = []
        with self._lock:
            if key not in self._admitted:
                # The gang departed between the caller's admitted-snapshot
                # and here (its reservation is gone): allocating now would
                # park slices under a dead key forever.  The pods that
                # prompted this call re-enter through fresh admission.
                return
            slots = self._slots.setdefault(key, {})
            fresh: Dict[tuple, List[Pod]] = {}
            for pod in unbound:
                topo = pod.metadata.annotations.get(
                    constants.ANNOTATION_SLICE_TOPOLOGY
                )
                if self.slice_provider is None or not topo:
                    bind_plain.append(pod)
                    continue
                name = pod.metadata.name
                slot = slots.get(name)
                if slot is not None:
                    _ns, slice_id, rank = slot
                    slc = self.slice_provider.get_slice(slice_id)
                    if (slc is not None and slc.holder == key
                            and slc.state == SliceState.ALLOCATED):
                        assignment.append((pod, slice_id, rank))
                        continue
                    if (slc is not None and slc.holder == key
                            and slc.state == SliceState.PREEMPTED):
                        continue  # wait for repair
                    del slots[name]  # stale: slice repaired/released/gone
                fresh.setdefault(_pod_shape(pod), []).append(pod)
            for (_rtype, accelerator, topology), members in sorted(fresh.items()):
                hosts = topology_hosts(topology)
                topo_norm = normalize_topology(topology)
                # Free host slots on held slices of this shape.
                used_ranks: Dict[str, Set[int]] = {}
                for _ns, sid, rank in slots.values():
                    used_ranks.setdefault(sid, set()).add(rank)
                open_slots: List[tuple] = []
                seen_sids: Set[str] = set()
                for _ns, sid, _rank in list(slots.values()):
                    if sid in seen_sids:
                        continue
                    seen_sids.add(sid)
                    slc = self.slice_provider.get_slice(sid)
                    if (slc is None or slc.holder != key
                            or slc.state != SliceState.ALLOCATED
                            or slc.accelerator != accelerator
                            or slc.topology != topo_norm):
                        continue
                    open_slots.extend(
                        (sid, r) for r in range(slc.hosts)
                        if r not in used_ranks.get(sid, set())
                    )
                open_slots.sort()
                need = len(members) - len(open_slots)
                if need > 0:
                    count = math.ceil(need / hosts)
                    granted = self.slice_provider.allocate(
                        key, accelerator, topology, count
                    )
                    if granted is None:
                        log.info(
                            "gang %s late members waiting: %d x %s/%s "
                            "slice(s) unavailable", key, count, accelerator,
                            topology,
                        )
                        continue  # these pods stay Pending
                    open_slots.extend(
                        (s.id, r) for s in granted for r in range(s.hosts)
                    )
                members.sort(key=_pod_replica_order)
                for pod, (sid, rank) in zip(members, open_slots):
                    assignment.append((pod, sid, rank))
                    slots[pod.metadata.name] = (
                        pod.metadata.namespace, sid, rank
                    )
        self._apply_slice_assignment(assignment)
        self._bind_all(bind_plain + [pod for pod, _sid, _rank in assignment])

    # requires-lock: _lock
    def _warn_unsatisfiable(self, key: str, namespace: str, group_name: str,
                            sliced: List[Pod]) -> None:
        """Surface 'this shape can NEVER be satisfied' (vs transient
        capacity waits) as a Warning event on the job.  Caller holds the
        lock; record_event is safe there (no re-entrant pod watch)."""
        for pod in sliced:
            _rtype, accelerator, topology = _pod_shape(pod)
            if self.slice_provider.has_shape(accelerator, topology):
                continue
            mark = (key, accelerator, normalize_topology(topology))
            if mark in self._warned:
                continue
            self._warned.add(mark)
            self.cluster.record_event(Event(
                object_kind="TPUJob",
                object_name=group_name,
                namespace=namespace,
                event_type="Warning",
                reason="UnschedulableSliceShape",
                message=(
                    f"no slice of shape {accelerator}/{topology} exists in "
                    "the fabric inventory; the gang cannot be admitted"
                ),
            ))

    def _apply_slice_assignment(self, assignment: List[tuple]) -> None:
        for pod, slice_id, host_rank in assignment:
            pod.metadata.annotations[constants.ANNOTATION_SLICE_ID] = slice_id
            pod.metadata.annotations[constants.ANNOTATION_SLICE_HOST] = str(host_rank)
            try:
                self.cluster.update_pod(pod)
            except NotFound:
                pass  # deleted while admitting; departure handling reconciles

    def _on_slice_event(self, slc: Slice, event: str) -> None:
        """Fabric notifications: whole-slice preemption and repair."""
        if event == "repaired":
            # A repaired slice is fresh capacity with no holder; any slot
            # entry still referencing it belongs to a gang whose claim died
            # at preemption (its pods on the slice were failed then).
            # Purge eagerly — left in place the stale entries pollute the
            # gang's host-rank accounting if it ever re-allocates the same
            # slice, and they misrepresent state between events.
            with self._lock:
                for slot_map in self._slots.values():
                    stale = [
                        name for name, (_ns, sid, _rank) in slot_map.items()
                        if sid == slc.id
                    ]
                    for name in stale:
                        del slot_map[name]
            self._retry_waiting()
            return
        if event != "preempted" or slc.holder is None:
            return
        key = slc.holder
        # Only the pods on the dead slice are failed here; the gang's
        # reservation (including its healthy slices) stays in place until the
        # pods depart — the controller's gang-restart deletes them, the
        # departure path releases everything, and re-admission re-allocates
        # (the preempted slice is out of the pool until repaired).  Releasing
        # eagerly would double-book the healthy slices under live pods.
        # The victim set comes from the slot map written under the admission
        # lock, not from annotations — annotation writes happen after the
        # lock is dropped, so a preemption racing admission would otherwise
        # find nothing to fail.
        with self._lock:
            victims = [
                (ns, name)
                for name, (ns, sid, _rank) in self._slots.get(key, {}).items()
                if sid == slc.id
            ]
        log.info("slice %s preempted: failing %d pod(s) of gang %s on it",
                 slc.id, len(victims), key)
        # Pods on the dead slice terminate with SIGTERM's code (143) — the
        # retryable preemption signal (runtime/exit_codes.py); the
        # controller's gang-restart machinery does the rest.
        from ..api.core import ContainerStatus, PodPhase

        for namespace, name in victims:
            try:
                pod = self.cluster.get_pod(namespace, name)
            except NotFound:
                continue
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            pod.status.phase = PodPhase.FAILED
            pod.status.reason = "SlicePreempted"
            pod.status.message = f"TPU slice {slc.id} was preempted"
            names = [c.name for c in pod.spec.containers] or ["tensorflow"]
            pod.status.container_statuses = [
                ContainerStatus(name=n, terminated=True, exit_code=143)
                for n in names
            ]
            try:
                self.cluster.update_pod_status(pod)
            except NotFound:
                continue

    @staticmethod
    def _is_bound(pod: Pod) -> bool:
        # InMemory/local substrates stamp the bound annotation; the k8s
        # backend binds via the pods/binding subresource, which materializes
        # as spec.nodeName.
        return bool(
            pod.spec.node_name
            or pod.metadata.annotations.get(constants.ANNOTATION_BOUND) == "true"
        )

    def _set_podgroup_phase(self, podgroup, phase: str) -> None:
        """Mutate + persist the PodGroup phase.  InMemoryCluster hands out
        the stored object so mutation alone sticks; remote backends expose
        update_podgroup for the write-back.  Never called under self._lock
        (the write is a network round-trip on the k8s backend), and never
        allowed to raise — a failed phase write must not abort the binds
        that follow it (the phase is observability, not admission state)."""
        if podgroup.phase == phase:
            return
        podgroup.phase = phase
        writer = getattr(self.cluster, "update_podgroup", None)
        if writer is None:
            return
        try:
            writer(podgroup)
        except NotFound:
            pass  # group deleted mid-admission; departure path reconciles
        except Exception as exc:  # noqa: BLE001 — see docstring
            log.warning("podgroup %s phase write failed: %r",
                        podgroup.metadata.name, exc)

    def _bind_all(self, pods: List[Pod]) -> None:
        """Bind every pod, isolating failures: one member's transient bind
        error (5xx, racing 409) must not abort the siblings — a partially
        started gang is the exact state gang scheduling exists to prevent.
        Failed members stay Pending and the periodic retry re-attempts them
        (the gang is already admitted, so _assign_late just re-binds).
        Batches through cluster.bind_pods when the backend has it (one
        node/usage snapshot per gang instead of per member)."""
        if not pods:
            return
        with self._bind_lock:
            batch = getattr(self.cluster, "bind_pods", None)
            if batch is not None:
                try:
                    bound = batch([(p.metadata.namespace, p.metadata.name)
                                   for p in pods])
                    if bound:
                        metrics.bound_gang_pods.labels().inc(int(bound))
                    return
                except Exception as exc:  # noqa: BLE001 — fall back to singles
                    log.warning("batch bind failed (%r); retrying individually",
                                exc)
            for pod in pods:
                self._bind(pod)

    def _bind(self, pod: Pod) -> None:
        binder = getattr(self.cluster, "bind_pod", None)
        if binder is None:
            return
        try:
            bound = binder(pod.metadata.namespace, pod.metadata.name)
            if bound:
                # bind_pod reports NEWLY bound pods (0/None for no-ops), so
                # retry sweeps don't re-count the same pod
                metrics.bound_gang_pods.labels().inc(int(bound))
        except NotFound:
            pass  # deleted between admission snapshot and bind
        except Exception as exc:  # noqa: BLE001 — isolate member failures
            log.warning("bind of %s/%s failed: %r; it stays Pending until "
                        "the next retry", pod.metadata.namespace,
                        pod.metadata.name, exc)

    def _retry_waiting(self) -> None:
        """Retry admission for every gang with unbound pods — waiting gangs
        get a full admission attempt; admitted gangs get their Pending late
        members (re)assigned (e.g. after a slice repair)."""
        namespaces = {}
        for pod in self.cluster.list_pods():
            key = self._group_key(pod)
            if key is None or pod.spec.scheduler_name != self.scheduler_name:
                continue
            if self._is_bound(pod):
                continue
            namespaces[key] = pod.metadata.namespace
        with self._lock:
            waiting = sum(1 for key in namespaces if key not in self._admitted)
        metrics.waiting_gangs.labels().set(waiting)
        for key, namespace in namespaces.items():
            self._try_admit(key, namespace)
