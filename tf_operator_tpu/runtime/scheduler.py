"""Gang scheduler: all-or-nothing admission of TPU slice gangs.

The reference delegates gang semantics to Volcano (PodGroup MinMember,
vendor/.../common/job_controller.go:211-239) and trusts the cluster to
enforce them.  Our substrates are the cluster, so this module enforces them:
pods stamped with the gang scheduler name are held unbound (Pending) until

  1. the whole gang is present (count >= PodGroup.min_member), and
  2. the slice pool has capacity for the gang's total chip request

— then every member binds atomically.  A partial TPU slice is useless, so
admission is all-or-nothing by construction; capacity is released when gang
pods are deleted.

The pool models the driver-visible fabric (e.g. one v5e-32 = 32 chips).
`google.com/tpu` container requests (injected by defaults from the replica's
topology block) are the unit of accounting.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Set

from ..api import constants
from ..api.core import Pod
from ..utils import logging as tpulog
from .cluster import ClusterInterface, EventType, NotFound

log = tpulog.logger_for_key("gang-scheduler")


def pod_chip_request(pod: Pod) -> float:
    total = 0.0
    for container in pod.spec.containers:
        total += float(container.resources.get(constants.TPU_RESOURCE, 0.0))
    return total


class SlicePool:
    """Chip-capacity accounting. capacity None = unlimited."""

    def __init__(self, total_chips: Optional[float] = None) -> None:
        self.total = total_chips
        self.used = 0.0
        self._lock = threading.Lock()

    def try_reserve(self, chips: float) -> bool:
        with self._lock:
            if self.total is not None and self.used + chips > self.total:
                return False
            self.used += chips
            return True

    def release(self, chips: float) -> None:
        with self._lock:
            self.used = max(0.0, self.used - chips)


class GangScheduler:
    """Watches pods; binds complete gangs atomically.

    The substrate must support deferred binding: pods whose
    `spec.scheduler_name` equals the gang scheduler name are created Pending
    and only start when `cluster.bind_pod(ns, name)` is called
    (InMemoryCluster implements this)."""

    def __init__(self, cluster: ClusterInterface,
                 total_chips: Optional[float] = None,
                 scheduler_name: str = constants.GANG_SCHEDULER_NAME) -> None:
        self.cluster = cluster
        self.pool = SlicePool(total_chips)
        self.scheduler_name = scheduler_name
        self._lock = threading.Lock()
        # group key -> reserved chips (admitted gangs)
        self._admitted: Dict[str, float] = {}
        # group key -> member pod names currently existing
        self._members: Dict[str, Set[str]] = {}
        register = getattr(cluster, "register_gang_scheduler", None)
        if register is not None:
            register(scheduler_name)
        cluster.watch_pods(self._on_pod_event)

    @staticmethod
    def _group_key(pod: Pod) -> Optional[str]:
        group = pod.metadata.annotations.get(constants.GANG_GROUP_ANNOTATION)
        if not group:
            return None
        return f"{pod.metadata.namespace}/{group}"

    def _on_pod_event(self, etype: EventType, pod: Pod) -> None:
        if pod.spec.scheduler_name != self.scheduler_name:
            return
        key = self._group_key(pod)
        if key is None:
            return
        if etype == EventType.ADDED:
            with self._lock:
                self._members.setdefault(key, set()).add(pod.metadata.name)
            self._try_admit(key, pod.metadata.namespace)
        elif etype == EventType.DELETED:
            self._handle_departure(key, pod)
        elif etype == EventType.MODIFIED:
            # A terminal pod holds no chips: treat Succeeded/Failed members
            # as departed so completed gangs free the slice.
            from ..api.core import PodPhase

            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                self._handle_departure(key, pod)

    def _handle_departure(self, key: str, pod: Pod) -> None:
        with self._lock:
            members = self._members.get(key)
            if members is not None:
                members.discard(pod.metadata.name)
                if not members:
                    # Gang fully gone: release its reservation.
                    chips = self._admitted.pop(key, None)
                    self._members.pop(key, None)
                    if chips:
                        self.pool.release(chips)
                        log.info("released %.0f chips from gang %s", chips, key)
        # Capacity may have freed: retry other waiting gangs.
        self._retry_waiting()

    def _try_admit(self, key: str, namespace: str) -> None:
        group_name = key.split("/", 1)[1]
        try:
            podgroup = self.cluster.get_podgroup(namespace, group_name)
        except NotFound:
            return  # controller hasn't synced the PodGroup yet; retried on next event
        from ..api.core import PodPhase

        pods = [
            p for p in self.cluster.list_pods(namespace)
            if self._group_key(p) == key
            and p.status.phase not in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        ]
        unbound = [p for p in pods if not self._is_bound(p)]
        # Atomic check-admit section: the already-admitted check, the chip
        # reservation, and the admitted record must not interleave with a
        # concurrent _try_admit for the same gang (double-reserve would leak
        # pool capacity permanently).
        with self._lock:
            if key in self._admitted:
                admit_late_only = True
            else:
                admit_late_only = False
                if len(pods) < podgroup.min_member:
                    return
                chips = sum(pod_chip_request(p) for p in pods)
                if not self.pool.try_reserve(chips):
                    log.info(
                        "gang %s waiting: %.0f chips requested, %.0f/%s in use",
                        key, chips, self.pool.used, self.pool.total,
                    )
                    podgroup.phase = "Pending"
                    return
                self._admitted[key] = chips
        if admit_late_only:
            # Late members of an admitted gang (e.g. a restarted pod) bind
            # immediately — the reservation is gang-lifetime.
            for pod in unbound:
                self._bind(pod)
            return
        podgroup.phase = "Running"
        log.info("admitting gang %s (%d pods, %.0f chips)", key, len(pods), chips)
        for pod in unbound:
            self._bind(pod)

    @staticmethod
    def _is_bound(pod: Pod) -> bool:
        return pod.metadata.annotations.get("tpu-operator.dev/bound") == "true"

    def _bind(self, pod: Pod) -> None:
        binder = getattr(self.cluster, "bind_pod", None)
        if binder is not None:
            binder(pod.metadata.namespace, pod.metadata.name)

    def _retry_waiting(self) -> None:
        namespaces = {}
        for pod in self.cluster.list_pods():
            key = self._group_key(pod)
            if key is None or pod.spec.scheduler_name != self.scheduler_name:
                continue
            with self._lock:
                if key in self._admitted:
                    continue
            namespaces[key] = pod.metadata.namespace
        for key, namespace in namespaces.items():
            self._try_admit(key, namespace)
