"""Subpackage."""
