"""Exit-code classification for the ExitCode restart policy.

Behavioral contract of the reference's classifier
(/root/reference/vendor/github.com/kubeflow/common/pkg/util/train/train_util.go:18-53):

  retryable:  130 (SIGINT), 137 (SIGKILL), 143 (SIGTERM) — exactly the codes a
              preempted VM produces — plus 138 (SIGUSR1), reserved for
              user-signalled retryable failures.
  permanent:  1, 2, 126, 127, 128, 139 — config/usage errors and SIGSEGV.
  other codes ≥ 129 not listed above are treated as permanent.

TPU note: on preemptible TPU-VM slices the whole gang dies with SIGTERM; the
classifier is what turns that into a JobRestarting cycle instead of JobFailed.
"""

RETRYABLE_EXIT_CODES = frozenset({130, 137, 143, 138})
PERMANENT_EXIT_CODES = frozenset({1, 2, 126, 127, 128, 139})

# The infrastructure-kill subset of the retryable codes: exactly what a
# preempted TPU-VM produces (SIGINT/SIGKILL/SIGTERM).  Restarts caused by
# these are the fabric's fault, not the workload's, so backoff accounting
# exempts them — a crash-looping job and a job riding out preemptions must
# not share a budget.  138 (SIGUSR1, user-signalled retry) stays counted:
# the workload asked for that restart itself.
PREEMPTION_EXIT_CODES = frozenset({130, 137, 143})

# Sentinel used when a failed pod carries no terminated container state
# (ref: pkg/controller.v1/tensorflow/pod.go:124 — 0xbeef default).
UNKNOWN_EXIT_CODE = 0xBEEF


def is_retryable_exit_code(exit_code: int) -> bool:
    return exit_code in RETRYABLE_EXIT_CODES


def is_permanent_exit_code(exit_code: int) -> bool:
    return not is_retryable_exit_code(exit_code)


def is_preemption_exit_code(exit_code: int) -> bool:
    return exit_code in PREEMPTION_EXIT_CODES
