"""Lease-based shard ownership: the federation layer (docs/federation.md).

PR 6 sharded the workqueue so one process could reconcile 1,000+ TPUJobs;
the next 100× cannot come from one Python process ("Exploring the limits of
Concurrency in ML Training on Google TPUs", PAPERS.md).  This module
generalizes the 1-owns-all leader election (`server.LeaderElector` over
`ClusterInterface.try_acquire_lease`) into **per-shard leases**: N controller
replicas split the `shard_for(key, num_shards)` space, each replica syncs
only the shards whose leases it holds, and replica death hands the orphaned
shards to survivors with no lost and no doubly-owned key.

Protocol (all state lives in the cluster's lease store, none is exchanged
replica-to-replica):

  - **Membership.**  Each replica heartbeats one lease named
    `tpu-operator-replica-<identity>` every `renew_period`.  The live
    member set is the holders of unexpired replica leases — a crashed
    replica simply stops renewing and ages out after `lease_duration`.
  - **Deterministic assignment.**  Shard `i`'s desired owner is
    `sorted(members)[i % len(members)]`.  Every replica computes the same
    assignment from the same lease store, so rebalancing needs no
    coordinator: when membership changes, each replica independently
    acquires the shards newly assigned to it and releases the ones that
    are not.
  - **Ownership = an unexpired shard lease.**  A replica acquires/renews
    `tpu-operator-shard-<i>` only while it is the desired owner.
    `owns(i)` answers True only inside the lease it last renewed, MINUS
    `ownership_margin` — so a replica stops claiming a shard strictly
    before the lease can expire under anyone else, and two replicas can
    never both answer True for one shard (the no-doubly-owned half of the
    invariant; `tests/test_schedule_explorer.py` pins it under adversarial
    interleavings).
  - **Handoff.**  Voluntary (rebalance/shutdown): drop from the owned set
    FIRST, then release the lease — the new owner can only acquire after
    we stopped claiming.  Involuntary (crash): the lease expires and the
    new desired owner's next tick acquires it.  Either way the adopter's
    `on_adopt` callback re-enqueues every key of the shard, which is the
    no-lost-key half of the invariant.

Timing uses `clock.now()` throughout (never wall time directly) so the
interleaving explorer can drive lease expiry deterministically under a
FakeClock, exactly as the in-memory lease store does.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..utils import clock, locks
from ..utils import logging as tpulog
from ..utils import metrics

log = tpulog.logger_for_key("shardlease")

SHARD_LEASE_PREFIX = "tpu-operator-shard-"
REPLICA_LEASE_PREFIX = "tpu-operator-replica-"


@dataclass
class ShardLeaseConfig:
    """Tuning knobs for shard-lease federation (server --shard-lease-*)."""

    # shard count — MUST equal the controller's workqueue shard count so
    # lease ownership and queue routing agree on shard_for(key)
    num_shards: int = 1
    # seconds a shard/replica lease lives without renewal; crash-failover
    # latency is bounded by this
    lease_duration: float = 15.0
    # seconds between renew/rebalance ticks; must be well under
    # lease_duration or a healthy replica loses its own shards
    renew_period: float = 5.0
    # owns() answers False this many seconds BEFORE the lease expires, so a
    # late renewal can never overlap a peer's expiry-based adoption.
    # Clamped to a quarter of lease_duration so short (test/chaos) leases
    # keep a usable ownership window.
    ownership_margin: float = 1.0

    def effective_margin(self) -> float:
        return min(self.ownership_margin, self.lease_duration / 4.0)


def shard_lease_name(shard: int) -> str:
    return f"{SHARD_LEASE_PREFIX}{shard}"


class ShardLeaseManager:
    """One replica's view of the shard-lease protocol above.

    `tick()` is the whole protocol — heartbeat membership, compute the
    deterministic assignment, acquire/renew desired shards, drop the rest —
    and is safe to call directly (the explorer scenarios do); `start()`
    runs it on a `tpujob-shardlease` thread every `renew_period`.
    `on_adopt(shard)` / `on_drop(shard)` fire outside every internal lock,
    after the owned set already reflects the change."""

    def __init__(
        self,
        cluster,
        identity: str,
        config: Optional[ShardLeaseConfig] = None,
        on_adopt: Optional[Callable[[int], None]] = None,
        on_drop: Optional[Callable[[int], None]] = None,
    ) -> None:
        self.cluster = cluster
        self.identity = identity
        self.config = config or ShardLeaseConfig()
        self.on_adopt = on_adopt
        self.on_drop = on_drop
        self._lock = locks.new_lock("shard-lease")
        # shard -> expiry (clock.now() domain) of OUR last successful renew
        self._owned: Dict[int, float] = {}  # guarded-by: _lock
        self._adoptions = 0  # guarded-by: _lock
        self._drops = 0  # guarded-by: _lock
        # member list as of the last tick, for report(): /healthz must not
        # pay (or hang on) a wire LIST of leases per poll
        self._members_cache: List[str] = [identity]  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False

    # ------------------------------------------------------------------
    # membership + assignment

    def members(self) -> List[str]:
        """Sorted live replica identities (unexpired replica leases), always
        including self.  A substrate without list_leases federates as a
        fleet of one — every shard is ours, the solo-controller behavior."""
        holders = {self.identity}
        list_leases = getattr(self.cluster, "list_leases", None)
        if list_leases is not None:
            try:
                # `or {}`: a substrate inheriting ClusterInterface's bare
                # `...` stub returns None — treat that like the method
                # being absent (fleet of one), not as an error to log
                # every renew tick.
                leases = list_leases(prefix=REPLICA_LEASE_PREFIX) or {}
                holders.update(leases.values())
            except Exception as err:  # noqa: BLE001 — stale view beats a dead tick
                log.warning("listing replica leases failed: %s", err)
        return sorted(holders)

    @staticmethod
    def desired_owner(shard: int, members: List[str]) -> str:
        """The deterministic assignment every replica computes identically:
        round-robin over the sorted member list."""
        return members[shard % len(members)]

    # ------------------------------------------------------------------
    # the protocol tick

    def tick(self) -> None:
        """One renew/rebalance pass (see module docstring)."""
        cfg = self.config
        try:
            self.cluster.try_acquire_lease(
                REPLICA_LEASE_PREFIX + self.identity, self.identity,
                cfg.lease_duration)
        except Exception as err:  # noqa: BLE001 — membership heartbeat is best-effort per tick
            log.warning("replica lease heartbeat failed: %s", err)
        members = self.members()
        with self._lock:
            self._members_cache = list(members)
        adopted: List[int] = []
        dropped: List[int] = []
        held_now = 0
        for shard in range(cfg.num_shards):
            desired = self.desired_owner(shard, members) == self.identity
            acquired = False
            # Expiry computed from a timestamp taken BEFORE the acquire
            # call goes out: the store stamps its own expiry no earlier
            # than this, so claiming asked_at+duration can only
            # under-claim — never claim ownership past the store's own
            # expiry.  (Stamping after the call is a real split-brain
            # window: time that passes DURING the acquire would extend our
            # local claim beyond the lease a peer sees expire — the
            # interleaving explorer's shard-lease scenario catches exactly
            # this.)
            asked_at = clock.now()
            expiry = asked_at + cfg.lease_duration
            if desired:
                try:
                    acquired = self.cluster.try_acquire_lease(
                        shard_lease_name(shard), self.identity,
                        cfg.lease_duration)
                except Exception as err:  # noqa: BLE001 — a failed renew is a drop, not a crash
                    log.warning("shard %d lease renew failed: %s", shard, err)
            # One critical section per shard decides everything about
            # _owned — check and act are never split across acquisitions.
            release_needed = False
            with self._lock:
                entry = self._owned.get(shard)
                # "Held" means we never stopped CLAIMING it: the recorded
                # expiry (minus margin — owns()'s own rule) was still in
                # the future when this tick asked.  An entry that lapsed
                # (a stalled renew thread, say) does NOT count: workers
                # already began absorbing its keys on the ownership fence,
                # so a successful re-acquire below must be a full adoption
                # (on_adopt replays the shard) — treating it as a renewal
                # would strand every key absorbed during the lapse until
                # the next resync backstop tick.
                held = (entry is not None
                        and asked_at < entry - cfg.effective_margin())
                if acquired:
                    self._owned[shard] = expiry
                    if not held:
                        adopted.append(shard)
                        self._adoptions += 1
                elif entry is not None and desired and held:
                    # Renew failed (wire blip, throttle) while OUR store
                    # lease is still unexpired: no peer can acquire it
                    # before that expiry, so keep claiming and retry next
                    # tick.  Dropping here would purge the shard's queue
                    # and force a full adoption replay per transient blip
                    # (a fleet-wide replay storm at 10k jobs); if renews
                    # keep failing, owns() lapses at expiry−margin on its
                    # own — the same fence a wedged renew thread gets —
                    # and the next tick takes the drop branch below.
                    pass
                elif entry is not None:
                    # The assignment moved the shard away, or the entry
                    # already lapsed.  Stop claiming NOW, and never leave
                    # a lapsed entry behind (it would inflate the held
                    # gauge and turn the eventual re-acquire into a
                    # silent renewal).  Order matters on the voluntary
                    # path: drop from _owned first (owns() flips False),
                    # THEN release the lease outside the lock so the new
                    # owner can acquire — the reverse order would let two
                    # replicas answer owns()=True at once.
                    del self._owned[shard]
                    dropped.append(shard)
                    self._drops += 1
                    release_needed = not desired
                held_now = len(self._owned)
            if release_needed:
                self._release(shard_lease_name(shard))
        metrics.shard_leases_held.labels(self.identity).set(float(held_now))
        for shard in dropped:
            metrics.shard_drops.labels(self.identity).inc()
            self._fire(self.on_drop, shard)
        for shard in adopted:
            metrics.shard_adoptions.labels(self.identity).inc()
            self._fire(self.on_adopt, shard)

    def _fire(self, callback: Optional[Callable[[int], None]], shard: int) -> None:
        if callback is None:
            return
        try:
            callback(shard)
        except Exception as err:  # noqa: BLE001 — a callback error must not kill the renew loop
            log.warning("shard %d ownership callback failed: %s", shard, err)

    def _release(self, name: str) -> None:
        release = getattr(self.cluster, "release_lease", None)
        if release is None:
            return  # the lease simply expires; expiry-based handoff covers it
        try:
            release(name, self.identity)
        except Exception as err:  # noqa: BLE001 — expiry is the backstop
            log.warning("releasing lease %s failed: %s", name, err)

    # ------------------------------------------------------------------
    # ownership queries

    def owns(self, shard: int) -> bool:
        """True only while OUR lease on `shard` is unexpired with margin to
        spare.  This is the fence every enqueue and every worker pop checks:
        once it flips False, nothing new is synced on this shard even if the
        renew thread is wedged."""
        now = clock.now()
        with self._lock:
            expiry = self._owned.get(shard)
        return (expiry is not None
                and now < expiry - self.config.effective_margin())

    def owned_shards(self) -> List[int]:
        """Shards owns() currently answers True for (sorted)."""
        now = clock.now()
        with self._lock:
            snapshot = dict(self._owned)
        margin = self.config.effective_margin()
        return sorted(s for s, exp in snapshot.items() if now < exp - margin)

    def report(self) -> dict:
        """Federation section of the deep health report.  `members` is the
        LAST TICK's view, not a fresh read: report() serves /healthz, and a
        wire LIST here would couple probe latency to the apiserver — the
        hang-coupling the watchdog machinery deliberately avoids."""
        with self._lock:
            adoptions, drops = self._adoptions, self._drops
            members = list(self._members_cache)
        return {
            "identity": self.identity,
            "num_shards": self.config.num_shards,
            "owned": self.owned_shards(),
            "members": members,
            "adoptions": adoptions,
            "drops": drops,
            "lease_duration_seconds": self.config.lease_duration,
            "renew_period_seconds": self.config.renew_period,
        }

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        """First tick runs synchronously so a fresh replica owns its share
        before the controller's workers start; then the renew loop takes
        over.  Idempotent."""
        if self._thread is not None and self._thread.is_alive():
            return
        self.tick()
        thread = threading.Thread(target=self._loop,
                                  name="tpujob-shardlease", daemon=True)
        self._thread = thread
        thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.config.renew_period):
            try:
                self.tick()
            except Exception as err:  # noqa: BLE001 — the renew loop must outlive any tick
                log.warning("shard lease tick failed: %s", err)

    def stop(self, release: bool = True) -> None:
        """Stop renewing.  `release=True` (graceful shutdown) hands every
        owned shard back immediately so survivors adopt without waiting out
        the lease; `release=False` models a crash — the leases age out.
        Idempotent: the second call is a no-op, so a test that crash-stops
        the manager before controller.stop() keeps crash semantics."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
        with self._lock:
            owned = list(self._owned)
            self._owned.clear()
        metrics.shard_leases_held.labels(self.identity).set(0.0)
        if release:
            for shard in owned:
                self._release(shard_lease_name(shard))
            # Leave the membership too: peers recompute the assignment
            # without us on their next tick and adopt the released shards
            # immediately instead of waiting out the replica lease.
            self._release(REPLICA_LEASE_PREFIX + self.identity)
