"""Deterministic fault injection for the control plane.

The reference operator never tests its failure regime — client-go's retrying
RESTClient and the informer relist machinery are trusted to absorb apiserver
flakiness.  On preemptible TPU-VM slices that flakiness is the common case
(arxiv 2011.03641 §5; VirtualFlow, arxiv 2009.09523), so this framework makes
it a first-class, reproducible test input:

  - FaultPlan: the schedule.  Either seeded (a private random.Random decides
    per call whether and which fault fires) or scripted (an explicit list of
    Fault-or-None decisions consumed in order).  Same seed + same call
    sequence => same faults.
  - FaultInjector: the tap.  KubeClient consults it once per request attempt
    (for_request) and once per watch stream (for_watch); FaultyCluster
    consults it per ClusterInterface call.  Every injected fault is appended
    to `trace`, so a failing chaos run prints exactly what was injected and
    replays from its seed or from FaultPlan(script=injector.replay_script()).
  - FaultyCluster: a ClusterInterface delegate injecting the same faults at
    the method-call boundary, for chaos over in-memory substrates where no
    HTTP exists.

Fault kinds (the `kind` strings are a contract with runtime/k8s.py's
_apply_fault / stream_watch):

  request: "reset" (connection reset; before_send picks the phase),
           "throttle" (429 + Retry-After), "server_error" (500/503),
           "latency" (stall, then proceed), "conflict" (409)
  watch:   "watch_drop" (stream ends after N events), "gone" (410 Expired
           => relist)
"""
from __future__ import annotations

import random
import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

from ..utils import locks
from .cluster import AlreadyExists, TooManyRequests

FAULT_RESET = "reset"
FAULT_THROTTLE = "throttle"
FAULT_SERVER_ERROR = "server_error"
FAULT_LATENCY = "latency"
FAULT_CONFLICT = "conflict"
FAULT_WATCH_DROP = "watch_drop"
FAULT_GONE = "gone"

REQUEST_KINDS: Tuple[str, ...] = (
    FAULT_RESET, FAULT_THROTTLE, FAULT_SERVER_ERROR, FAULT_LATENCY,
    FAULT_CONFLICT,
)
WATCH_KINDS: Tuple[str, ...] = (FAULT_WATCH_DROP, FAULT_GONE)


@dataclass
class Fault:
    """One injected failure, fully parameterized (no randomness left)."""

    kind: str
    status: int = 0
    retry_after: Optional[float] = None
    latency: float = 0.0
    before_send: bool = True
    after_events: int = 1  # watch_drop: events served before the cut
    message: str = "injected fault"


@dataclass
class FaultRecord:
    """One trace entry: what fired, where, in injection order."""

    seq: int
    scope: str  # "request" | "watch" | "cluster"
    op: str     # HTTP verb, or the ClusterInterface method name
    path: str
    fault: Fault


@dataclass
class FaultRule:
    """A targeted, deterministic fault: fire `fault` on every consult whose
    op/path match the given regexes (empty = match anything), up to `times`
    consults (None = forever).  Rules are what chaos tests use to pin a
    failure to one object — "this job's pod creates always 500", "this
    job's get hangs once" — which seeded randomness cannot express.  Rules
    are consulted before the seeded/scripted schedule; a non-matching
    consult falls through to it."""

    fault: Fault
    op: str = ""             # regex over the verb / ClusterInterface method
    path: str = ""           # regex over the path / call detail
    scope: str = "request"   # "request" (also cluster calls) | "watch"
    times: Optional[int] = None
    fired: int = 0           # mutated under the owning plan's lock

    def matches(self, op: str, path: str) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return (re.search(self.op, op) is not None
                and re.search(self.path, path) is not None)


class FaultPlan:
    """Seeded-or-scripted fault schedule.

    Seeded mode: each request consult fires a fault with probability `rate`
    (watch consults: `watch_rate`), kind drawn uniformly from `kinds` /
    `watch_kinds`, parameters drawn from the given ranges.  `max_faults`
    caps total injections so an unlucky seed cannot starve a run forever.

    Scripted mode: `script` entries are consumed in order, split by scope:
    a plain Fault (or None) feeds request consults; a ("watch", Fault)
    tuple feeds watch consults (("request"|"cluster", Fault) tuples are
    accepted too — the shape FaultInjector.replay_script() produces), so a
    replayed schedule lands at the same layer it originally fired at.
    """

    def __init__(self, seed: Optional[int] = None, rate: float = 0.1,
                 watch_rate: float = 0.0,
                 kinds: Sequence[str] = REQUEST_KINDS,
                 watch_kinds: Sequence[str] = WATCH_KINDS,
                 max_faults: Optional[int] = None,
                 retry_after_range: Tuple[float, float] = (0.01, 0.05),
                 latency_range: Tuple[float, float] = (0.005, 0.02),
                 script: Optional[Sequence[Optional[Fault]]] = None,
                 rules: Optional[Sequence[FaultRule]] = None) -> None:
        self.seed = seed
        self.rules: List[FaultRule] = list(rules or ())  # guarded-by: _lock
        self.rate = float(rate)
        self.watch_rate = float(watch_rate)
        self.kinds = tuple(kinds)
        self.watch_kinds = tuple(watch_kinds)
        self.max_faults = max_faults
        self.retry_after_range = retry_after_range
        self.latency_range = latency_range
        self._script: Optional[List[Optional[Fault]]] = None
        self._watch_script: Optional[List[Fault]] = None
        if script is not None:
            self._script, self._watch_script = [], []
            for entry in script:
                if isinstance(entry, tuple):
                    scope, fault = entry
                    if scope == "watch":
                        self._watch_script.append(fault)
                    else:
                        self._script.append(fault)
                else:
                    self._script.append(entry)
        self._rng = random.Random(seed)
        self._injected = 0  # guarded-by: _lock
        self._lock = locks.new_lock("fault-plan")

    def _spent(self) -> bool:
        return self.max_faults is not None and self._injected >= self.max_faults

    def _make(self, kind: str) -> Fault:
        if kind == FAULT_RESET:
            return Fault(FAULT_RESET, before_send=self._rng.random() < 0.5,
                         message="injected connection reset")
        if kind == FAULT_THROTTLE:
            return Fault(FAULT_THROTTLE, status=429,
                         retry_after=round(
                             self._rng.uniform(*self.retry_after_range), 4),
                         message="injected apiserver throttle")
        if kind == FAULT_SERVER_ERROR:
            return Fault(FAULT_SERVER_ERROR,
                         status=self._rng.choice((500, 503)),
                         message="injected server error")
        if kind == FAULT_LATENCY:
            return Fault(FAULT_LATENCY,
                         latency=self._rng.uniform(*self.latency_range),
                         message="injected latency")
        if kind == FAULT_CONFLICT:
            return Fault(FAULT_CONFLICT, status=409,
                         message="injected write conflict")
        if kind == FAULT_WATCH_DROP:
            return Fault(FAULT_WATCH_DROP,
                         after_events=self._rng.randint(1, 5),
                         message="injected watch drop")
        if kind == FAULT_GONE:
            return Fault(FAULT_GONE, status=410,
                         message="injected 410: watch history expired")
        raise ValueError(f"unknown fault kind {kind!r}")

    def _rule_fault(self, scope: str, op: str, path: str) -> Optional[Fault]:  # requires-lock: _lock
        for rule in self.rules:
            if rule.scope == scope and rule.matches(op, path):
                rule.fired += 1
                return rule.fault
        return None

    def next_request_fault(self, op: str, path: str) -> Optional[Fault]:
        with self._lock:
            fault = self._rule_fault("request", op, path)
            if fault is None:
                if self._script is not None:
                    fault = self._script.pop(0) if self._script else None
                elif self._spent() or not self.kinds or self._rng.random() >= self.rate:
                    fault = None
                else:
                    fault = self._make(self._rng.choice(self.kinds))
            if fault is not None:
                self._injected += 1
            return fault

    def next_watch_fault(self, path: str) -> Optional[Fault]:
        with self._lock:
            fault = self._rule_fault("watch", "WATCH", path)
            if fault is not None:
                self._injected += 1
                return fault
            if self._watch_script is not None:
                fault = (self._watch_script.pop(0)
                         if self._watch_script else None)
                if fault is not None:
                    self._injected += 1
                return fault
            if (self._spent() or not self.watch_kinds
                    or self._rng.random() >= self.watch_rate):
                return None
            fault = self._make(self._rng.choice(self.watch_kinds))
            self._injected += 1
            return fault


class FaultInjector:
    """The tap KubeClient/FaultyCluster consult; records every injection."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self.trace: List[FaultRecord] = []  # guarded-by: _lock
        self._lock = locks.new_lock("fault-trace")

    def _record(self, scope: str, op: str, path: str,
                fault: Optional[Fault]) -> Optional[Fault]:
        if fault is not None:
            with self._lock:
                self.trace.append(FaultRecord(
                    seq=len(self.trace), scope=scope, op=op, path=path,
                    fault=fault,
                ))
        return fault

    def for_request(self, method: str, path: str) -> Optional[Fault]:
        return self._record(
            "request", method, path,
            self.plan.next_request_fault(method, path))

    def for_watch(self, path: str) -> Optional[Fault]:
        return self._record(
            "watch", "WATCH", path, self.plan.next_watch_fault(path))

    def for_cluster_call(self, method_name: str,
                         detail: Optional[str] = None) -> Optional[Fault]:
        """`detail` (when FaultyCluster can derive one) is the call's object
        path — "default/jobname" or "default/jobname-worker-0" — so
        FaultRules can target one object and the trace names what was hit."""
        path = detail or method_name
        return self._record(
            "cluster", method_name, path,
            self.plan.next_request_fault(method_name, path))

    def describe(self) -> str:
        """Human-readable trace for chaos failure reports — paste-able next
        to the printed seed."""
        with self._lock:
            trace = list(self.trace)
        lines = [f"seed={self.plan.seed} injected={len(trace)}"]
        for rec in trace:
            lines.append(
                f"  #{rec.seq} [{rec.scope}] {rec.op} {rec.path}: "
                f"{rec.fault.kind}"
                + (f" status={rec.fault.status}" if rec.fault.status else "")
            )
        return "\n".join(lines)

    def replay_script(self) -> List[Tuple[str, Fault]]:
        """The injected faults in order as (scope, fault) entries — feed to
        FaultPlan(script=...) to replay this exact schedule against the
        same call sequence, each fault at the layer it originally hit.
        Snapshot under the lock: a chaos test reads the script while the
        controller's threads may still be injecting."""
        with self._lock:
            return [(rec.scope, rec.fault) for rec in self.trace]


# ClusterInterface methods FaultyCluster intercepts.  Watches, events and
# leases pass through: events are best-effort by contract, and faulting the
# watch registration itself would blind the controller in a way no real
# substrate failure does (streams fail mid-flight instead — a k8s-layer
# concern, exercised via KubeClient's for_watch).
_FAULTED_PREFIXES = (
    "create_", "get_", "list_", "update_", "patch_", "delete_", "evict_",
    "bind_",
)
_PASSTHROUGH = {"list_events"}


def _call_detail(args: Tuple[Any, ...], kwargs: dict) -> Optional[str]:
    """Best-effort object path for a ClusterInterface call: string args
    joined ("default/name" for (namespace, name) signatures), or the
    metadata of an object argument ("default/name-worker-0" for
    create_pod(pod)).  None when nothing identifying is present."""
    parts: List[str] = []
    for arg in list(args) + list(kwargs.values()):
        if isinstance(arg, str):
            parts.append(arg)
        else:
            meta = getattr(arg, "metadata", None)
            if meta is not None:
                parts.append(f"{meta.namespace}/{meta.name}")
    return "/".join(parts) or None


class FaultyCluster:
    """ClusterInterface delegate that injects plan faults per method call.

    Chaos for in-memory/local substrates, where there is no HTTP seam: the
    controller sees the same exception shapes the k8s backend would surface
    after retry exhaustion (ConnectionError, TooManyRequests, RuntimeError,
    AlreadyExists), so its requeue/expectation handling is exercised without
    an apiserver.  Latency faults stall the call, then let it through.
    """

    def __init__(self, inner: Any, injector: FaultInjector,
                 sleep=None) -> None:
        import time as _time

        self._inner = inner
        self._injector = injector
        self._sleep = sleep or _time.sleep

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._inner, name)
        if (not callable(attr) or name in _PASSTHROUGH
                or not name.startswith(_FAULTED_PREFIXES)):
            return attr

        def faulted(*args: Any, **kwargs: Any) -> Any:
            fault = self._injector.for_cluster_call(
                name, _call_detail(args, kwargs))
            if fault is not None:
                self._raise(fault, name)
            return attr(*args, **kwargs)

        return faulted

    def _raise(self, fault: Fault, name: str) -> None:
        if fault.kind == FAULT_LATENCY:
            self._sleep(fault.latency)
            return
        if fault.kind == FAULT_RESET:
            raise ConnectionResetError(f"{fault.message} ({name})")
        if fault.kind == FAULT_THROTTLE:
            raise TooManyRequests(f"{fault.message} ({name})",
                                  retry_after=fault.retry_after)
        if fault.kind == FAULT_CONFLICT:
            raise AlreadyExists(f"{fault.message} ({name})")
        raise RuntimeError(f"{fault.status}: {fault.message} ({name})")
