"""Generic job reconcile engine.

This is our own rebuild of the vendored kubeflow/common job-controller runtime
(/root/reference/vendor/github.com/kubeflow/common/pkg/controller.v1/common/),
preserving its behavioral contract (SURVEY.md §2.3, §7 stage 2):

  - ReconcileJobs master algorithm (job.go:72-252): terminal-state cleanup
    ordering → backoff/deadline enforcement → gang sync → per-replica-type pod
    and service reconciliation → status computation → DeepEqual-guarded write.
  - Pod "slices" indexed by the replica-index label (pod.go:281-318), create
    missing indices, delete out-of-range indices (dynamic scale down).
  - Headless service per replica with the same naming scheme (service.go).
  - Gang scheduling: PodGroup with MinMember = total replicas, lifecycle tied
    to job terminal state (job_controller.go:211-239, job.go:117-125).

Job-type-specific behavior (cluster-spec injection, master-role labeling,
exit-code restarts, success rules) plugs in through `JobPlugin` — the analogue
of the 15-method ControllerInterface (vendor/.../apis/common/v1/interface.go:10-73).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ..api import constants
from ..api.core import (
    Event,
    ObjectMeta,
    Pod,
    PodGroup,
    PodPhase,
    Service,
    ServicePort,
)
from ..api.types import (
    CleanPodPolicy,
    JobStatus,
    ReplicaSpec,
    ReplicaStatus,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    TPUJobSpec,
    effective_replicas,
    effective_total_replicas,
    elastic_bounds,
    elastic_status_doc,
    is_elastic,
    zero_sharding_plan_doc,
)
from ..analysis.hlo import admission_memory_check
from ..utils import clock, locks
from ..utils import logging as tpulog
from ..utils import metrics
from . import conditions
from .cluster import AlreadyExists, ClusterInterface, NotFound
from .control import PodControlInterface, ServiceControlInterface
from .expectations import Expectations, expectation_key
from .statuswriter import CoalescingStatusWriter, snapshot_status


class JobPlugin:
    """Job-type plugin contract (ref: interface.go:10-73).

    The generic engine calls these hooks; TPUJobController implements them.
    """

    controller_name: str = "generic-job-controller"

    def set_cluster_spec(self, job: TPUJob, pod: Pod, rtype: ReplicaType, index: int) -> None:
        """Inject topology env into the pod (ref: SetClusterSpec, tensorflow.go:85-139)."""

    def is_master_role(
        self, replicas: Dict[ReplicaType, ReplicaSpec], rtype: ReplicaType, index: int
    ) -> bool:
        """(ref: controller.go:409-416)"""
        return False

    def update_job_status(
        self,
        job: TPUJob,
        replicas: Dict[ReplicaType, ReplicaSpec],
        status: JobStatus,
        pods: List[Pod],
        restarting_this_pass: bool,
    ) -> None:
        """Compute success/failure/running conditions (ref: status.go:57-204).

        `pods` is the already-listed/claimed pod set of this pass (the
        reference threads the same view through); `restarting_this_pass` is
        true iff reconcile_pods deleted a pod for a retryable failure in THIS
        pass — the per-sync restart signal that suppresses JobFailed."""

    def on_pod_created(self, job: TPUJob, rtype: ReplicaType) -> None:
        """Metric/event hook."""

    def pod_failed_is_retryable(self, job: TPUJob, rspec: ReplicaSpec, pod: Pod, exit_code: int) -> bool:
        """Whether an ExitCode-policy failure should trigger a restart."""
        from .exit_codes import is_retryable_exit_code

        return is_retryable_exit_code(exit_code)

    def usable_slice_hosts(
        self, job: TPUJob, accelerator: str, topology: str
    ) -> Optional[int]:
        """Host capacity an elastic group of this slice shape could run on
        right now: hosts of FREE slices plus hosts of slices this job
        already holds.  None means unknown (no slice provider wired into
        this deployment) — the engine then never grows, only spec resizes
        and preemption shrinks apply."""
        return None


@dataclass
class ReconcilerConfig:
    """(ref: JobControllerConfiguration, job_controller.go:60-77)"""

    reconciler_sync_loop_period: float = 15.0
    enable_gang_scheduling: bool = False
    gang_scheduler_name: str = constants.GANG_SCHEDULER_NAME
    # "podgroup": all-or-nothing admission via PodGroup + the in-process
    # gang scheduler (runtime/scheduler.py; PodGroup shape ref: SyncPodGroup,
    # job_controller.go:211-239).  "volcano": same PodGroup, but pods carry
    # the reference's exact gang shapes — schedulerName "volcano" + the
    # scheduling.k8s.io/group-name annotation (pod.go:43,52-53,472-488) — so
    # a cluster-installed Volcano enforces admission and no in-process
    # scheduler runs.  "pdb": default scheduler + PodDisruptionBudget
    # guarding voluntary evictions (ref: SyncPdb, job_controller.go:242-316).
    gang_mechanism: str = "podgroup"


@dataclass
class ReconcileResult:
    """What a sync decided, for observability/tests."""

    terminal: bool = False
    failed_reason: str = ""
    requeue_after: Optional[float] = None
    # did this pass PUT a status to the wire?  The controller's quiescence
    # tracker uses this: a pass that wrote nothing AND left expectations
    # satisfied is an idle job the event-driven resync backstop may skip.
    wrote_status: bool = False
    # did this pass stamp a new elastic generation and drain a gang for it?
    resized: bool = False


# Pod failure reason the gang scheduler stamps on whole-slice preemption
# victims (runtime/scheduler.py _on_slice_event); the elastic engine and the
# backoff exemption key off it.
SLICE_PREEMPTED_REASON = "SlicePreempted"

# Pod failure reason the gang scheduler stamps when it evicts a whole gang
# to admit a higher-priority one (runtime/scheduler.py _evict_gang,
# docs/scheduling-policy.md).
GANG_PREEMPTED_REASON = "GangPreempted"

# Both flavors share the preemption contract: the restart is the operator's
# (or the fabric's) doing, not the workload's, so it is backoff-exempt and
# the controller resets the job's rate-limiter state on requeue.  Preempted
# jobs requeue; they never Fail.
PREEMPTION_REASONS = frozenset({SLICE_PREEMPTED_REASON, GANG_PREEMPTED_REASON})

# Resize-history entries kept in status.elastic (newest last): enough to
# audit a burst of preempt/repair cycles without growing status unboundedly.
ELASTIC_HISTORY_LIMIT = 20

# Fleet-wide {job key: (mapped, resizing)} virtual-replica counts behind the
# tpujob_virtual_replicas gauge.  Gauges carry absolute values, so each pass
# republishes the sums instead of inc/dec deltas (idempotent under the
# event-driven resync's repeated passes).
_virtual_replica_lock = locks.new_lock("virtual-replica-gauge")
_virtual_replica_states: Dict[str, Tuple[int, int]] = {}  # guarded-by: _virtual_replica_lock


def _publish_virtual_replicas(
    job_key: str, mapped: Optional[int], resizing: int
) -> None:
    """Record one job's virtual-replica split and republish the fleet sums.
    mapped=None drops the job (terminal/deleted)."""
    with _virtual_replica_lock:
        # Access seam for the dynamic race detector: the dict is shared
        # across every reconciling thread, and this one call marks the
        # whole read-modify-republish as a write access to it.
        locks.track_access(_virtual_replica_states, "entries", True)
        if mapped is None:
            _virtual_replica_states.pop(job_key, None)
        else:
            _virtual_replica_states[job_key] = (mapped, resizing)
        snapshot = list(_virtual_replica_states.values())
    metrics.virtual_replicas.labels("mapped").set(
        sum(m for m, _ in snapshot)
    )
    metrics.virtual_replicas.labels("resizing").set(
        sum(r for _, r in snapshot)
    )


def _memory_infeasibility(spec: TPUJobSpec) -> Optional[str]:
    """First infeasible replica group's reason, or None.  Pure spec math
    (analysis/hlo.admission_memory_check) — groups that declare no
    tpu.deviceMemoryGB/modelParams budget are never rejected."""
    for rspec in spec.replica_specs.values():
        if rspec is None or rspec.tpu is None:
            continue
        reason = admission_memory_check(rspec.tpu)
        if reason:
            return reason
    return None


def gen_labels(job_name: str) -> Dict[str, str]:
    """(ref: GenLabels, job_controller.go:201-209 — '/' replaced with '-')"""
    return {
        constants.LABEL_GROUP_NAME: constants.API_GROUP,
        constants.LABEL_JOB_NAME: job_name.replace("/", "-"),
    }


def gen_general_name(job_name: str, rtype: str, index: int) -> str:
    """Pod/service naming '<job>-<rtype>-<index>' (ref: common/pod.go:447)."""
    return f"{job_name}-{rtype.lower()}-{index}".replace("/", "-")


def calculate_pod_slice_size(pods: List[Pod], replicas: int) -> int:
    """(ref: calculatePodSliceSize, common/pod.go:303-318)"""
    size = 0
    for pod in pods:
        try:
            index = int(pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX, -1))
        except ValueError:
            continue
        size = max(size, index + 1)
    return max(size, replicas)


def _index_slices(objs, replicas: int):
    """Bucket labeled objects by replica-index into a list sized
    max(maxIndex+1, replicas) (ref: GetPodSlices common/pod.go:281-300 and
    GetServiceSlices common/service.go:166-200 — one shared impl here)."""
    slices = [[] for _ in range(calculate_pod_slice_size(objs, replicas))]
    for obj in objs:
        raw = obj.metadata.labels.get(constants.LABEL_REPLICA_INDEX)
        try:
            index = int(raw)
        except (TypeError, ValueError):
            continue
        if 0 <= index < len(slices):
            slices[index].append(obj)
    return slices


def get_pod_slices(pods: List[Pod], replicas: int) -> List[List[Pod]]:
    return _index_slices(pods, replicas)


def get_service_slices(services: List[Service], replicas: int) -> List[List[Service]]:
    return _index_slices(services, replicas)


def filter_for_replica_type(objs, rtype: ReplicaType):
    """(ref: FilterPodsForReplicaType, common/pod.go:257-276)"""
    want = rtype.value.lower()
    return [
        o
        for o in objs
        if o.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "").lower() == want
    ]


def get_port_from_job(spec: TPUJobSpec, rtype: ReplicaType) -> int:
    """Port of the well-known container port (ref: GetPortFromJob,
    service.go:256-274; pkg/.../util.go:29-42)."""
    rspec = spec.replica_specs.get(rtype)
    if rspec is not None:
        container = rspec.template.container(
            constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME
        )
        if container is not None:
            for port in container.ports:
                if port.name == constants.DEFAULT_PORT_NAME:
                    return port.container_port
    return constants.DEFAULT_PORT


def update_job_replica_statuses(status: JobStatus, rtype: ReplicaType, pod: Pod) -> None:
    """(ref: updateJobReplicaStatuses, common/pod.go + initializeReplicaStatuses)"""
    rs = status.replica_statuses.setdefault(rtype.value, ReplicaStatus())
    if pod.status.phase == PodPhase.RUNNING:
        rs.active += 1
    elif pod.status.phase == PodPhase.SUCCEEDED:
        rs.succeeded += 1
    elif pod.status.phase == PodPhase.FAILED:
        rs.failed += 1


def get_container_exit_code(pod: Pod, container_names=(
    constants.DEFAULT_CONTAINER_NAME, constants.ALT_CONTAINER_NAME
)) -> int:
    """Terminated exit code of the operator container, 0xbeef if unknown
    (ref: pkg/controller.v1/tensorflow/pod.go:124-133)."""
    from .exit_codes import UNKNOWN_EXIT_CODE

    for cs in pod.status.container_statuses:
        if cs.name in container_names and cs.terminated and cs.exit_code is not None:
            return cs.exit_code
    return UNKNOWN_EXIT_CODE


class JobReconciler:
    """The generic engine (ref: JobController, common/job_controller.go:83-140)."""

    def __init__(
        self,
        cluster: ClusterInterface,
        pod_control: PodControlInterface,
        service_control: ServiceControlInterface,
        plugin: JobPlugin,
        config: Optional[ReconcilerConfig] = None,
        reads: Optional[Any] = None,
        status_writer: Optional[CoalescingStatusWriter] = None,
    ) -> None:
        self.cluster = cluster
        self.pod_control = pod_control
        self.service_control = service_control
        self.plugin = plugin
        self.config = config or ReconcilerConfig()
        self.expectations = Expectations()
        # Every status PUT goes through the coalescing writer
        # (runtime/statuswriter.py): no-op suppression, per-pass transition
        # merging, stale-informer-read echo suppression.  Shared with the
        # controller so its Stuck-marker writes keep the same bookkeeping.
        self.status_writer = status_writer or CoalescingStatusWriter(cluster)
        # The read path: an informer cache (runtime/informer.py) when the
        # controller runs one, else the cluster itself.  Only the list verbs
        # the per-sync hot path issues go through it; every write — and the
        # gang/PDB bookkeeping — stays on the wire.  Stale reads are safe
        # because the expectations cache gates syncs until this view has
        # observed our own creations/deletions (ref: controller.go:319).
        self.reads = reads if reads is not None else cluster

    # ------------------------------------------------------------------
    # object ownership

    def get_pods_for_job(self, job: TPUJob) -> List[Pod]:
        """Label-selected pods, claimed by owner UID; orphans with matching
        labels are claimed (ref: GetPodsForJob + ControllerRefManager,
        common/pod.go:219-254).  Claiming is a per-pass decision, NOT an
        in-place adoption write: the listed objects are shared informer/
        store state, and stamping a job uid onto them would persist a
        controller-local fiction the apiserver never saw — a later job
        recreated under the same name (new uid) would then find the cached
        pods "owned" by the dead uid and refuse to claim them.  The
        reference adopts by PATCHing ownerReferences server-side; until we
        do that, an orphan is simply claimed again each pass."""
        selector = gen_labels(job.metadata.name)
        pods = self.reads.list_pods(namespace=job.metadata.namespace, selector=selector)
        return [
            pod for pod in pods
            if not pod.metadata.owner_uid
            or pod.metadata.controlled_by(job.kind, job.metadata.uid)
        ]

    def get_services_for_job(self, job: TPUJob) -> List[Service]:
        selector = gen_labels(job.metadata.name)
        services = self.reads.list_services(
            namespace=job.metadata.namespace, selector=selector
        )
        return [
            s
            for s in services
            if not s.metadata.owner_uid or s.metadata.controlled_by(job.kind, job.metadata.uid)
        ]

    # ------------------------------------------------------------------
    # the master algorithm (ref: ReconcileJobs, common/job.go:72-252)

    def reconcile_job(self, job: TPUJob) -> ReconcileResult:
        log = tpulog.logger_for_job(job)
        old_status = _snapshot_status(job.status)
        job.status.last_reconcile_time = clock.now()
        result = ReconcileResult()

        pods = self.get_pods_for_job(job)
        services = self.get_services_for_job(job)
        replicas = job.spec.replica_specs

        if conditions.is_finished(job.status):
            # Terminal: cleanup, flip active counts, write status once.
            # (ref: job.go:107-143)
            self.delete_pods_and_services(job, pods)
            ttl = job.spec.run_policy.ttl_seconds_after_finished
            ttl_remaining = self.cleanup_job(job)
            if ttl is not None and ttl_remaining is None:
                # TTL expired: the job object itself was just deleted.
                result.terminal = True
                return result
            if ttl_remaining is not None:
                # Re-sync when the TTL expires (ref: job.go:316-323 requeue).
                result.requeue_after = ttl_remaining
            if self.config.enable_gang_scheduling:
                self.delete_gang(job)
            if conditions.is_succeeded(job.status):
                for rs in job.status.replica_statuses.values():
                    rs.succeeded += rs.active
                    rs.active = 0
            if is_elastic(job):
                _publish_virtual_replicas(job.key(), None, 0)
            result.terminal = True
            result.wrote_status = self._write_status_if_changed(job, old_status)
            return result

        # Job-level limits (ref: job.go:159-214).  Memory feasibility runs
        # first: a layout whose analytic per-device lower bound (analysis/
        # hlo.py, cross-checked against the compiled-HLO measurement) cannot
        # fit the declared tpu.deviceMemoryGB budget is rejected at
        # admission — before any pod exists to OOM (ROADMAP item 2).
        failure_reason = ""
        failure_message = ""
        infeasible = _memory_infeasibility(job.spec)
        if infeasible:
            failure_reason = "MemoryInfeasible"
            failure_message = (
                f"TPUJob {job.metadata.name} rejected at admission: "
                f"{infeasible}")
        elif self.past_backoff_limit(job, pods):
            failure_reason = "BackoffLimitExceeded"
            failure_message = f"TPUJob {job.metadata.name} has failed because it has reached the specified backoff limit"
        elif self.past_active_deadline(job):
            failure_reason = "DeadlineExceeded"
            failure_message = f"TPUJob {job.metadata.name} has failed because it was active longer than specified deadline"

        if failure_reason:
            self.cluster.record_event(
                Event(
                    object_kind=job.kind,
                    object_name=job.metadata.name,
                    namespace=job.metadata.namespace,
                    event_type="Warning",
                    reason=failure_reason,
                    message=failure_message,
                )
            )
            self.delete_pods_and_services(job, pods)
            if self.config.enable_gang_scheduling:
                self.delete_gang(job)
            conditions.update_job_conditions(
                job.status, conditions.JobConditionType.FAILED, failure_reason, failure_message
            )
            if job.status.completion_time is None:
                job.status.completion_time = clock.now()
            metrics.jobs_failed.labels().inc()
            if is_elastic(job):
                _publish_virtual_replicas(job.key(), None, 0)
            result.terminal = True
            result.failed_reason = failure_reason
            result.wrote_status = self._write_status_if_changed(job, old_status)
            return result

        # Elastic resize arc (docs/elasticity.md): detect a mapped-width
        # change — preemption shrink, repair/grow, spec resize — stamp the
        # new virtual→physical mapping doc and drain the old gang.  Runs
        # BEFORE sync_gang so the PodGroup min_member refresh in this same
        # pass gates admission at the new width, and the drained pods are
        # dropped from this pass's view so every index is recreated at the
        # new width below (not double-deleted next pass).
        resizing_this_pass, drained = self._reconcile_elastic(job, pods)
        result.resized = resizing_this_pass
        if drained:
            pods = [p for p in pods if p.metadata.name not in drained]

        # Gang scheduling: ensure the PodGroup exists before any pod
        # (ref: job.go:217-223; all-or-nothing slice allocation).
        if self.config.enable_gang_scheduling:
            self.sync_gang(job)

        # Mirror the spec's ZeRO weight-update strategy into status so the
        # chosen layout is a searchable artifact (AMP planner, ROADMAP #3);
        # cleared when the knob turns off.  The coalescing writer treats a
        # changed plan as a status transition, so this costs one write when
        # it changes and zero while it is stable.
        job.status.zero_sharding_plan = zero_sharding_plan_doc(job.spec)

        # Fresh replica-status accounting for this pass
        # (ref: initializeReplicaStatuses, common/status.go).
        job.status.replica_statuses = {}
        for rtype in replicas:
            job.status.replica_statuses[rtype.value] = ReplicaStatus()

        restarting_this_pass = False
        for rtype, rspec in replicas.items():
            if self.reconcile_pods(job, pods, rtype, rspec, replicas):
                restarting_this_pass = True
            self.reconcile_services(job, services, rtype, rspec)

        # A resizing pass looks like a restart to the status engine: the
        # drained gang must not read as a failure while the resized one
        # comes up.
        self.plugin.update_job_status(
            job, replicas, job.status, pods,
            restarting_this_pass or resizing_this_pass,
        )
        # The resized gang runs again: retract Resizing to False in place
        # (condition history keeps the arc visible), mirroring how terminal
        # conditions flip Running rather than removing it.
        if (
            not resizing_this_pass
            and conditions.is_running(job.status)
            and conditions.has_condition(
                job.status, conditions.JobConditionType.RESIZING
            )
        ):
            generation = int((job.status.elastic or {}).get("generation") or 0)
            conditions.clear_condition(
                job.status,
                conditions.JobConditionType.RESIZING,
                "RunningResized",
                f"TPUJob {job.metadata.name} is running at resize "
                f"generation {generation}",
            )
        # Same retract shape for Preempted: once the requeued gang runs
        # again the condition flips False in place (history keeps the
        # eviction visible) instead of being removed.
        if (
            not restarting_this_pass
            and conditions.is_running(job.status)
            and conditions.has_condition(
                job.status, conditions.JobConditionType.PREEMPTED
            )
        ):
            conditions.clear_condition(
                job.status,
                conditions.JobConditionType.PREEMPTED,
                "RunningAfterPreemption",
                f"TPUJob {job.metadata.name} is running again after "
                "gang preemption",
            )
        if is_elastic(job):
            total_virtual = sum(
                elastic_bounds(rs)[2]
                for rs in job.spec.replica_specs.values()
                if rs.elastic is not None
            )
            mid_resize = conditions.has_condition(
                job.status, conditions.JobConditionType.RESIZING
            )
            _publish_virtual_replicas(
                job.key(),
                0 if mid_resize else total_virtual,
                total_virtual if mid_resize else 0,
            )
        result.wrote_status = self._write_status_if_changed(job, old_status)
        # ActiveDeadlineSeconds enforcement: re-arm the wakeup on EVERY
        # pass, not only when start_time is first set (the plugin hook,
        # ref: status.go:78-86).  The workqueue coalesces delayed
        # deliveries to the earliest pending deadline per key, so a
        # one-shot far-future arm can be displaced by a sooner retry; with
        # every pass re-arming, whichever delivery runs first restores the
        # deadline wakeup.  The periodic resync loop remains the restart
        # backstop.
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is not None and job.status.start_time is not None:
            remaining = deadline - (clock.now() - job.status.start_time)
            if remaining > 0 and (result.requeue_after is None
                                  or remaining < result.requeue_after):
                result.requeue_after = remaining
        log.debug("reconcile complete")
        return result

    # ------------------------------------------------------------------
    # elastic virtual replicas (no reference analogue — VirtualFlow-style
    # virtual-device indirection, docs/elasticity.md)

    def _reconcile_elastic(
        self, job: TPUJob, pods: List[Pod]
    ) -> Tuple[bool, Set[str]]:
        """Decide the physical width P of every elastic replica group.

        spec.replicas stays the FIXED virtual width V of the group; P
        floats in [minReplicas, maxReplicas] and virtual replica j runs on
        physical replica j % P.  Transitions, in priority order per group:

          SpecResized     — the current spec bounds no longer admit the
                            stored width (user edited replicas/min/max):
                            adopt the clamped width.
          SlicePreempted  — whole-slice preemption failed `lost` physical
                            replicas and P-lost >= min: shrink to P-lost
                            instead of dying.  Below the floor the group
                            HOLDS its width and waits for repair (the
                            ordinary retryable-restart path recreates the
                            pods, which pend until capacity returns).
          SliceRepaired   — capacity reappeared and P < max: grow to
                            min(max, usable hosts).

        Any transition bumps the resize generation, appends history,
        raises the Resizing condition, and drains EVERY pod of the resized
        groups — the TF_CONFIG/topology world changes for all members, so
        a partial drain would leave survivors addressing dead peers.
        Returns (resized, names of drained pods).
        """
        if not is_elastic(job):
            return False, set()
        log = tpulog.logger_for_job(job)
        doc = elastic_status_doc(job)
        prior = job.status.elastic if isinstance(job.status.elastic, dict) else {}
        prior_groups = prior.get("groups") or {}
        transitions = []  # (rtype, from_width, to_width, reason)

        for rtype, rspec in job.spec.replica_specs.items():
            if rspec.elastic is None:
                continue
            lo, hi, virtual = elastic_bounds(rspec)
            group = doc["groups"][rtype.value]
            current = int(group["physical"])
            prior_width = (prior_groups.get(rtype.value) or {}).get("physical")
            if prior_width is None:
                continue  # first pass: initial stamp only, no transition
            if int(prior_width) != current:
                # elastic_status_doc clamps the stored width to the live
                # spec bounds, so a difference here IS a spec resize.
                transitions.append((rtype, int(prior_width), current, "SpecResized"))
                continue

            lost = {
                pod.metadata.labels.get(constants.LABEL_REPLICA_INDEX)
                for pod in filter_for_replica_type(pods, rtype)
                if pod.status.phase == PodPhase.FAILED
                and pod.status.reason == SLICE_PREEMPTED_REASON
            }
            lost.discard(None)
            if lost:
                target = current - len(lost)
                if target >= lo:
                    group["physical"] = target
                    group["assignment"] = {
                        str(j): j % target for j in range(virtual)
                    }
                    transitions.append(
                        (rtype, current, target, SLICE_PREEMPTED_REASON)
                    )
                else:
                    log.info(
                        "elastic %s: %d replicas preempted but width %d is "
                        "below floor %d; holding and waiting for repair",
                        rtype.value, len(lost), target, lo,
                    )
                continue

            if current < hi and rspec.tpu is not None and rspec.tpu.topology:
                capacity = self.plugin.usable_slice_hosts(
                    job, rspec.tpu.accelerator, rspec.tpu.topology
                )
                if capacity is not None:
                    target = min(hi, int(capacity))
                    if target > current:
                        group["physical"] = target
                        group["assignment"] = {
                            str(j): j % target for j in range(virtual)
                        }
                        transitions.append(
                            (rtype, current, target, "SliceRepaired")
                        )

        drained: Set[str] = set()
        if transitions:
            doc["generation"] = int(doc.get("generation") or 0) + 1
            history = doc.setdefault("history", [])
            for rtype, frm, to, reason in transitions:
                history.append({
                    "generation": doc["generation"],
                    "group": rtype.value,
                    "from": frm,
                    "to": to,
                    "reason": reason,
                    "time": clock.now(),
                })
                metrics.resizes.labels(reason).inc()
                log.info(
                    "elastic %s: resizing %d -> %d (%s), generation %d",
                    rtype.value, frm, to, reason, doc["generation"],
                )
            del history[:-ELASTIC_HISTORY_LIMIT]
            for rtype, _, _, _ in transitions:
                for pod in filter_for_replica_type(pods, rtype):
                    self._delete_pod(job, rtype, pod)
                    drained.add(pod.metadata.name)
            summary = "; ".join(
                f"{rtype.value} {frm}->{to} ({reason})"
                for rtype, frm, to, reason in transitions
            )
            conditions.update_job_conditions(
                job.status,
                conditions.JobConditionType.RESIZING,
                "JobResizing",
                f"TPUJob {job.metadata.name} is resizing: {summary}",
            )
            self.cluster.record_event(Event(
                object_kind=job.kind,
                object_name=job.metadata.name,
                namespace=job.metadata.namespace,
                event_type="Normal",
                reason="JobResizing",
                message=f"Resizing to generation {doc['generation']}: {summary}",
            ))
        job.status.elastic = doc
        return bool(transitions), drained

    # ------------------------------------------------------------------
    # pods (ref: TF override ReconcilePods, pkg/.../pod.go:64-160, atop
    # common/pod.go slice machinery)

    def reconcile_pods(
        self,
        job: TPUJob,
        all_pods: List[Pod],
        rtype: ReplicaType,
        rspec: ReplicaSpec,
        replicas: Dict[ReplicaType, ReplicaSpec],
    ) -> bool:
        """Returns True if a retryable-failure restart happened this pass."""
        log = tpulog.logger_for_replica(job, rtype)
        pods = filter_for_replica_type(all_pods, rtype)
        # Elastic groups run at the mapped PHYSICAL width from the resize
        # doc, not the virtual spec width; non-elastic groups are untouched.
        if rspec.elastic is not None:
            num_replicas = effective_replicas(job, rtype)
        else:
            num_replicas = int(rspec.replicas or 0)
        slices = get_pod_slices(pods, num_replicas)
        gang_restart = False
        restarted = False
        deleted_names = set()

        def delete(pod: Pod) -> None:
            self._delete_pod(job, rtype, pod)
            deleted_names.add(pod.metadata.name)

        for index, pod_slice in enumerate(slices):
            if len(pod_slice) > 1:
                # Never expected: slice invariant broken; keep the oldest
                # (ref: common/pod.go logs "more than one pod").
                log.warning("more than one pod found at index %d; deleting extras", index)
                for extra in sorted(pod_slice, key=lambda p: p.metadata.creation_timestamp)[1:]:
                    delete(extra)
                pod_slice = [min(pod_slice, key=lambda p: p.metadata.creation_timestamp)]

            if index >= num_replicas:
                # Scale down: out-of-range index (ref: pkg/.../pod.go:93-123).
                for pod in pod_slice:
                    delete(pod)
                continue

            if not pod_slice:
                self.create_new_pod(job, rtype, rspec, index, replicas)
                continue

            pod = pod_slice[0]
            exit_code = get_container_exit_code(pod)
            if pod.status.phase == PodPhase.FAILED and exit_code != 0:
                from .exit_codes import UNKNOWN_EXIT_CODE

                if exit_code != UNKNOWN_EXIT_CODE:
                    self.cluster.record_event(
                        Event(
                            object_kind=job.kind,
                            object_name=job.metadata.name,
                            namespace=job.metadata.namespace,
                            event_type="Normal",
                            reason="ExitedWithCode",
                            message=f"Pod: {pod.metadata.namespace}.{pod.metadata.name} exited with code {exit_code}",
                        )
                    )

            if (
                pod.status.phase == PodPhase.FAILED
                and pod.status.reason == GANG_PREEMPTED_REASON
            ):
                # The operator itself evicted this gang to admit a
                # higher-priority one.  The job requeues REGARDLESS of
                # restartPolicy — failing it would convert a scheduling
                # decision into a workload failure — and reads Preempted,
                # not Restarting: the condition is the documented signal
                # that the drain was a policy action, retracted
                # (RunningAfterPreemption) once the gang runs again.
                log.info("requeueing pod %s after gang preemption", pod.metadata.name)
                delete(pod)
                restarted = True
                conditions.update_job_conditions(
                    job.status,
                    conditions.JobConditionType.PREEMPTED,
                    "GangPreempted",
                    f"TPUJob {job.metadata.name} was preempted for a "
                    "higher-priority gang; it requeues at its own priority",
                )
                metrics.restarted_pods.labels().inc()
                if rspec.tpu is not None and rspec.tpu.topology:
                    gang_restart = True
                update_job_replica_statuses(job.status, rtype, pod)
                continue

            if (
                rspec.restart_policy == RestartPolicy.EXIT_CODE
                and pod.status.phase == PodPhase.FAILED
                and self.plugin.pod_failed_is_retryable(job, rspec, pod, exit_code)
            ):
                # Retryable failure: delete; recreated next sync by slice diff.
                # Also surfaces the JobRestarting condition — the TF-specific
                # addition over common (ref: pkg/.../pod.go:135-154).
                log.info("restarting pod %s (exit code %d)", pod.metadata.name, exit_code)
                delete(pod)
                restarted = True
                conditions.update_job_conditions(
                    job.status,
                    conditions.JobConditionType.RESTARTING,
                    "JobRestarting",
                    f"TPUJob {job.metadata.name} is restarting because {rtype.value} replica {index} exited with retryable code {exit_code}",
                )
                metrics.jobs_restarted.labels().inc()
                metrics.restarted_pods.labels().inc()
                if rspec.tpu is not None and rspec.tpu.topology:
                    gang_restart = True

            update_job_replica_statuses(job.status, rtype, pod)

        if gang_restart:
            # TPU gang restart (no reference analogue — SURVEY.md §7 "hard
            # parts"): one dead host leaves the slice's ICI ring broken, so
            # surviving hosts of this replica group are restarted with it.
            for pod in pods:
                if pod.metadata.name in deleted_names:
                    continue
                if pod.status.phase in (PodPhase.RUNNING, PodPhase.PENDING):
                    log.info("gang restart: deleting sibling pod %s", pod.metadata.name)
                    delete(pod)
                    metrics.restarted_pods.labels().inc()
                    if pod.status.phase == PodPhase.RUNNING:
                        # A deleted sibling is not active: leaving it counted
                        # would let the status engine set Running this pass,
                        # whose mutual-exclusion filter erases the Restarting
                        # condition just recorded (ref: status.go:168-180
                        # Running<->Restarting exclusion).
                        rs = job.status.replica_statuses.get(rtype.value)
                        if rs is not None and rs.active > 0:
                            rs.active -= 1
        return restarted

    def create_new_pod(
        self,
        job: TPUJob,
        rtype: ReplicaType,
        rspec: ReplicaSpec,
        index: int,
        replicas: Dict[ReplicaType, ReplicaSpec],
    ) -> None:
        """(ref: createNewPod, pkg/.../pod.go:163-247)"""
        import copy as _copy

        job_key = job.key()
        self.expectations.raise_expectations(
            expectation_key(job_key, rtype.value, "pods"), adds=1, dels=0
        )

        labels = gen_labels(job.metadata.name)
        labels[constants.LABEL_REPLICA_TYPE] = rtype.value.lower()
        labels[constants.LABEL_REPLICA_INDEX] = str(index)
        if self.plugin.is_master_role(replicas, rtype, index):
            labels[constants.LABEL_JOB_ROLE] = constants.JOB_ROLE_MASTER

        template = _copy.deepcopy(rspec.template)
        template.metadata.labels.update(labels)

        pod = Pod(
            metadata=ObjectMeta(
                name=gen_general_name(job.metadata.name, rtype.value, index),
                namespace=job.metadata.namespace,
                labels=dict(template.metadata.labels),
                annotations=dict(template.metadata.annotations),
            ),
            spec=template,
        )

        self.plugin.set_cluster_spec(job, pod, rtype, index)
        _set_restart_policy(pod, rspec)

        if self.config.enable_gang_scheduling:
            # (ref: pod.go:472-488 — scheduler name + group annotation; a
            # user-specified scheduler is warned about, never overridden).
            # The pdb mechanism keeps the default scheduler: protection comes
            # from the budget, not from admission.
            gang_name = (
                constants.VOLCANO_SCHEDULER_NAME
                if self.config.gang_mechanism == "volcano"
                else self.config.gang_scheduler_name
            )
            if self.config.gang_mechanism != "pdb":
                if pod.spec.scheduler_name and pod.spec.scheduler_name != gang_name:
                    self.cluster.record_event(Event(
                        object_kind=job.kind,
                        object_name=job.metadata.name,
                        namespace=job.metadata.namespace,
                        event_type="Warning",
                        reason="PodTemplateSchedulerName",
                        message=("Another scheduler is specified when "
                                 "gang-scheduling is enabled and it will "
                                 "not be overwritten"),
                    ))
                elif not pod.spec.scheduler_name:
                    pod.spec.scheduler_name = gang_name
            group_annotation = (
                constants.VOLCANO_GROUP_ANNOTATION
                if self.config.gang_mechanism == "volcano"
                else constants.GANG_GROUP_ANNOTATION
            )
            pod.metadata.annotations[group_annotation] = job.metadata.name
            if job.spec.scheduling is not None:
                # Policy knobs ride to the gang scheduler on annotations so
                # admission never needs a TPUJob read (the scheduler watches
                # pods, not jobs).  setdefault keeps a hand-stamped template
                # authoritative, matching the slice-shape annotations below.
                sched = job.spec.scheduling
                pod.metadata.annotations.setdefault(
                    constants.ANNOTATION_PRIORITY_CLASS, sched.priority_class
                )
                pod.metadata.annotations.setdefault(
                    constants.ANNOTATION_TENANT, sched.tenant
                )
                pod.metadata.annotations.setdefault(
                    constants.ANNOTATION_PREEMPTIBLE,
                    "true" if sched.preemptible else "false",
                )
        if rspec.tpu is not None and rspec.tpu.topology:
            # Slice shape for the scheduler's slice-shaped admission
            # (runtime/slices.py); slice id/host written back at admission.
            pod.metadata.annotations.setdefault(
                constants.ANNOTATION_ACCELERATOR, rspec.tpu.accelerator
            )
            pod.metadata.annotations.setdefault(
                constants.ANNOTATION_SLICE_TOPOLOGY, rspec.tpu.topology
            )

        try:
            self.pod_control.create_pod(pod, job)
        except AlreadyExists:
            # Benign: the pod exists server-side but this sync's view was
            # stale — possible since reads come from the informer cache and
            # enable_dynamic_worker bypasses the expectations gate.  The
            # watch event will land and the next sync sees the pod; failing
            # the sync here would turn the race into a backoff/quarantine
            # spiral on a healthy job.
            self.expectations.creation_observed(expectation_key(job_key, rtype.value, "pods"))
            return
        except Exception:
            self.expectations.creation_observed(expectation_key(job_key, rtype.value, "pods"))
            raise
        metrics.created_pods.labels().inc()
        self.plugin.on_pod_created(job, rtype)

    def _delete_pod(self, job: TPUJob, rtype: ReplicaType, pod: Pod) -> None:
        self.expectations.raise_expectations(
            expectation_key(job.key(), rtype.value, "pods"), adds=0, dels=1
        )
        try:
            self.pod_control.delete_pod(pod.metadata.namespace, pod.metadata.name, job)
        except Exception:
            self.expectations.deletion_observed(expectation_key(job.key(), rtype.value, "pods"))
            raise
        metrics.deleted_pods.labels().inc()

    # ------------------------------------------------------------------
    # services (ref: common/service.go:206-339)

    def reconcile_services(
        self, job: TPUJob, all_services: List[Service], rtype: ReplicaType, rspec: ReplicaSpec
    ) -> None:
        services = filter_for_replica_type(all_services, rtype)
        if rspec.elastic is not None:
            num_replicas = effective_replicas(job, rtype)
        else:
            num_replicas = int(rspec.replicas or 0)
        slices = get_service_slices(services, num_replicas)

        for index, svc_slice in enumerate(slices):
            if index >= num_replicas:
                for svc in svc_slice:
                    self._delete_service(job, rtype, svc)
                continue
            if not svc_slice:
                self.create_new_service(job, rtype, rspec, index)

    def create_new_service(
        self, job: TPUJob, rtype: ReplicaType, rspec: ReplicaSpec, index: int
    ) -> None:
        """Headless service for one replica (ref: CreateNewService,
        common/service.go:277-339)."""
        self.expectations.raise_expectations(
            expectation_key(job.key(), rtype.value, "services"), adds=1, dels=0
        )
        labels = gen_labels(job.metadata.name)
        labels[constants.LABEL_REPLICA_TYPE] = rtype.value.lower()
        labels[constants.LABEL_REPLICA_INDEX] = str(index)
        port = get_port_from_job(job.spec, rtype)
        svc = Service(
            metadata=ObjectMeta(
                name=gen_general_name(job.metadata.name, rtype.value, index),
                namespace=job.metadata.namespace,
                labels=dict(labels),
            ),
            selector=dict(labels),
            ports=[ServicePort(name=constants.DEFAULT_PORT_NAME, port=port)],
            cluster_ip="None",
        )
        try:
            self.service_control.create_service(svc, job)
        except AlreadyExists:
            # Same stale-view race as create_new_pod: existing == created.
            self.expectations.creation_observed(
                expectation_key(job.key(), rtype.value, "services")
            )
            return
        except Exception:
            self.expectations.creation_observed(
                expectation_key(job.key(), rtype.value, "services")
            )
            raise
        metrics.created_services.labels().inc()

    def _delete_service(self, job: TPUJob, rtype: ReplicaType, svc: Service) -> None:
        self.expectations.raise_expectations(
            expectation_key(job.key(), rtype.value, "services"), adds=0, dels=1
        )
        try:
            self.service_control.delete_service(svc.metadata.namespace, svc.metadata.name, job)
        except Exception:
            self.expectations.deletion_observed(
                expectation_key(job.key(), rtype.value, "services")
            )
            raise
        metrics.deleted_services.labels().inc()

    # ------------------------------------------------------------------
    # terminal cleanup (ref: DeletePodsAndServices, common/job.go:19-42;
    # CleanupJob TTL, job.go:307-330)

    def delete_pods_and_services(self, job: TPUJob, pods: List[Pod]) -> None:
        policy = job.spec.run_policy.clean_pod_policy or CleanPodPolicy.RUNNING
        if policy == CleanPodPolicy.NONE:
            return
        for pod in pods:
            if policy == CleanPodPolicy.RUNNING and pod.status.phase not in (
                PodPhase.RUNNING,
                PodPhase.PENDING,
            ):
                continue
            rtype_raw = pod.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
            rtype = _replica_type_from_label(rtype_raw)
            if rtype is not None:
                self._delete_pod(job, rtype, pod)
            else:
                self.pod_control.delete_pod(pod.metadata.namespace, pod.metadata.name, job)
        # Services always go with the job's pods (ref: job.go:33-40 deletes
        # services regardless of policy once pods are handled).
        for svc in self.get_services_for_job(job):
            rtype = _replica_type_from_label(
                svc.metadata.labels.get(constants.LABEL_REPLICA_TYPE, "")
            )
            if rtype is not None:
                self._delete_service(job, rtype, svc)
            else:
                self.service_control.delete_service(svc.metadata.namespace, svc.metadata.name, job)

    def cleanup_job(self, job: TPUJob) -> Optional[float]:
        """TTLSecondsAfterFinished: delete the job once expired; returns the
        remaining delay if not yet due (ref: CleanupJob, job.go:307-330)."""
        ttl = job.spec.run_policy.ttl_seconds_after_finished
        if ttl is None:
            return None
        finish_time = job.status.completion_time or clock.now()
        expires_at = finish_time + ttl
        remaining = expires_at - clock.now()
        if remaining <= 0:
            try:
                self.cluster.delete_job(job.metadata.namespace, job.metadata.name)
                metrics.jobs_deleted.labels().inc()
            except NotFound:
                pass
            return None
        return remaining

    # ------------------------------------------------------------------
    # gang scheduling (ref: SyncPodGroup/DeletePodGroup,
    # common/job_controller.go:211-239,280-298)

    def sync_podgroup(self, job: TPUJob) -> PodGroup:
        from ..api.defaults import total_replicas

        sp = job.spec.run_policy.scheduling_policy
        min_member = (
            sp.min_available
            if sp is not None and sp.min_available is not None
            else (
                effective_total_replicas(job)
                if is_elastic(job)
                else total_replicas(job)
            )
        )
        try:
            pg = self.cluster.get_podgroup(job.metadata.namespace, job.metadata.name)
            if pg.min_member != min_member:
                # Elastic resize changed the gang size this pass: the
                # admission gate must see the new width before the
                # recreated pods' ADDED events reach the scheduler, or
                # admission waits a full retry sweep.
                pg.min_member = min_member
                update = getattr(self.cluster, "update_podgroup", None)
                if update is not None:
                    pg = update(pg)
            return pg
        except NotFound:
            pg = PodGroup(
                metadata=ObjectMeta(
                    name=job.metadata.name,
                    namespace=job.metadata.namespace,
                    owner_kind=job.kind,
                    owner_name=job.metadata.name,
                    owner_uid=job.metadata.uid,
                ),
                min_member=min_member,
                queue=sp.queue if sp is not None else "",
            )
            created = self.cluster.create_podgroup(pg)
            metrics.created_podgroups.labels().inc()
            return created

    def delete_podgroup(self, job: TPUJob) -> None:
        try:
            self.cluster.delete_podgroup(job.metadata.namespace, job.metadata.name)
            metrics.deleted_podgroups.labels().inc()
        except NotFound:
            pass

    def sync_pdb(self, job: TPUJob):
        """(ref: SyncPdb, common/job_controller.go:242-276)"""
        from ..api.core import PodDisruptionBudget
        from ..api.defaults import total_replicas

        sp = job.spec.run_policy.scheduling_policy
        min_available = (
            sp.min_available
            if sp is not None and sp.min_available is not None
            else (
                effective_total_replicas(job)
                if is_elastic(job)
                else total_replicas(job)
            )
        )
        try:
            pdb = self.cluster.get_pdb(job.metadata.namespace, job.metadata.name)
            if pdb.min_available != min_available:
                # Elastic scale changed the gang size: refresh the budget so
                # voluntary evictions are judged against the live replica count.
                pdb.min_available = min_available
                pdb = self.cluster.update_pdb(pdb)
            return pdb
        except NotFound:
            pdb = PodDisruptionBudget(
                metadata=ObjectMeta(
                    name=job.metadata.name,
                    namespace=job.metadata.namespace,
                    owner_kind=job.kind,
                    owner_name=job.metadata.name,
                    owner_uid=job.metadata.uid,
                ),
                min_available=min_available,
                selector=gen_labels(job.metadata.name),
            )
            created = self.cluster.create_pdb(pdb)
            metrics.created_pdbs.labels().inc()
            return created

    def delete_pdb(self, job: TPUJob) -> None:
        """(ref: DeletePdb, common/job_controller.go:299-316)"""
        try:
            self.cluster.delete_pdb(job.metadata.namespace, job.metadata.name)
            metrics.deleted_pdbs.labels().inc()
        except NotFound:
            pass

    def sync_gang(self, job: TPUJob) -> None:
        if self.config.gang_mechanism == "pdb":
            self.sync_pdb(job)
        else:
            self.sync_podgroup(job)

    def delete_gang(self, job: TPUJob) -> None:
        if self.config.gang_mechanism == "pdb":
            self.delete_pdb(job)
        else:
            self.delete_podgroup(job)

    # ------------------------------------------------------------------
    # job-level limits

    def past_active_deadline(self, job: TPUJob) -> bool:
        """(ref: PastActiveDeadline, common/job.go:255-264)"""
        deadline = job.spec.run_policy.active_deadline_seconds
        if deadline is None or job.status.start_time is None:
            return False
        return clock.now() - job.status.start_time >= deadline

    def past_backoff_limit(self, job: TPUJob, pods: List[Pod]) -> bool:
        """Sum container restart counts of Running pods over restartable
        replicas; limit 0 means any restart fails the job
        (ref: PastBackoffLimit, common/job.go:268-305).

        Preemption exemption (ISSUE: elastic jobs): restarts the fabric
        caused — a pod the gang scheduler failed as SlicePreempted, or a
        container whose last exit code is in PREEMPTION_EXIT_CODES — do not
        count toward the limit."""
        from .exit_codes import is_preemption_exit_code

        limit = job.spec.run_policy.backoff_limit
        if limit is None:
            return False
        restarts = 0
        for rtype, rspec in job.spec.replica_specs.items():
            if rspec.restart_policy not in (RestartPolicy.ALWAYS, RestartPolicy.ON_FAILURE):
                # Only in-place kubelet restarts count toward backoff
                # (ref: job.go:275-278).
                continue
            for pod in filter_for_replica_type(pods, rtype):
                if pod.status.phase != PodPhase.RUNNING:
                    continue  # (ref: job.go:287-289)
                if pod.status.reason in PREEMPTION_REASONS:
                    # Preemption — the fabric's (SlicePreempted) or the
                    # scheduler's own (GangPreempted) — is not the
                    # workload's fault: a job riding out preemptions must
                    # not share a backoff budget with a crash-looping one.
                    continue
                for cs in pod.status.container_statuses:
                    if cs.exit_code is not None and is_preemption_exit_code(
                        cs.exit_code
                    ):
                        # Approximation: PodStatus keeps only the LAST
                        # terminated code, so a preemption code exempts the
                        # whole counter for this container — per-restart
                        # attribution would need history the substrate
                        # doesn't retain.
                        continue
                    restarts += cs.restart_count
        if limit == 0:
            return restarts > 0
        return restarts >= limit

    # ------------------------------------------------------------------

    def _write_status_if_changed(self, job: TPUJob, old_status_snapshot) -> bool:
        """DeepEqual status-write guard (ref: job.go:248-250, status.go:207-225),
        now served by the coalescing writer — which also merges multi-
        transition passes into one PUT and suppresses stale-informer-read
        echoes of our own last write.  Returns True when a PUT went out."""
        return self.status_writer.write_if_changed(job, old_status_snapshot)


def _set_restart_policy(pod: Pod, rspec: ReplicaSpec) -> None:
    """ExitCode policy maps to substrate 'Never' — the controller owns the
    restart decision (ref: setRestartPolicy, pkg/.../pod.go:310-317)."""
    if rspec.restart_policy == RestartPolicy.EXIT_CODE:
        pod.spec.restart_policy = "Never"
    else:
        pod.spec.restart_policy = (rspec.restart_policy or RestartPolicy.NEVER).value


def _replica_type_from_label(raw: str) -> Optional[ReplicaType]:
    for rt in ReplicaType:
        if rt.value.lower() == raw.lower():
            return rt
    return None


# Canonical impl moved to runtime/statuswriter.py (the coalescing writer
# compares the same snapshots); kept importable under the old name.
_snapshot_status = snapshot_status
